"""Scaler / watcher / auto-scaler against the fake cluster."""

import time

import pytest

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.auto_scaler import (
    AllreduceAutoScaler,
    LocalResourceOptimizer,
)
from dlrover_tpu.master.job_manager import JobManager, ScalePlan
from dlrover_tpu.master.scaler import (
    FakeClusterClient,
    PodEventWatcher,
    TPUPodScaler,
)
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.scheduler import get_platform


def _node(i, chips=4, tpu="v5p"):
    return Node(
        type=NodeType.WORKER,
        id=i,
        rank=i,
        status=NodeStatus.PENDING,
        config_resource=NodeResource(
            cpu=8, memory_mb=16384, chips=chips, tpu_type=tpu
        ),
    )


def test_pod_scaler_creates_pods_and_services():
    client = FakeClusterClient()
    scaler = TPUPodScaler("job1", client)
    plan = ScalePlan()
    plan.launch_nodes = [_node(0), _node(1)]
    scaler.scale(plan)
    pods = client.list_pods("job1")
    assert len(pods) == 2
    assert pods[0]["tpu_accelerator"] == "v5p"
    assert pods[0]["tpu_chips"] == 4
    assert "job1-worker-0" in client.services


def test_pod_scaler_removes_pods():
    client = FakeClusterClient()
    scaler = TPUPodScaler("job1", client)
    plan = ScalePlan()
    plan.launch_nodes = [_node(0)]
    scaler.scale(plan)
    plan2 = ScalePlan()
    plan2.remove_nodes = [_node(0)]
    scaler.scale(plan2)
    assert client.list_pods("job1") == []


def test_pod_scaler_retries_transient_create_failure():
    client = FakeClusterClient()
    client.create_errors = 2
    scaler = TPUPodScaler("job1", client, retry_interval=0.01)
    scaler.start()
    plan = ScalePlan()
    plan.launch_nodes = [_node(0)]
    scaler.scale(plan)
    deadline = time.time() + 5
    while time.time() < deadline and not client.list_pods("job1"):
        time.sleep(0.02)
    scaler.stop()
    assert len(client.list_pods("job1")) == 1


def test_watcher_relaunches_on_pod_failure():
    client = FakeClusterClient()
    scaler = TPUPodScaler("job1", client)
    jm = JobManager(scaler=scaler)
    watcher = PodEventWatcher("job1", client, jm)
    node = jm.register_node(node_id=0)

    plan = ScalePlan()
    plan.launch_nodes = [_node(0)]
    scaler.scale(plan)
    client.fail_pod("job1-worker-0", reason="Error")
    # drain events synchronously; the fake cluster starts the
    # replacement pod instantly, so the full cycle lands on RUNNING
    while not client.events.empty():
        watcher.process_event(client.events.get())
    assert jm.get_node(0).status == NodeStatus.RUNNING
    # the scaler was asked to realize the replacement
    assert any(
        p.launch_nodes for p in scaler.executed_plans[1:]
    )
    assert "job1-worker-0" in {
        p["name"] for p in client.list_pods("job1")
    }


def test_watcher_preemption_classified():
    client = FakeClusterClient()
    scaler = TPUPodScaler("job1", client)
    jm = JobManager(scaler=scaler)
    watcher = PodEventWatcher("job1", client, jm)
    jm.register_node(node_id=0)
    plan = ScalePlan()
    plan.launch_nodes = [_node(0)]
    scaler.scale(plan)
    client.preempt_pod("job1-worker-0")
    while not client.events.empty():
        watcher.process_event(client.events.get())
    # preempted nodes relaunch; fake cluster restarts them instantly
    assert jm.get_node(0).status == NodeStatus.RUNNING
    assert any(p.launch_nodes for p in scaler.executed_plans[1:])


def test_auto_scaler_replaces_missing_workers():
    client = FakeClusterClient()
    scaler = TPUPodScaler("job1", client)
    jm = JobManager(scaler=scaler)
    for i in range(2):
        jm.register_node(node_id=i)
    auto = AllreduceAutoScaler(
        jm, SpeedMonitor(), target_workers=4, interval=999
    )
    plan = auto.adjust_once()
    assert plan is not None
    assert len(plan.launch_nodes) == 2
    # adopted into the job manager as pending
    assert jm.get_node(2).status == NodeStatus.PENDING
    # idempotent: pending nodes count toward the target
    assert auto.adjust_once() is None


def test_auto_scaler_slice_alignment():
    opt = LocalResourceOptimizer(hosts_per_slice=4)
    assert opt.target_worker_count(7, SpeedMonitor()) == 4
    assert opt.target_worker_count(8, SpeedMonitor()) == 8
    assert opt.target_worker_count(2, SpeedMonitor()) == 4


def test_auto_scaler_grows_oom_memory():
    client = FakeClusterClient()
    jm = JobManager(scaler=TPUPodScaler("job1", client))
    jm.register_node(node_id=0)
    action = jm.handle_failure_report(
        0, "CUDA out of memory", "process_error", 0
    )
    assert action == "relaunch_node"
    node = jm.get_node(0)
    node.config_resource = NodeResource(memory_mb=8192)
    auto = AllreduceAutoScaler(
        jm, SpeedMonitor(), target_workers=1, interval=999
    )
    auto.grow_oom_resources()
    assert jm.get_node(0).config_resource.memory_mb == 12288


def test_platform_factory_local_and_gated():
    platform = get_platform("local", "jobX")
    assert platform.client is not None
    plan = ScalePlan()
    plan.launch_nodes = [_node(0)]
    platform.scaler.scale(plan)
    assert platform.client.list_pods("jobX")
    with pytest.raises(RuntimeError, match="kubernetes"):
        get_platform("gke", "jobX")
    with pytest.raises(RuntimeError, match="ray"):
        get_platform("ray", "jobX")


def test_node_gone_does_not_refail_pending_replacement():
    """The pod-Deleted event that follows every relaunch (the scaler
    removes the old pod) must not burn a second relaunch count."""
    client = FakeClusterClient()
    scaler = TPUPodScaler("job1", client)
    jm = JobManager(scaler=scaler)
    jm.register_node(node_id=0)
    jm.handle_failure_report(0, "CUDA out of memory", "process_error", 0)
    node = jm.get_node(0)
    assert node.status == NodeStatus.PENDING
    count_before = node.relaunch_count
    jm.handle_node_gone(0, reason="Deleted")
    assert jm.get_node(0).relaunch_count == count_before
    assert jm.get_node(0).status == NodeStatus.PENDING


def test_auto_scaler_fills_deficient_slice():
    """Multi-slice: replacements land in the slice that lost hosts so
    the DCN (outer) mesh axis stays balanced."""
    client = FakeClusterClient()
    scaler = TPUPodScaler("job1", client)
    jm = JobManager(scaler=scaler)
    # slice 0 has 2 alive hosts, slice 1 only 1 (one died)
    for i, s in enumerate([0, 0, 1]):
        node = jm.register_node(node_id=i)
        node.config_resource = NodeResource(
            cpu=8, chips=4, tpu_type="v5p", slice_id=s
        )
    auto = AllreduceAutoScaler(
        jm, SpeedMonitor(), target_workers=4, interval=999,
        num_slices=2,
    )
    plan = auto.adjust_once()
    assert plan is not None and len(plan.launch_nodes) == 1
    assert plan.launch_nodes[0].config_resource.slice_id == 1
    # the pod spec carries the slice pin
    pods = {p["name"]: p for p in client.list_pods("job1")}
    new_pod = pods[f"job1-worker-{plan.launch_nodes[0].id}"]
    assert new_pod["tpu_slice"] == 1


def test_auto_scaler_pending_counts_once_toward_target():
    """A PENDING replacement must not be double-counted (ALIVE already
    includes PENDING) — the job would otherwise converge one short."""
    client = FakeClusterClient()
    jm = JobManager(scaler=TPUPodScaler("job1", client))
    n0 = jm.register_node(node_id=0)
    assert n0.is_alive()
    auto = AllreduceAutoScaler(
        jm, SpeedMonitor(), target_workers=3, interval=999
    )
    plan = auto.adjust_once()
    assert len(plan.launch_nodes) == 2  # 1 alive -> need 2 more
    # all three now count; no further launches
    assert auto.adjust_once() is None


def test_duplicate_scaleplan_is_noop_on_fake_cluster():
    """A replayed/duplicate ScalePlan (retried scale RPC, engine
    re-fire after a warm restart) applied twice must be a no-op: one
    pod, one service, ONE ADDED event — a duplicate ADDED would
    double-register the node with the job manager."""
    client = FakeClusterClient()
    scaler = TPUPodScaler("job1", client)
    plan = ScalePlan()
    plan.launch_nodes = [_node(0)]
    scaler.scale(plan)
    pods_once = {k: dict(v) for k, v in client.pods.items()}
    events_once = client.events.qsize()
    scaler.scale(plan)  # the replay
    assert client.pods == pods_once
    assert client.events.qsize() == events_once
    assert len(client.services) == 1
    # Remove-side replay: deleting an already-deleted pod no-ops too.
    rm = ScalePlan()
    rm.remove_nodes = [_node(0)]
    scaler.scale(rm)
    after_delete = client.events.qsize()
    scaler.scale(rm)
    assert client.events.qsize() == after_delete
    assert not client.pods


def test_adjust_once_idempotent_under_duplicate_plan():
    """adjust_once -> replay its plan through the scaler -> another
    adjust_once: the job manager's node table and the fake cluster
    must be exactly as after the first pass."""
    client = FakeClusterClient()
    scaler = TPUPodScaler("job1", client)
    jm = JobManager(scaler=scaler)
    jm.register_node(node_id=0)
    auto = AllreduceAutoScaler(
        jm, SpeedMonitor(), target_workers=2, interval=999
    )
    plan = auto.adjust_once()
    assert plan is not None and len(plan.launch_nodes) == 1
    nodes_once = {n.id: n.status for n in jm.list_nodes()}
    events_once = client.events.qsize()
    scaler.scale(plan)  # duplicate delivery of the same plan
    assert auto.adjust_once() is None
    assert {n.id: n.status for n in jm.list_nodes()} == nodes_once
    assert client.events.qsize() == events_once


def test_auto_scaler_replaces_cordoned_worker():
    """A cordoned host is deliberately benched by the remediation
    engine: it must NOT count toward the target (the auto-scaler
    launches a stand-in), and the PENDING stand-in keeps the pass
    idempotent."""
    client = FakeClusterClient()
    jm = JobManager(scaler=TPUPodScaler("job1", client))
    for i in range(2):
        jm.register_node(node_id=i)
    auto = AllreduceAutoScaler(
        jm, SpeedMonitor(), target_workers=2, interval=999
    )
    assert auto.adjust_once() is None  # fleet at target
    assert jm.cordon_node(1, reason="throughput_degradation")
    plan = auto.adjust_once()
    assert plan is not None and len(plan.launch_nodes) == 1
    assert auto.adjust_once() is None  # replacement counts now
    # Rollback path: un-cordon -> the fleet is one OVER target, which
    # the replace-only scaler leaves alone (no thrash).
    assert jm.uncordon_node(1)
    assert auto.adjust_once() is None


class _FakeRayActorHandle:
    def __init__(self, name, spec):
        self.name = name
        self.spec = spec


class _FakeRay:
    """Just enough of the ray API for RayClusterClient."""

    def __init__(self):
        self.actors = {}
        self.killed = []

    def is_initialized(self):
        return True

    def init(self, **kw):
        pass

    def remote(self, cls):
        fake = self

        class _Remote:
            def options(self, **options):
                class _Launcher:
                    def remote(self, spec):
                        h = _FakeRayActorHandle(
                            options["name"], spec
                        )
                        fake.actors[options["name"]] = h
                        return h

                return _Launcher()

        return _Remote()

    def get_actor(self, name, namespace=None):
        if name not in self.actors:
            raise ValueError(name)
        return self.actors[name]

    def kill(self, handle, no_restart=False):
        self.killed.append(handle.name)
        self.actors.pop(handle.name, None)


def _ray_client(monkeypatch):
    import sys
    import types

    fake = _FakeRay()
    ray_mod = types.ModuleType("ray")
    for attr in ("is_initialized", "init", "remote", "get_actor",
                 "kill"):
        setattr(ray_mod, attr, getattr(fake, attr))
    util = types.ModuleType("ray.util")
    util.list_named_actors = lambda all_namespaces=False: [
        {"name": n} for n in fake.actors
    ]
    ray_mod.util = util
    monkeypatch.setitem(sys.modules, "ray", ray_mod)
    monkeypatch.setitem(sys.modules, "ray.util", util)
    from dlrover_tpu.scheduler.factory import RayClusterClient

    return RayClusterClient(), fake


def test_ray_client_pods_as_named_actors(monkeypatch):
    """Ray platform (ref scheduler/ray.py RayClient): pods become
    named detached actors; delete kills; list reports phases."""
    client, fake = _ray_client(monkeypatch)
    scaler = TPUPodScaler("rj", client)
    plan = ScalePlan()
    plan.launch_nodes = [_node(0), _node(1)]
    scaler.scale(plan)
    assert set(fake.actors) == {"rj-worker-0", "rj-worker-1"}
    pods = client.list_pods("rj")
    assert {p["phase"] for p in pods} == {"Running"}

    plan2 = ScalePlan()
    plan2.remove_nodes = [_node(0)]
    scaler.scale(plan2)
    assert fake.killed == ["rj-worker-0"]
    phases = {p["name"]: p["phase"] for p in client.list_pods("rj")}
    assert "rj-worker-0" not in phases  # deleted on purpose
    assert phases["rj-worker-1"] == "Running"
    # a CRASHED actor (spec known, actor gone) reports Failed so the
    # watcher can relaunch it
    fake.actors.pop("rj-worker-1")
    phases = {p["name"]: p["phase"] for p in client.list_pods("rj")}
    assert phases["rj-worker-1"] == "Failed"


def test_ray_platform_factory(monkeypatch):
    _, fake = _ray_client(monkeypatch)
    from dlrover_tpu.scheduler import get_platform

    platform = get_platform("ray", "rj2")
    plan = ScalePlan()
    plan.launch_nodes = [_node(0)]
    platform.scaler.scale(plan)
    assert platform.client.list_pods("rj2")


def test_ray_listing_survives_client_restart(monkeypatch):
    """Detached actors outlive the master; a FRESH client must still
    list them (and not recreate the world)."""
    client, fake = _ray_client(monkeypatch)
    scaler = TPUPodScaler("rj3", client)
    plan = ScalePlan()
    plan.launch_nodes = [_node(0), _node(1)]
    scaler.scale(plan)
    from dlrover_tpu.scheduler.factory import RayClusterClient

    fresh = RayClusterClient()  # empty spec cache, same "cluster"
    pods = {p["name"]: p for p in fresh.list_pods("rj3")}
    assert set(pods) == {"rj3-worker-0", "rj3-worker-1"}
    assert pods["rj3-worker-0"]["node_id"] == 0


def test_ray_delete_of_dead_actor_clears_cache(monkeypatch):
    """Removing a node whose actor already crashed must not leave a
    phantom 'Failed' pod for the watcher to relaunch."""
    client, fake = _ray_client(monkeypatch)
    scaler = TPUPodScaler("rj4", client)
    plan = ScalePlan()
    plan.launch_nodes = [_node(0)]
    scaler.scale(plan)
    fake.actors.pop("rj4-worker-0")  # crash
    client.delete_pod("rj4-worker-0")  # deliberate removal
    assert client.list_pods("rj4") == []
