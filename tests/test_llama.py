"""Llama model family: RoPE, GQA, SwiGLU, sharded training.

Parity targets: the reference trains Llama-2 through HF modules +
atorch auto_accelerate (/root/reference/atorch/examples/llama2/
fsdp_llama2.py); here the model is native (models/llama.py) and the
same logical-axis rule table shards it.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.trainer.step import (
    make_sharded_init,
    make_train_step,
    shard_batch,
)


@pytest.fixture(scope="module")
def tiny():
    return llama.LlamaConfig.tiny()


@pytest.fixture(scope="module")
def tiny_params(tiny):
    return llama.init_params(jax.random.PRNGKey(0), tiny)


def test_forward_shape_and_finite(tiny, tiny_params):
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, tiny.block_size), 0, tiny.vocab_size
    )
    logits = llama.forward(tiny_params, tokens, tiny)
    assert logits.shape == (2, tiny.block_size, tiny.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_rope_preserves_norm(tiny):
    cos, sin = llama.rope_table(tiny, 16)
    x = jax.random.normal(
        jax.random.PRNGKey(0), (1, 16, 2, tiny.head_dim)
    )
    rot = llama.apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        jnp.linalg.norm(rot, axis=-1),
        jnp.linalg.norm(x, axis=-1),
        rtol=1e-5,
    )
    # position 0 is the identity rotation
    np.testing.assert_allclose(rot[:, 0], x[:, 0], atol=1e-6)


def test_rope_relative_shift_invariance(tiny):
    """Attention scores under RoPE depend only on relative offsets:
    rotating (q at p+s, k at p'+s) gives the same dot product."""
    d = tiny.head_dim
    cos, sin = llama.rope_table(tiny, 32)
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 1, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 1, d))
    qr = llama.apply_rope(q, cos, sin)[0, :, 0]
    kr = llama.apply_rope(k, cos, sin)[0, :, 0]
    # score(5, 3) computed at positions (5,3) vs the same vectors
    # rotated as if at (15, 13): equal because offset is equal.
    q2 = jnp.broadcast_to(q[0, 5, 0], (1, 32, 1, d))
    k2 = jnp.broadcast_to(k[0, 3, 0], (1, 32, 1, d))
    q2r = llama.apply_rope(q2, cos, sin)[0, :, 0]
    k2r = llama.apply_rope(k2, cos, sin)[0, :, 0]
    s_a = jnp.dot(q2r[15], k2r[13])
    s_b = jnp.dot(q2r[5], k2r[3])
    np.testing.assert_allclose(s_a, s_b, rtol=1e-4)
    # and sanity: the in-context score at (5,3) uses those vectors
    np.testing.assert_allclose(
        jnp.dot(qr[5], kr[3]), s_b, rtol=1e-4, atol=1e-5
    )


def test_gqa_matches_explicit_head_broadcast(tiny, tiny_params):
    """GQA forward == an MHA forward whose k/v weights are the kv
    weights tiled over each query group."""
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (2, tiny.block_size), 0, tiny.vocab_size
    )
    out_gqa = llama.forward(tiny_params, tokens, tiny)

    import dataclasses

    mha = dataclasses.replace(tiny, n_kv_head=tiny.n_head)
    D, Hkv, g = tiny.head_dim, tiny.n_kv_head, tiny.q_per_kv
    p2 = jax.tree.map(lambda x: x, tiny_params)

    def tile(w):  # [L, E, Hkv*D] -> [L, E, H*D] repeating per group
        L, E = w.shape[0], w.shape[1]
        w = w.reshape(L, E, Hkv, D)
        w = jnp.repeat(w, g, axis=2)
        return w.reshape(L, E, Hkv * g * D)

    p2["blocks"] = dict(p2["blocks"])
    p2["blocks"]["wk"] = tile(tiny_params["blocks"]["wk"])
    p2["blocks"]["wv"] = tile(tiny_params["blocks"]["wv"])
    out_mha = llama.forward(p2, tokens, mha)
    np.testing.assert_allclose(out_gqa, out_mha, atol=1e-4, rtol=1e-4)


def test_fused_loss_matches_plain(tiny, tiny_params):
    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (2, tiny.block_size), 0, tiny.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)
    plain = llama.loss_fn(tiny_params, tokens, targets, tiny)
    fused = llama.loss_fn_fused(
        tiny_params, tokens, targets, tiny, num_chunks=4
    )
    np.testing.assert_allclose(fused, plain, rtol=1e-5)
    fused_sl = llama.loss_fn_fused(
        tiny_params, tokens, targets, tiny, num_chunks=4, save_logits=True
    )
    np.testing.assert_allclose(fused_sl, plain, rtol=1e-5)


@pytest.mark.slow
def test_remat_policies_grad_parity(tiny, tiny_params):
    import dataclasses

    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (2, tiny.block_size), 0, tiny.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)
    base = jax.grad(
        lambda p: llama.loss_fn(p, tokens, targets, tiny)
    )(tiny_params)
    for policy in (True, "attention", "dots"):
        cfg = dataclasses.replace(tiny, remat=policy)
        g = jax.grad(
            lambda p: llama.loss_fn(p, tokens, targets, cfg)
        )(tiny_params)
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(base)):
            np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-3)


def test_sharded_train_step_tp_fsdp(tiny):
    """Full sharded train step on the 8-device CPU mesh: fsdp=2 x
    tensor=2 x data=2, loss finite and decreasing over steps."""
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    optimizer = optax.adamw(1e-3)
    loss = functools.partial(llama.loss_fn, cfg=tiny)
    init, _ = make_sharded_init(
        mesh,
        functools.partial(llama.init_params, cfg=tiny),
        llama.param_logical_axes(tiny),
        optimizer,
    )
    params, opt_state = init(jax.random.PRNGKey(0))
    step = make_train_step(mesh, loss, optimizer)
    tokens = jax.random.randint(
        jax.random.PRNGKey(5), (8, tiny.block_size), 0, tiny.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)
    tokens, targets = shard_batch(mesh, tokens, targets)
    losses = []
    for _ in range(4):
        params, opt_state, metrics = step(
            params, opt_state, tokens, targets
        )
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


class TestLlamaMoE:
    """Mixtral-shaped family: Llama blocks with expert-routed MLPs."""

    @pytest.fixture(scope="class")
    def moe_cfg(self):
        return llama.LlamaConfig.moe_tiny()

    @pytest.fixture(scope="class")
    def moe_params(self, moe_cfg):
        return llama.init_params(jax.random.PRNGKey(0), moe_cfg)

    def test_forward_and_loss_finite(self, moe_cfg, moe_params):
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, moe_cfg.block_size), 0,
            moe_cfg.vocab_size,
        )
        logits = llama.forward(moe_params, tokens, moe_cfg)
        assert logits.shape == (
            2, moe_cfg.block_size, moe_cfg.vocab_size
        )
        assert bool(jnp.all(jnp.isfinite(logits)))
        targets = jnp.roll(tokens, -1, axis=1)
        loss = llama.loss_fn(moe_params, tokens, targets, moe_cfg)
        assert bool(jnp.isfinite(loss))
        # aux loss contributes: plain CE from logits differs from loss
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.mean(
            jnp.take_along_axis(logp, targets[..., None], axis=-1)
        )
        assert float(loss) > float(ce)

    def test_fused_matches_plain(self, moe_cfg, moe_params):
        tokens = jax.random.randint(
            jax.random.PRNGKey(2), (2, moe_cfg.block_size), 0,
            moe_cfg.vocab_size,
        )
        targets = jnp.roll(tokens, -1, axis=1)
        plain = llama.loss_fn(moe_params, tokens, targets, moe_cfg)
        fused = llama.loss_fn_fused(
            moe_params, tokens, targets, moe_cfg, num_chunks=4
        )
        np.testing.assert_allclose(fused, plain, rtol=1e-5)

    def test_expert_sharded_train_step(self, moe_cfg):
        """expert x data mesh: one sharded train step, loss decreasing."""
        mesh = build_mesh(MeshConfig(data=2, expert=4))
        optimizer = optax.adamw(1e-3)
        loss = functools.partial(llama.loss_fn, cfg=moe_cfg)
        init, _ = make_sharded_init(
            mesh,
            functools.partial(llama.init_params, cfg=moe_cfg),
            llama.param_logical_axes(moe_cfg),
            optimizer,
        )
        params, opt_state = init(jax.random.PRNGKey(0))
        step = make_train_step(mesh, loss, optimizer)
        tokens = jax.random.randint(
            jax.random.PRNGKey(3), (4, moe_cfg.block_size), 0,
            moe_cfg.vocab_size,
        )
        targets = jnp.roll(tokens, -1, axis=1)
        tokens, targets = shard_batch(mesh, tokens, targets)
        losses = []
        for _ in range(3):
            params, opt_state, m = step(
                params, opt_state, tokens, targets
            )
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_cached_decode_matches_forward(self, moe_cfg, moe_params):
        """Parity needs no capacity dropping: the training forward
        drops over batch*seq while decode sees one token at a time, so
        pin a capacity factor high enough that neither path drops."""
        import dataclasses

        from dlrover_tpu.models import generate

        cfg = dataclasses.replace(moe_cfg, moe_capacity_factor=8.0)
        tokens = jax.random.randint(
            jax.random.PRNGKey(4), (2, 12), 0, cfg.vocab_size
        )
        got = generate.decode_logits_sequential(moe_params, cfg, tokens)
        want = llama.forward(moe_params, tokens, cfg)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-3
        )

    def test_moe_flops_counts_active_experts_only(self, moe_cfg):
        got = llama.flops_per_token(moe_cfg)
        E, L, I = moe_cfg.n_embd, moe_cfg.n_layer, moe_cfg.intermediate
        kv = moe_cfg.n_kv_head * moe_cfg.head_dim
        # active SwiGLU experts (top_k of n_experts, 3 matmuls each)
        # + router, NOT all experts
        mlp = 3 * moe_cfg.moe_top_k * E * I + E * moe_cfg.n_experts
        want = 6.0 * (
            L * (2 * E * E + 2 * E * kv + mlp)
            + moe_cfg.vocab_size * E
        ) + 12 * L * moe_cfg.block_size * E
        assert got == want
        # sanity: all-experts accounting would be strictly larger
        all_experts = got + 6.0 * L * 3 * (
            moe_cfg.n_experts - moe_cfg.moe_top_k
        ) * E * I
        assert got < all_experts


def test_flops_per_token_matches_analytic(tiny):
    got = llama.flops_per_token(tiny)
    E, L, I = tiny.n_embd, tiny.n_layer, tiny.intermediate
    kv = tiny.n_kv_head * tiny.head_dim
    want = 6.0 * (
        L * (2 * E * E + 2 * E * kv + 3 * E * I)
        + tiny.vocab_size * E
    ) + 12 * L * tiny.block_size * E
    assert got == want


class TestSlidingWindow:
    """Mistral-shaped family: Llama backbone + sliding-window band
    (models/llama.py LlamaConfig.sliding_window, mistral_7b preset)."""

    def test_windowed_forward_matches_manual_band_mask(self):
        import dataclasses

        cfg = dataclasses.replace(
            llama.LlamaConfig.tiny(), sliding_window=24
        )
        params = llama.init_params(jax.random.PRNGKey(3), cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(4), (2, cfg.block_size), 0,
            cfg.vocab_size,
        )
        out = llama.forward(params, tokens, cfg)

        # Same params through an explicit band-masked attention.
        from dlrover_tpu.models.gpt import _default_attention

        manual = llama.forward(
            params, tokens, cfg,
            attn_fn=functools.partial(
                _default_attention, causal=True, window=24
            ),
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(manual), atol=1e-5, rtol=1e-5
        )
        # And the band must actually matter: full-causal differs.
        full = llama.forward(
            params, tokens, cfg,
            attn_fn=functools.partial(_default_attention, causal=True),
        )
        assert not np.allclose(np.asarray(out), np.asarray(full))

    def test_windowed_train_step_decreases_loss(self):
        import dataclasses

        cfg = dataclasses.replace(
            llama.LlamaConfig.tiny(), sliding_window=16
        )
        params = llama.init_params(jax.random.PRNGKey(5), cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(6), (4, cfg.block_size), 0,
            cfg.vocab_size,
        )
        targets = jnp.roll(tokens, -1, axis=1)
        opt = optax.adam(1e-2)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(
                lambda p: llama.loss_fn(p, tokens, targets, cfg)
            )(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        losses = []
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_mistral_7b_preset_shape(self):
        cfg = llama.LlamaConfig.mistral_7b()
        assert cfg.sliding_window == 4096
        assert cfg.n_kv_head == 8 and cfg.q_per_kv == 4
        assert cfg.block_size == 8192
