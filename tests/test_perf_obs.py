"""Performance observability: step phases, compile/MFU accounting,
the PROFILE action, the bench ledger gate, and the capture-tooling
satellites (TimeoutExpired bytes decoding, fail-closed job deadline).

Everything here is hermetic: fake clocks for phase attribution, the
8-device CPU mesh for the trainer paths, tmp-file ledgers, and an
in-process servicer for the PROFILE end-to-end flow.
"""

import ast
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from dlrover_tpu import obs
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.constants import EventAction
from dlrover_tpu.obs import profiling

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# Step-phase attribution
# ---------------------------------------------------------------------------


class TestPhaseAttribution:
    def _profiler(self, clock, **kw):
        kw.setdefault("poll_requests", False)
        return profiling.StepPhaseProfiler(clock=clock, **kw)

    def test_phases_partition_wall_time_exactly(self):
        clock = FakeClock(100.0)
        prof = self._profiler(clock)
        prof.end_step()  # anchor the step start at t=100
        prof.note_data_wait(0.2)
        prof.note_dispatch(0.05, compiled=False)
        clock.t = 101.0
        b = prof.end_step()
        assert b["data_wait"] == pytest.approx(0.2)
        assert b["dispatch"] == pytest.approx(0.05)
        assert b["compile"] == 0.0
        # residual = 1.0 - 0.25
        assert b["device_execute"] == pytest.approx(0.75)
        assert b["wall_s"] == pytest.approx(1.0)
        assert sum(b[p] for p in profiling.PHASES) == pytest.approx(
            b["wall_s"]
        )

    def test_first_step_start_backdated_to_cover_its_data_wait(self):
        """Before any end_step, the first note backdates the step
        start: the first step's wall covers its own data wait."""
        clock = FakeClock(100.0)
        prof = self._profiler(clock)
        prof.note_data_wait(0.2)  # started fetching at 99.8
        clock.t = 101.0
        b = prof.end_step()
        assert b["wall_s"] == pytest.approx(1.2)
        assert b["device_execute"] == pytest.approx(1.0)

    def test_compiled_dispatch_books_compile_phase(self):
        clock = FakeClock(0.0)
        prof = self._profiler(clock)
        prof.end_step()  # anchor the step start at t=0
        prof.note_data_wait(0.1)
        prof.note_dispatch(2.0, compiled=True)
        clock.t = 2.5
        b = prof.end_step()
        assert b["compile"] == pytest.approx(2.0)
        assert b["dispatch"] == 0.0
        assert b["device_execute"] == pytest.approx(0.4)

    def test_second_step_wall_measured_from_previous_end(self):
        clock = FakeClock(10.0)
        prof = self._profiler(clock)
        clock.t = 11.0
        prof.end_step()
        # no notes at all: the whole inter-end interval is residual
        clock.t = 13.5
        b = prof.end_step()
        assert b["wall_s"] == pytest.approx(2.5)
        assert b["device_execute"] == pytest.approx(2.5)

    def test_noted_overshoot_never_goes_negative(self):
        clock = FakeClock(0.0)
        prof = self._profiler(clock)
        prof.end_step()  # anchor at t=0
        # Scheduler jitter: notes sum past the measured wall.
        prof.note_data_wait(0.8)
        prof.note_dispatch(0.4)
        clock.t = 1.0
        b = prof.end_step()
        assert b["device_execute"] == 0.0
        assert all(b[p] >= 0 for p in profiling.PHASES)
        assert sum(b[p] for p in profiling.PHASES) == pytest.approx(
            b["wall_s"]
        )

    def test_phase_counters_accumulate(self):
        counter = obs.get_registry().get(
            "dlrover_step_phase_seconds_total"
        )
        before = counter.value(phase="data_wait")
        clock = FakeClock(0.0)
        prof = self._profiler(clock)
        prof.note_data_wait(0.25)
        clock.t = 0.5
        prof.end_step()
        assert counter.value(phase="data_wait") == pytest.approx(
            before + 0.25
        )

    def test_h2d_split_partitions_the_input_wait(self):
        """note_data_wait(host, h2d_seconds=...) splits the input
        wait into data_wait vs h2d_stage, backdates the step start by
        the SUM, and keeps the five-phase partition exact."""
        clock = FakeClock(100.0)
        prof = self._profiler(clock)
        prof.end_step()  # anchor at t=100
        prof.note_data_wait(0.2, h2d_seconds=0.1)
        prof.note_dispatch(0.05)
        clock.t = 101.0
        b = prof.end_step()
        assert b["data_wait"] == pytest.approx(0.2)
        assert b["h2d_stage"] == pytest.approx(0.1)
        assert b["device_execute"] == pytest.approx(0.65)
        assert sum(b[p] for p in profiling.PHASES) == pytest.approx(
            b["wall_s"]
        )

    def test_h2d_backdates_first_step_start_by_full_wait(self):
        clock = FakeClock(100.0)
        prof = self._profiler(clock)
        prof.note_data_wait(0.2, h2d_seconds=0.3)  # fetch began 99.5
        clock.t = 101.0
        b = prof.end_step()
        assert b["wall_s"] == pytest.approx(1.5)
        assert b["h2d_stage"] == pytest.approx(0.3)
        assert b["device_execute"] == pytest.approx(1.0)

    def test_h2d_counter_and_overshoot_clamp_cover_new_phase(self):
        counter = obs.get_registry().get(
            "dlrover_step_phase_seconds_total"
        )
        before = counter.value(phase="h2d_stage")
        clock = FakeClock(0.0)
        prof = self._profiler(clock)
        prof.note_data_wait(0.1, h2d_seconds=0.15)
        clock.t = 0.5
        prof.end_step()
        assert counter.value(phase="h2d_stage") == pytest.approx(
            before + 0.15
        )
        # overshoot clamp scales h2d_stage down with the others
        prof.end_step()  # re-anchor
        prof.note_data_wait(0.8, h2d_seconds=0.4)
        clock.t = 1.1
        b = prof.end_step()
        assert b["device_execute"] == 0.0
        assert sum(b[p] for p in profiling.PHASES) == pytest.approx(
            b["wall_s"]
        )
        assert b["h2d_stage"] < 0.4  # scaled, not dropped

    def test_step_phases_event_carries_h2d_field(self):
        from dlrover_tpu.obs import tracer as tracer_mod

        tracer = tracer_mod.configure_tracer()
        try:
            clock = FakeClock(0.0)
            prof = self._profiler(clock)
            prof.note_data_wait(0.02, h2d_seconds=0.01)
            clock.t = 0.1
            prof.end_step()
            rows = [
                e for e in tracer.events()
                if e["name"] == "trainer.step_phases"
            ]
            assert rows and rows[-1]["h2d_s"] == pytest.approx(0.01)
        finally:
            tracer_mod.disable_tracer()


# ---------------------------------------------------------------------------
# Compile tracking (real forced retrace) and MFU
# ---------------------------------------------------------------------------


class TestCompileTracker:
    def test_forced_retrace_increments_counters(self):
        import jax
        import jax.numpy as jnp

        jfn = jax.jit(lambda x: (x * x).sum())
        tracker = profiling.CompileTracker("perf_obs_fn", jfn=jfn)
        total = obs.get_registry().get("dlrover_compile_total")
        secs = obs.get_registry().get("dlrover_compile_seconds_total")
        base = total.value(fn="perf_obs_fn")
        base_s = secs.value(fn="perf_obs_fn")

        jfn(jnp.ones((4,)))
        assert tracker.observe_call(0.5) is True
        jfn(jnp.ones((4,)))
        assert tracker.observe_call(0.001) is False  # cache hit
        jfn(jnp.ones((8,)))  # new shape -> retrace
        assert tracker.observe_call(0.25) is True

        assert tracker.compiles == 2
        assert total.value(fn="perf_obs_fn") == base + 2
        assert secs.value(fn="perf_obs_fn") == pytest.approx(
            base_s + 0.75
        )

    def test_fallback_without_cache_api_counts_first_call_only(self):
        tracker = profiling.CompileTracker("perf_obs_nofn", jfn=object())
        assert tracker.observe_call(1.0) is True
        assert tracker.observe_call(1.0) is False
        assert tracker.compiles == 1


class TestMfu:
    def test_mfu_matches_hand_computed_value(self):
        """Pure-matmul FLOPs are known analytically (2*m*k*n); with an
        injected peak and step time the gauge must equal the
        hand-computed utilisation."""
        import jax
        import jax.numpy as jnp

        m = 16
        jfn = jax.jit(lambda a, b: a @ b)
        a = jnp.ones((m, m), jnp.float32)
        flops = profiling.step_flops(jfn, a, a)
        hand_flops = 2 * m * m * m
        assert flops == pytest.approx(hand_flops, rel=0.05)

        meter = profiling.MfuMeter(peak_flops=1e6)
        meter.set_flops(flops)
        mfu = meter.observe_step(1e-3)  # 8192 flops / (1e-3s * 1e6/s)
        assert mfu == pytest.approx(hand_flops / (1e-3 * 1e6), rel=0.05)
        gauge = obs.get_registry().get("dlrover_train_mfu")
        assert gauge.value() == pytest.approx(mfu)
        assert obs.get_registry().get(
            "dlrover_train_flops_per_step"
        ).value() == pytest.approx(flops)

    def test_elastic_trainer_mfu_agrees_with_hand_computation(self, monkeypatch):
        """End-to-end on the tiny test model: the trainer's live gauge
        must agree (within 5%) with flops/(mean step wall * peak)
        recomputed independently from its own measured quantities."""
        import jax.numpy as jnp
        import numpy as np
        import optax

        import jax

        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
        from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer

        # Tiny peak so the utilisation is O(1) instead of 1e-10.
        monkeypatch.setenv(profiling.PEAK_TFLOPS_ENV, "1e-9")  # 1e3 FLOP/s
        mesh = build_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
        loss = lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2)  # noqa: E731
        trainer = ElasticTrainer(
            mesh, loss, optax.sgd(0.01),
            global_batch_size=4, micro_batch_size=4,
        )
        params = {"w": jnp.ones((8, 8))}
        opt_state = trainer.optimizer.init(params)
        x = np.ones((4, 8), np.float32)
        y = np.zeros((4, 8), np.float32)
        # Warm past both compile boundaries (initial + the
        # committed-sharding retrace), then clear the window so the
        # hand measurement and the meter see the same steady-state
        # steps (around a compile, dispatch returns asynchronously
        # and outer/inner interval boundaries legitimately differ).
        for _ in range(3):
            params, opt_state, _ = trainer.train_step(
                params, opt_state, x, y
            )
        trainer.mfu_meter._times.clear()
        times = []
        prev = time.perf_counter()
        for _ in range(9):
            params, opt_state, _ = trainer.train_step(
                params, opt_state, x, y
            )
            now = time.perf_counter()
            times.append(now - prev)
            prev = now
        flops = trainer.mfu_meter.flops_per_step
        assert flops is not None and flops > 0
        assert trainer.mfu is not None
        # Hand recomputation from independently measured step walls
        # (same steady-state steps, outer boundaries): gauge must
        # agree within 5%.
        hand = flops / ((sum(times) / len(times)) * 1e3)
        assert trainer.mfu == pytest.approx(hand, rel=0.05)

    def test_mfu_disabled_by_env(self, monkeypatch):
        import jax.numpy as jnp
        import numpy as np
        import optax

        import jax

        from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
        from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer

        monkeypatch.setenv(profiling.MFU_ENV, "0")
        mesh = build_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
        loss = lambda p, x, y: jnp.mean((x @ p["w"] - y) ** 2)  # noqa: E731
        trainer = ElasticTrainer(
            mesh, loss, optax.sgd(0.01),
            global_batch_size=4, micro_batch_size=4,
        )
        params = {"w": jnp.ones((8, 8))}
        opt_state = trainer.optimizer.init(params)
        x = np.ones((4, 8), np.float32)
        y = np.zeros((4, 8), np.float32)
        for _ in range(3):
            params, opt_state, _ = trainer.train_step(
                params, opt_state, x, y
            )
        assert trainer.mfu_meter.flops_per_step is None
        assert trainer.mfu is None


# ---------------------------------------------------------------------------
# PROFILE action end to end
# ---------------------------------------------------------------------------


class _ServicerClient:
    """MasterClient facade forwarding diagnostics into a servicer."""

    def __init__(self, servicer, node_id=0):
        self.servicer = servicer
        self.node_id = node_id

    def heartbeat(self):
        resp = self.servicer._heartbeat(
            msg.HeartbeatRequest(node_id=self.node_id)
        )
        return resp.action

    def report_diagnostics(self, kind, bundle_path="", digest=""):
        self.servicer._report_diagnostics(
            msg.DiagnosticsReport(
                node_id=self.node_id,
                kind=kind,
                bundle_path=bundle_path,
                digest=digest,
                timestamp=time.time(),
            )
        )


def _bare_servicer():
    from dlrover_tpu.master.job_manager import JobManager
    from dlrover_tpu.master.rendezvous import (
        ElasticRendezvous,
        NetworkCheckRendezvous,
    )
    from dlrover_tpu.master.servicer import MasterServicer
    from dlrover_tpu.master.task_manager import TaskManager

    return MasterServicer(
        job_manager=JobManager(),
        task_manager=TaskManager(),
        elastic_rdzv=ElasticRendezvous(),
        check_rdzv=NetworkCheckRendezvous(),
    )


class TestProfileAction:
    def test_profile_rpc_queues_heartbeat_action(self):
        servicer = _bare_servicer()
        servicer._profile_node_req(msg.ProfileActionRequest(node_id=3))
        assert servicer.pending_actions(3) == [
            EventAction.PROFILE.value
        ]

    def test_end_to_end_master_to_digest_history(
        self, tmp_path, monkeypatch
    ):
        """Master queues PROFILE -> agent heartbeat picks it up ->
        agent drops a request file -> a live trainer loop's profiler
        captures N steps -> digest ships back as a DiagnosticsReport
        -> queryable from the master's per-node history."""
        from dlrover_tpu.agent.agent import AgentConfig, ElasticAgent

        req_file = str(tmp_path / "req.json")
        dig_file = str(tmp_path / "dig.json")
        monkeypatch.setenv(profiling.PROFILE_REQUEST_ENV, req_file)
        monkeypatch.setenv(profiling.PROFILE_DIGEST_ENV, dig_file)
        monkeypatch.setenv(profiling.PROFILE_STEPS_ENV, "4")
        monkeypatch.setenv("DLROVER_TPU_PROFILE_WAIT_S", "20")

        servicer = _bare_servicer()
        servicer.profile_node(0)
        client = _ServicerClient(servicer, node_id=0)
        agent = ElasticAgent(
            AgentConfig(node_id=0), ["true"], client=client
        )

        # The "trainer": a loop stepping a fake-clocked profiler with
        # a known phase shape, polling the request file like
        # Trainer.train does.
        clock = FakeClock(0.0)
        mfu = profiling.MfuMeter(peak_flops=1e6)
        mfu.set_flops(5000.0)
        prof = profiling.StepPhaseProfiler(
            clock=clock,
            mfu=mfu,
            request_file=req_file,
            digest_file=dig_file,
        )
        stop = threading.Event()

        def trainer_loop():
            while not stop.is_set():
                prof.note_data_wait(0.002)
                prof.note_dispatch(0.001)
                clock.t += 0.01
                prof.end_step()
                time.sleep(0.005)

        t = threading.Thread(target=trainer_loop, daemon=True)
        t.start()
        try:
            # Heartbeat delivers the action; the agent's worker drops
            # the request and waits for the digest.
            action = client.heartbeat()
            assert action == EventAction.PROFILE.value
            agent._run_profile()
            agent._profile_thread.join(timeout=25)
            assert not agent._profile_thread.is_alive()
        finally:
            stop.set()
            t.join(timeout=5)

        reports = servicer._query_diagnostics(
            msg.DiagnosticsQueryRequest(node_id=0)
        ).reports
        assert len(reports) == 1
        rep = reports[0]
        assert rep.kind == "profile"
        digest = json.loads(rep.digest)
        assert digest["steps"] == 4
        assert digest["fn"] == "train_step"
        # Known phase shape: 0.002 wait + 0.001 dispatch per 0.01 step.
        phases = digest["phases"]
        assert phases["data_wait"]["mean_s"] == pytest.approx(
            0.002, abs=1e-6
        )
        assert phases["dispatch"]["mean_s"] == pytest.approx(
            0.001, abs=1e-6
        )
        assert phases["device_execute"]["mean_s"] == pytest.approx(
            0.007, abs=1e-6
        )
        # MFU from the fake meter: 5000 / (0.01 * 1e6) = 0.5
        assert digest["mfu"] == pytest.approx(0.5, rel=0.05)
        assert rep.bundle_path == dig_file

    def test_agent_reports_error_digest_when_no_trainer_answers(
        self, tmp_path, monkeypatch
    ):
        from dlrover_tpu.agent.agent import AgentConfig, ElasticAgent

        monkeypatch.setenv(
            profiling.PROFILE_REQUEST_ENV, str(tmp_path / "req.json")
        )
        monkeypatch.setenv(
            profiling.PROFILE_DIGEST_ENV, str(tmp_path / "dig.json")
        )
        monkeypatch.setenv("DLROVER_TPU_PROFILE_WAIT_S", "0.2")
        servicer = _bare_servicer()
        client = _ServicerClient(servicer, node_id=1)
        agent = ElasticAgent(
            AgentConfig(node_id=1), ["true"], client=client
        )
        agent._run_profile()
        agent._profile_thread.join(timeout=10)
        reports = servicer._query_diagnostics(
            msg.DiagnosticsQueryRequest(node_id=1)
        ).reports
        assert len(reports) == 1
        assert "error" in json.loads(reports[0].digest)

    def test_stale_request_not_rearmed(self, tmp_path):
        """A profiler must not re-trigger on the same request id (the
        agent's request file persists between captures)."""
        req_file = str(tmp_path / "req.json")
        dig_file = str(tmp_path / "dig.json")
        clock = FakeClock(0.0)
        prof = profiling.StepPhaseProfiler(
            clock=clock, request_file=req_file, digest_file=dig_file
        )
        profiling.write_profile_request(steps=2, path=req_file)
        assert prof.poll_request() is True
        for _ in range(2):
            clock.t += 1.0
            prof.end_step()
        assert not prof.capturing
        assert profiling.read_profile_digest(path=dig_file) is not None
        # Same file, unchanged: no new capture.
        assert prof.poll_request() is False
        # A NEW request re-arms.
        profiling.write_profile_request(steps=1, path=req_file)
        assert prof.poll_request() is True


# ---------------------------------------------------------------------------
# Bench ledger
# ---------------------------------------------------------------------------


class TestBenchLedger:
    def _append(self, path, value, stage, stats=None, error=None):
        import bench_ledger

        rec = {
            "metric": "nanogpt_tokens_per_sec_per_chip",
            "value": value,
            "unit": "tokens/s/chip",
            "stage": stage,
        }
        if stats:
            rec["stats"] = stats
        if error:
            rec["error"] = error
        return bench_ledger.append_record(rec, path=str(path))

    def test_append_fingerprints_record(self, tmp_path):
        import bench_ledger

        path = tmp_path / "ledger.jsonl"
        rec = self._append(path, 100.0, "baseline")
        for key in ("git_rev", "config_hash", "meta", "ts"):
            assert rec[key], key
        assert rec["meta"]["jax"]  # toolchain version stamped
        loaded = bench_ledger.load_records(str(path))
        assert len(loaded) == 1 and loaded[0] == rec

    def test_no_change_run_passes_gate(self, tmp_path):
        import bench_ledger

        path = tmp_path / "ledger.jsonl"
        self._append(path, 100.0, "baseline")
        self._append(path, 99.5, "adhoc")
        rc, report = bench_ledger.compare(
            "baseline", threshold=0.03, path=str(path)
        )
        assert rc == 0, report

    def test_injected_regression_trips_gate(self, tmp_path):
        import bench_ledger

        path = tmp_path / "ledger.jsonl"
        self._append(path, 100.0, "baseline")
        self._append(path, 89.0, "adhoc")  # -11%
        rc, report = bench_ledger.compare(
            "baseline", threshold=0.05, path=str(path)
        )
        assert rc == 1
        assert "REGRESSION" in report
        # Threshold is configurable: the same delta passes at 15%.
        rc, _ = bench_ledger.compare(
            "baseline", threshold=0.15, path=str(path)
        )
        assert rc == 0

    def test_stability_stats_preferred_over_value(self, tmp_path):
        import bench_ledger

        path = tmp_path / "ledger.jsonl"
        self._append(
            path, 0.0, "stability",
            stats={"n": 3, "mean": 100.0, "stddev": 1.0},
        )
        self._append(path, 96.0, "adhoc")
        rc, report = bench_ledger.compare(
            "stability", threshold=0.05, path=str(path)
        )
        assert rc == 0
        assert "n=3" in report

    def test_error_records_never_compared(self, tmp_path):
        import bench_ledger

        path = tmp_path / "ledger.jsonl"
        self._append(path, 100.0, "baseline")
        self._append(path, 0.0, "adhoc", error="tpu_unavailable")
        rc, report = bench_ledger.compare(
            "baseline", threshold=0.03, path=str(path)
        )
        # Head skips the error record and lands on... the baseline
        # itself is the only measurable one left — no older baseline.
        assert rc == 2, report
        self._append(path, 99.0, "adhoc")
        rc, _ = bench_ledger.compare(
            "baseline", threshold=0.03, path=str(path)
        )
        assert rc == 0

    def test_missing_ledger_is_rc2(self, tmp_path):
        import bench_ledger

        rc, _ = bench_ledger.compare(
            "baseline", path=str(tmp_path / "absent.jsonl")
        )
        assert rc == 2

    def test_cli_roundtrip(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        env = {**os.environ, "PYTHONPATH": REPO}
        append = subprocess.run(
            [
                sys.executable,
                os.path.join(TOOLS, "bench_ledger.py"),
                "--ledger", path, "append",
                "--json", '{"metric": "m", "value": 10.0, "unit": "u"}',
                "--stage", "baseline",
            ],
            capture_output=True, text=True, env=env, timeout=60,
        )
        assert append.returncode == 0, append.stderr
        compare = subprocess.run(
            [
                sys.executable,
                os.path.join(TOOLS, "bench_ledger.py"),
                "--ledger", path, "compare", "--baseline", "baseline",
            ],
            capture_output=True, text=True, env=env, timeout=60,
        )
        # Only one record: nothing older than head -> rc 2 (blind),
        # never a silent pass.
        assert compare.returncode == 2, compare.stdout


class TestRunMetadata:
    def test_stamp_has_required_fields(self):
        from dlrover_tpu.common.runmeta import run_metadata

        meta = run_metadata(backend="tpu")
        assert meta["backend"] == "tpu"
        assert meta["host"]
        assert meta["jax"] and meta["jaxlib"]
        assert meta["python"]

    def test_config_fingerprint_tracks_bench_env(self):
        from dlrover_tpu.common.runmeta import config_fingerprint

        a = config_fingerprint(env={"BENCH_REMAT": "full"})
        b = config_fingerprint(env={"BENCH_REMAT": "none"})
        c = config_fingerprint(env={"BENCH_REMAT": "full"})
        assert a != b and a == c
        # Non-BENCH env does not perturb the hash.
        d = config_fingerprint(
            env={"BENCH_REMAT": "full", "HOME": "/elsewhere"}
        )
        assert a == d


# ---------------------------------------------------------------------------
# Satellite: TimeoutExpired bytes handling under tools/
# ---------------------------------------------------------------------------


class TestTimeoutExpiredBytes:
    """VERDICT r5 #1: a TimeoutExpired's stdout arrives as BYTES when
    the child dies mid-pipe, and the r5 autotune handler crashed on
    it. Every handler under tools/ that reads the exception's output
    must survive the bytes path."""

    def _timeout_exc(self):
        return subprocess.TimeoutExpired(
            cmd=["x"], timeout=1,
            output="partial tok/s line".encode(),
            stderr="boom".encode(),
        )

    def test_capture_perf_decode_output(self):
        import capture_perf

        assert capture_perf.decode_output(b"abc\xff") == "abc�"
        assert capture_perf.decode_output(None) == ""
        assert capture_perf.decode_output("text") == "text"

    def test_run_autotune_survives_bytes_stdout(self, monkeypatch):
        import capture_perf

        sweep_bytes = (
            b"n_devices: 1\n"
            b"full,flash,18 step= 10.0ms tok/s= 1234.5\n"
        )

        def fake_run(*a, **kw):
            raise subprocess.TimeoutExpired(
                cmd=["autotune"], timeout=1, output=sweep_bytes
            )

        monkeypatch.setattr(
            capture_perf.subprocess, "run", fake_run
        )
        out = capture_perf.run_autotune(timeout_s=1)
        assert isinstance(out, str)
        # The partial sweep is still parseable — the r5 failure mode
        # (TypeError, results thrown away) cannot recur.
        assert capture_perf.parse_autotune(out) == (
            "full,flash,18", 1234.5
        )

    def test_run_bench_survives_bytes_tail(self, monkeypatch):
        import capture_perf

        def fake_run(*a, **kw):
            raise self_exc

        self_exc = self._timeout_exc()
        monkeypatch.setattr(
            capture_perf.subprocess, "run", fake_run
        )
        assert capture_perf.run_bench({}, timeout_s=1) is None

    def test_bench_stability_one_run_survives_timeout(self, monkeypatch):
        import bench_stability

        def fake_run(*a, **kw):
            raise subprocess.TimeoutExpired(
                cmd=["bench"], timeout=1, output=b"x", stderr=b"y"
            )

        monkeypatch.setattr(
            bench_stability.subprocess, "run", fake_run
        )
        assert bench_stability.one_run(1.0) is None

    def test_every_tools_handler_is_audited(self):
        """AST audit: enumerate every `except subprocess.TimeoutExpired`
        under tools/; any handler whose body touches the exception's
        stdout/output/stderr must route through decode_output. A new
        handler that reads raw capture attributes fails here until it
        decodes (or joins the audited no-read set)."""
        readers_without_decode = []
        handlers = 0
        for fname in sorted(os.listdir(TOOLS)):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(TOOLS, fname)
            tree = ast.parse(open(path, encoding="utf-8").read(),
                             filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                t = node.type
                names = []
                for sub in ast.walk(t) if t is not None else []:
                    if isinstance(sub, ast.Attribute):
                        names.append(sub.attr)
                    elif isinstance(sub, ast.Name):
                        names.append(sub.id)
                if "TimeoutExpired" not in names:
                    continue
                handlers += 1
                touches = False
                decodes = False
                for sub in [n for b in node.body for n in ast.walk(b)]:
                    if isinstance(sub, ast.Attribute) and sub.attr in (
                        "stdout", "output", "stderr"
                    ):
                        touches = True
                    if isinstance(sub, ast.Call):
                        fn = sub.func
                        callee = (
                            fn.attr
                            if isinstance(fn, ast.Attribute)
                            else getattr(fn, "id", "")
                        )
                        if callee == "decode_output":
                            decodes = True
                if touches and not decodes:
                    readers_without_decode.append(
                        f"{fname}:{node.lineno}"
                    )
        # The audit must actually see the known handlers (capture_perf
        # x2, bench_stability, chaos_drill) — zero means the walker
        # broke, not that the code is clean.
        assert handlers >= 4, handlers
        assert not readers_without_decode, readers_without_decode


# ---------------------------------------------------------------------------
# Satellite: fail-closed chip-contention deadline
# ---------------------------------------------------------------------------


class TestJobsChainDeadline:
    SCRIPT = os.path.join(TOOLS, "tpu_jobs_when_up.sh")

    def _run(self, env_extra):
        return subprocess.run(
            ["bash", self.SCRIPT],
            env={**os.environ, **env_extra},
            capture_output=True, text=True, timeout=30,
        )

    def test_refuses_deadline_zero(self):
        p = self._run({"DEADLINE_EPOCH": "0"})
        assert p.returncode == 2
        assert "not" in p.stderr and "DEADLINE_EPOCH" in p.stderr

    def test_refuses_garbage_deadline(self):
        p = self._run({"DEADLINE_EPOCH": "soon"})
        assert p.returncode == 2

    def test_expired_deadline_exits_cleanly_before_any_stage(self):
        p = self._run({"DEADLINE_EPOCH": "1000"})
        assert p.returncode == 0
        assert "deadline reached" in p.stdout

    def test_unset_deadline_is_derived_not_forever(self):
        # Budget of 1s: derivation happens, the first probe fails (no
        # TPU here), and the loop's deadline check fires on the next
        # iteration instead of probing forever.
        p = self._run(
            {"DEADLINE_BUDGET_S": "1", "PROBE_INTERVAL_S": "1"},
        )
        assert p.returncode == 0
        assert "derived" in p.stdout
        assert "deadline reached" in p.stdout

    def test_run_stage_kills_process_group(self, tmp_path):
        """SIGTERM -> SIGKILL of the whole stage process group on
        budget expiry: grandchildren must die with the child."""
        harness = tmp_path / "harness.sh"
        harness.write_text(
            "set -u\n"
            "DEADLINE_EPOCH=$(( $(date +%s) + 600 ))\n"
            + self._extract_run_stage()
            + '\nrun_stage 2 bash -c "sleep 7231 & exec sleep 7231"\n'
            + 'echo "stage_rc=$?"\n'
        )
        p = subprocess.run(
            ["bash", str(harness)],
            capture_output=True, text=True, timeout=60,
        )
        assert "stage_rc=124" in p.stdout
        time.sleep(0.5)
        left = subprocess.run(
            ["pgrep", "-f", "sleep 7231"],
            capture_output=True, text=True,
        )
        assert left.returncode != 0, f"leaked: {left.stdout}"

    def _extract_run_stage(self):
        src = open(self.SCRIPT).read()
        start = src.index("run_stage() {")
        end = src.index("\n}", start) + 2
        return src[start:end]


# ---------------------------------------------------------------------------
# Fleet/report integration of the new series
# ---------------------------------------------------------------------------


class TestPerfFleetIntegration:
    def test_mfu_flows_file_to_fleet_aggregate(self, tmp_path):
        """write_metrics(mfu=) -> ResourceMonitor snapshot resource ->
        FleetAggregator mfu series + aggregates."""
        from types import SimpleNamespace

        from dlrover_tpu.agent.monitor import (
            ResourceMonitor,
            TrainingMonitor,
        )
        from dlrover_tpu.obs.fleet import FleetAggregator
        from dlrover_tpu.obs.metrics import MetricsRegistry

        path = str(tmp_path / "metrics.json")
        TrainingMonitor.write_metrics(
            5, tokens=100, path=path, step_time=0.1, mfu=0.4321
        )
        mon = ResourceMonitor(client=None, metrics_file=path)
        snap = mon.build_snapshot(stats={})
        assert snap["resource"]["mfu"] == pytest.approx(0.4321)

        reg = MetricsRegistry()
        fleet = FleetAggregator(registry=reg, ttl=3600.0)
        fleet.ingest(
            SimpleNamespace(
                node_id=0, host="w0", timestamp=time.time(),
                registry={}, resource={"mfu": 0.40},
                step_times=[], events=[],
            )
        )
        fleet.ingest(
            SimpleNamespace(
                node_id=1, host="w1", timestamp=time.time(),
                registry={}, resource={"mfu": 0.50},
                step_times=[], events=[],
            )
        )
        body = reg.render()
        assert (
            'dlrover_fleet_series{series="mfu",stat="min"} 0.4' in body
        )
        assert (
            'dlrover_fleet_series{series="mfu",stat="max"} 0.5' in body
        )
        fleet.close()

    def test_obs_report_perf_flag(self, tmp_path):
        trace = tmp_path / "trace.jsonl"
        events = [
            {"name": "trainer.step_phases", "ts": 1.0, "step": 1,
             "wall_s": 1.0, "data_wait_s": 0.1, "compile_s": 0.0,
             "dispatch_s": 0.1, "device_s": 0.8, "mfu": 0.5},
            {"name": "trainer.compile", "ts": 0.5, "fn": "train_step",
             "dur_s": 2.0},
        ]
        trace.write_text(
            "".join(json.dumps(e) + "\n" for e in events)
        )
        p = subprocess.run(
            [
                sys.executable,
                os.path.join(TOOLS, "obs_report.py"),
                str(trace), "--perf",
            ],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert p.returncode == 0, p.stderr
        assert "step phases" in p.stdout
        assert "device_execute" in p.stdout
        assert "compiles: train_step x1" in p.stdout
