"""Observability subsystem: registry, tracer, timeline, exposition.

Covers the hermetic pieces (render without any server, span nesting,
timeline reconstruction from a synthetic event log), the master's
Prometheus surface (HTTP /metrics + MetricsRequest RPC on a real
in-process JobMaster), the obs_report CLI selftest, and the
stdlib-only contract (no prometheus_client / opentelemetry imports
anywhere in the package).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from dlrover_tpu import obs
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.obs.metrics import MetricsRegistry
from dlrover_tpu.obs.timeline import (
    load_events,
    reconstruct_recovery_timeline,
)
from dlrover_tpu.obs.tracer import EventTracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def tracer():
    """Fresh module-level tracer; restores the disabled default."""
    tr = obs.configure_tracer()
    yield tr
    obs.disable_tracer()


class TestMetricsRegistry:
    def test_counter_labels_and_render(self):
        reg = MetricsRegistry()
        c = reg.counter("events_total", "Things seen", ("kind",))
        c.inc(kind="a")
        c.inc(2, kind="b")
        assert c.value(kind="a") == 1
        assert c.value(kind="b") == 2
        out = reg.render()
        assert "# HELP events_total Things seen" in out
        assert "# TYPE events_total counter" in out
        assert 'events_total{kind="a"} 1' in out
        assert 'events_total{kind="b"} 2' in out

    def test_counter_rejects_negative_and_bad_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total", labelnames=("k",))
        with pytest.raises(ValueError):
            c.inc(-1, k="x")
        with pytest.raises(ValueError):
            c.inc(wrong="x")

    def test_gauge_set_inc_dec(self):
        reg = MetricsRegistry()
        g = reg.gauge("g")
        g.set(5)
        g.inc()
        g.dec(2)
        assert g.value() == 4
        assert "g 4" in reg.render()

    def test_histogram_buckets_cumulative(self):
        reg = MetricsRegistry()
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        out = reg.render()
        assert 'h_seconds_bucket{le="0.1"} 1' in out
        assert 'h_seconds_bucket{le="1"} 2' in out
        assert 'h_seconds_bucket{le="+Inf"} 3' in out
        assert "h_seconds_count 3" in out
        assert h.sum() == pytest.approx(5.55)

    def test_registration_idempotent_but_type_safe(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", labelnames=("k",))
        assert reg.counter("x_total", labelnames=("k",)) is a
        with pytest.raises(ValueError):
            reg.gauge("x_total")
        with pytest.raises(ValueError):
            reg.counter("x_total", labelnames=("other",))
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
        assert reg.histogram("h_seconds", buckets=(0.1, 1.0)) is h
        # +Inf is implied, so an explicit one is the same registration
        assert (
            reg.histogram("h_seconds", buckets=(0.1, 1.0, float("inf")))
            is h
        )
        with pytest.raises(ValueError):
            reg.histogram("h_seconds", buckets=(0.001, 0.002))

    def test_label_value_escaping(self):
        reg = MetricsRegistry()
        c = reg.counter("esc_total", labelnames=("v",))
        c.inc(v='say "hi"\nback\\slash')
        out = reg.render()
        assert r'esc_total{v="say \"hi\"\nback\\slash"} 1' in out


class TestTracer:
    def test_event_tags_and_ring(self, tracer):
        obs.event("unit.test", step=3)
        ev = tracer.events()[-1]
        assert ev["name"] == "unit.test"
        assert ev["step"] == 3
        assert ev["pid"] == os.getpid()
        assert "ts" in ev and "mono" in ev

    def test_span_nesting_records_parent(self, tracer):
        with obs.span("outer"):
            with obs.span("inner"):
                time.sleep(0.01)
        names = {e["name"]: e for e in tracer.events()}
        assert names["inner"]["parent"] == "outer"
        assert "parent" not in names["outer"]
        assert names["inner"]["dur_s"] >= 0.01
        # outer wraps inner entirely
        assert names["outer"]["dur_s"] >= names["inner"]["dur_s"]

    def test_span_records_exception(self, tracer):
        with pytest.raises(RuntimeError):
            with obs.span("boom"):
                raise RuntimeError("x")
        ev = tracer.events()[-1]
        assert ev["name"] == "boom"
        assert ev["error"] == "RuntimeError"

    def test_disabled_is_noop(self):
        obs.disable_tracer()
        assert obs.event("nope") is None
        with obs.span("nope"):
            pass
        assert not obs.tracing_enabled()

    def test_jsonl_export_roundtrip(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        tr = EventTracer(sink_path=path)
        tr.event("a", k=1)
        with tr.span("b"):
            pass
        tr.close()
        events = load_events(path)
        assert [e["name"] for e in events] == ["a", "b"]
        assert events[1]["dur_s"] >= 0

    def test_events_since_arrival_order_keeps_late_spans(self, tracer):
        """A span emitted AFTER a plain event carries an earlier mono
        (its start); the arrival-order cursor must still deliver it."""
        with obs.span("slow.span"):
            obs.event("mid.event")  # later mono than the span's start
        first, cursor = tracer.events_since(0)
        assert [e["name"] for e in first] == ["mid.event", "slow.span"]
        nothing, cursor2 = tracer.events_since(cursor)
        assert nothing == [] and cursor2 == cursor
        obs.event("after")
        fresh, _ = tracer.events_since(cursor)
        assert [e["name"] for e in fresh] == ["after"]

    def test_events_since_stale_cursor_resets(self):
        tr = EventTracer()
        tr.event("a")
        events, _ = tr.events_since(10_000)  # cursor from a dead tracer
        assert [e["name"] for e in events] == ["a"]

    def test_load_events_skips_torn_lines(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        path.write_text(
            json.dumps({"name": "ok", "ts": 1.0})
            + "\n{\"name\": \"torn"
        )
        events = load_events(str(path))
        assert [e["name"] for e in events] == ["ok"]


class TestTimeline:
    MARKS = (
        ("node.fail", 100.0),
        ("trainer.proc_start", 104.0),
        ("trainer.dist_ready", 110.0),
        ("trainer.built", 125.0),
        ("trainer.restore_done", 127.5),
        ("trainer.first_step_done", 140.0),
    )

    def events(self):
        return [{"name": n, "ts": t} for n, t in self.MARKS]

    def test_full_reconstruction(self):
        tl = reconstruct_recovery_timeline(self.events())
        assert tl is not None and tl.complete
        assert tl.phases["failure-detect"] == pytest.approx(4.0)
        assert tl.phases["rendezvous"] == pytest.approx(6.0)
        assert tl.phases["build"] == pytest.approx(15.0)
        assert tl.phases["restore"] == pytest.approx(2.5)
        assert tl.phases["first-step"] == pytest.approx(12.5)
        assert tl.phases["throughput-90"] is None
        assert tl.total_s == pytest.approx(40.0)

    def test_explicit_failure_time_and_recovery_ts(self):
        tl = reconstruct_recovery_timeline(
            self.events()[1:],  # no master-side failure event
            t_failure=101.0,
            throughput_recovered_ts=150.0,
        )
        assert tl.complete
        assert tl.phases["failure-detect"] == pytest.approx(3.0)
        assert tl.phases["throughput-90"] == pytest.approx(10.0)
        assert tl.total_s == pytest.approx(49.0)

    def test_multi_attempt_log_picks_first_after_failure(self):
        # A pre-failure attempt's marks must be ignored.
        stale = [
            {"name": n, "ts": t - 50.0}
            for n, t in self.MARKS[1:]
        ]
        tl = reconstruct_recovery_timeline(
            stale + self.events(), t_failure=100.0
        )
        assert tl.complete
        assert tl.marks["trainer.proc_start"] == 104.0

    def test_incomplete_when_marks_missing(self):
        tl = reconstruct_recovery_timeline(self.events()[:3])
        assert tl is not None and not tl.complete
        assert tl.phases["restore"] is None

    def test_no_anchor_returns_none(self):
        assert (
            reconstruct_recovery_timeline(self.events()[1:]) is None
        )

    def test_to_dict_round(self):
        d = reconstruct_recovery_timeline(self.events()).to_dict()
        assert d["complete"] is True
        assert d["phases"]["rendezvous"] == 6.0


class TestTimelineAdversarial:
    """Reconstruction under hostile streams: out-of-order events,
    duplicate marks, missing terminal phases. A damaged stream must
    yield a partial timeline (or None), never a negative or silently
    wrong duration."""

    def _assert_no_negative(self, tl):
        for name, dur in tl.phases.items():
            assert dur is None or dur >= 0, (name, dur)
        assert tl.total_s >= 0

    def test_out_of_order_stream_reconstructs_identically(self):
        import random

        base = TestTimeline().events()
        shuffled = list(base)
        random.Random(7).shuffle(shuffled)
        a = reconstruct_recovery_timeline(base)
        b = reconstruct_recovery_timeline(shuffled)
        assert a.phases == b.phases
        assert a.marks == b.marks

    def test_duplicate_marks_use_first_occurrence(self):
        events = TestTimeline().events()
        # A retried writer duplicates every mark a little later.
        dupes = [
            {"name": e["name"], "ts": e["ts"] + 0.5}
            for e in events
            if e["name"].startswith("trainer.")
        ]
        tl = reconstruct_recovery_timeline(events + dupes)
        assert tl.complete
        assert tl.marks["trainer.proc_start"] == 104.0
        assert tl.phases["rendezvous"] == pytest.approx(6.0)
        self._assert_no_negative(tl)

    def test_missing_terminal_phase_is_partial_not_wrong(self):
        events = [
            e
            for e in TestTimeline().events()
            if e["name"] != "trainer.first_step_done"
        ]
        tl = reconstruct_recovery_timeline(events)
        assert tl is not None and not tl.complete
        assert tl.phases["first-step"] is None
        self._assert_no_negative(tl)

    def test_missing_middle_mark_never_misassigns(self):
        # dist_ready lost: everything downstream of the gap must be
        # unknown rather than silently merged into one phase.
        events = [
            e
            for e in TestTimeline().events()
            if e["name"] != "trainer.dist_ready"
        ]
        tl = reconstruct_recovery_timeline(events)
        assert tl is not None and not tl.complete
        assert tl.phases["rendezvous"] is None
        assert tl.phases["build"] is None
        self._assert_no_negative(tl)

    def test_recovery_stamp_before_first_step_not_negative(self):
        tl = reconstruct_recovery_timeline(
            TestTimeline().events(),
            throughput_recovered_ts=130.0,  # before first_step (140)
        )
        assert tl is not None
        assert tl.phases["throughput-90"] is None
        self._assert_no_negative(tl)

    def test_marks_before_failure_only_yields_none_or_partial(self):
        # Every trainer mark predates the failure instant: nothing to
        # anchor on after t_failure.
        events = TestTimeline().events()
        tl = reconstruct_recovery_timeline(events, t_failure=999.0)
        assert tl is None or not tl.complete
        if tl is not None:
            self._assert_no_negative(tl)

    def test_equal_timestamps_yield_zero_not_negative(self):
        events = [
            {"name": "node.fail", "ts": 10.0},
            {"name": "trainer.proc_start", "ts": 10.0},
            {"name": "trainer.dist_ready", "ts": 10.0},
            {"name": "trainer.built", "ts": 10.0},
            {"name": "trainer.restore_done", "ts": 10.0},
            {"name": "trainer.first_step_done", "ts": 10.0},
        ]
        tl = reconstruct_recovery_timeline(events)
        assert tl.complete
        for name in ("rendezvous", "build", "restore", "first-step"):
            assert tl.phases[name] == 0.0
        self._assert_no_negative(tl)


class TestMetricNameHygiene:
    """Audit every obs.counter/gauge/histogram registration in the
    framework and tools: dlrover_-prefixed snake_case names, non-empty
    help strings, no name registered with conflicting types, and
    literal label names in snake_case (never the reserved Prometheus
    names ``le`` / ``quantile`` / ``__``-prefixed)."""

    METRIC_NAME_RE = r"^dlrover_[a-z0-9]+(_[a-z0-9]+)*$"
    LABEL_NAME_RE = r"^[a-z][a-z0-9_]*$"
    RESERVED_LABELS = ("le", "quantile")
    # Unbounded-cardinality identifiers: request/trace ids live in
    # SPANS (the trace store), never in a metric label — one label
    # value per request would grow every scrape forever.
    UNBOUNDED_LABELS = ("request_id", "trace_id", "span_id")

    def _call_sites(self):
        import ast

        sites = []
        for root in ("dlrover_tpu", "tools"):
            for dirpath, _, files in os.walk(os.path.join(REPO, root)):
                if "__pycache__" in dirpath:
                    continue
                for fname in files:
                    if not fname.endswith(".py"):
                        continue
                    fpath = os.path.join(dirpath, fname)
                    with open(fpath, encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=fpath)
                    for node in ast.walk(tree):
                        if not (
                            isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr
                            in ("counter", "gauge", "histogram")
                        ):
                            continue
                        args = node.args
                        if not (
                            args
                            and isinstance(args[0], ast.Constant)
                            and isinstance(args[0].value, str)
                        ):
                            continue  # dynamic name: not a literal
                            # registration site
                        name = args[0].value
                        help_ = None
                        if len(args) > 1 and isinstance(
                            args[1], ast.Constant
                        ):
                            help_ = args[1].value
                        labels_node = (
                            args[2] if len(args) > 2 else None
                        )
                        for kw in node.keywords:
                            if kw.arg == "help" and isinstance(
                                kw.value, ast.Constant
                            ):
                                help_ = kw.value.value
                            if kw.arg == "labelnames":
                                labels_node = kw.value
                        labels = None  # None = not a literal tuple
                        if isinstance(
                            labels_node, (ast.Tuple, ast.List)
                        ) and all(
                            isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                            for e in labels_node.elts
                        ):
                            labels = [
                                e.value for e in labels_node.elts
                            ]
                        rel = os.path.relpath(fpath, REPO)
                        sites.append(
                            (rel, node.lineno, node.func.attr,
                             name, help_, labels)
                        )
        return sites

    def test_all_registrations_are_hygienic(self):
        import re

        sites = self._call_sites()
        # The framework registers plenty of metrics; an empty audit
        # means the walker broke, not that the code is clean.
        assert len(sites) >= 15, sites
        problems = []
        types_seen = {}
        labeled_sites = 0
        for rel, line, mtype, name, help_, labels in sites:
            where = f"{rel}:{line}"
            if not re.match(self.METRIC_NAME_RE, name):
                problems.append(
                    f"{where}: {name!r} is not dlrover_-prefixed "
                    "snake_case"
                )
            if not (isinstance(help_, str) and help_.strip()):
                problems.append(
                    f"{where}: {name!r} registered without a help "
                    "string"
                )
            prev = types_seen.setdefault(name, (mtype, where))
            if prev[0] != mtype:
                problems.append(
                    f"{where}: {name!r} registered as {mtype} but "
                    f"as {prev[0]} at {prev[1]}"
                )
            if labels:
                labeled_sites += 1
                for label in labels:
                    if not re.match(self.LABEL_NAME_RE, label):
                        problems.append(
                            f"{where}: {name!r} label {label!r} is "
                            "not snake_case"
                        )
                    if (
                        label in self.RESERVED_LABELS
                        or label.startswith("__")
                    ):
                        problems.append(
                            f"{where}: {name!r} label {label!r} is "
                            "reserved by Prometheus"
                        )
                    if label in self.UNBOUNDED_LABELS:
                        problems.append(
                            f"{where}: {name!r} label {label!r} has "
                            "unbounded cardinality — ids belong in "
                            "trace spans, not metric labels"
                        )
        # The walker must actually see labeled registrations (e.g.
        # dlrover_forensics_bundles_total{node,kind}); zero means the
        # label extraction broke, not that the code is clean.
        assert labeled_sites >= 5, sites
        assert not problems, "\n".join(problems)

    def test_registry_rejects_conflicting_reregistration_runtime(self):
        reg = MetricsRegistry()
        reg.counter("dlrover_x_total", "x")
        with pytest.raises(ValueError):
            reg.gauge("dlrover_x_total", "x")

    def test_health_plane_metrics_are_audited(self):
        """The health plane's registrations must be visible to the
        walker with the contract names/types/labels — a rename or a
        dynamic registration would silently drop them from the audit
        (and from every dashboard keyed on them)."""
        sites = {
            name: (mtype, labels)
            for _, _, mtype, name, _, labels in self._call_sites()
        }
        assert sites.get("dlrover_health_verdicts_total") == (
            "counter",
            ["detector", "severity"],
        ), sites.get("dlrover_health_verdicts_total")
        mtype, labels = sites.get("dlrover_job_health_score", (None, 0))
        assert mtype == "gauge" and not labels, (mtype, labels)

    def test_serving_plane_metrics_are_audited(self):
        """The serving plane's dlrover_serve_* registrations
        (dlrover_tpu/serving/) must be visible to the walker with the
        contract names/types/labels — a rename or dynamic
        registration would drop them from the audit and from every
        dashboard keyed on them."""
        sites = {
            name: (mtype, labels)
            for _, _, mtype, name, _, labels in self._call_sites()
        }
        expected = {
            "dlrover_serve_requests_total": ("counter", ["outcome"]),
            "dlrover_serve_tokens_total": ("counter", ["kind"]),
            "dlrover_serve_kv_alloc_total": ("counter", ["outcome"]),
            "dlrover_serve_replicas": ("gauge", ["state"]),
            "dlrover_serve_kv_utilization": ("gauge", None),
            "dlrover_serve_kv_blocks_in_use": ("gauge", None),
            "dlrover_serve_queue_depth": ("gauge", None),
            "dlrover_serve_inflight": ("gauge", None),
            "dlrover_serve_replica_queue_depth": ("gauge", None),
            "dlrover_serve_active_sequences": ("gauge", None),
            "dlrover_serve_p99_latency_seconds": ("gauge", None),
            "dlrover_serve_qps": ("gauge", None),
            "dlrover_serve_preemptions_total": ("counter", None),
            "dlrover_serve_ttft_seconds": ("histogram", None),
            "dlrover_serve_tpot_seconds": ("histogram", None),
            "dlrover_serve_replica_restarts_total": (
                "counter", ["reason"],
            ),
            # Prefill/decode disaggregation (serving/handoff.py +
            # router role surface).
            "dlrover_serve_handoff_total": ("counter", ["outcome"]),
            "dlrover_serve_handoff_bytes": ("gauge", None),
            "dlrover_serve_handoff_queue_depth": ("gauge", None),
            "dlrover_serve_handoff_seconds": ("histogram", None),
            "dlrover_serve_role_replicas": ("gauge", ["role"]),
        }
        problems = {}
        for name, want in expected.items():
            got = sites.get(name)
            if got is None or got[0] != want[0] or (
                want[1] is not None and got[1] != want[1]
            ):
                problems[name] = (got, want)
        assert not problems, problems

    def test_pool_plane_metrics_are_audited(self):
        """The multi-job pool plane's dlrover_pool_* registrations
        (dlrover_tpu/pool/) must be visible to the walker with the
        contract names/types/labels — the obs_report --pool and
        docs/MULTI_JOB.md dashboard surface keys on them."""
        sites = {
            name: (mtype, labels)
            for _, _, mtype, name, _, labels in self._call_sites()
        }
        expected = {
            "dlrover_pool_slices": ("gauge", ["state"]),
            "dlrover_pool_tenant_slices": ("gauge", ["tenant"]),
            "dlrover_pool_queue_depth": ("gauge", ["band"]),
            "dlrover_pool_jobs": ("gauge", ["state"]),
            "dlrover_pool_placement_seconds": ("histogram", None),
            "dlrover_pool_wait_seconds": ("histogram", ["band"]),
            "dlrover_pool_preemptions_total": (
                "counter", ["reason"],
            ),
            "dlrover_pool_quota_denied_total": (
                "counter", ["tenant"],
            ),
            "dlrover_pool_backfills_total": ("counter", None),
        }
        problems = {}
        for name, want in expected.items():
            got = sites.get(name)
            if got is None or got[0] != want[0] or (
                want[1] is not None and got[1] != want[1]
            ):
                problems[name] = (got, want)
        assert not problems, problems

    def test_capacity_plane_metrics_are_audited(self):
        """The capacity accounting plane's registrations
        (obs/capacity.py chip-second ledger + obs/health.py SLO
        budget engine) must be visible to the walker with the
        contract names/types/labels — obs_report --capacity, the
        docs/OBSERVABILITY.md dashboard rows, and the burn-rate
        alerts all key on them. Labels stay bounded: tenant/state/
        slo only, never job_id."""
        sites = {
            name: (mtype, labels)
            for _, _, mtype, name, _, labels in self._call_sites()
        }
        expected = {
            "dlrover_pool_chip_seconds_total": (
                "counter", ["tenant", "state"],
            ),
            "dlrover_tenant_goodput_per_chip": (
                "gauge", ["tenant"],
            ),
            "dlrover_slo_budget_remaining": (
                "gauge", ["tenant", "slo"],
            ),
        }
        problems = {}
        for name, want in expected.items():
            got = sites.get(name)
            if got != want:
                problems[name] = (got, want)
        assert not problems, problems

    def test_stall_plane_metrics_are_audited(self):
        """The stall-localization plane's registrations
        (obs/stall.py) must be visible to the walker with the
        contract names/types/labels — the OBSERVABILITY.md alert
        rows ("open_incident > 0 for 5m", capture-rate) and the
        drill assertions key on them. Labels stay bounded: kind is
        {laggard, fleet_wide}, action is {diagnose, profile} —
        never host or incident id."""
        sites = {
            name: (mtype, labels)
            for _, _, mtype, name, _, labels in self._call_sites()
        }
        expected = {
            "dlrover_stall_incidents_total": ("counter", ["kind"]),
            "dlrover_stall_open_incident": ("gauge", None),
            "dlrover_stall_beacon_hosts": ("gauge", None),
            "dlrover_stall_captures_total": ("counter", ["action"]),
        }
        problems = {}
        for name, want in expected.items():
            got = sites.get(name)
            if got != want:
                problems[name] = (got, want)
        assert not problems, problems

    def test_stream_plane_metrics_are_audited(self):
        """The streaming exactly-once plane's registrations
        (ps_server fence, servicer barrier, trainer replay) must be
        visible to the walker with the contract names/types/labels —
        the FAULT_TOLERANCE.md failure matrix and stream_soak audit
        key on them. Labels stay bounded: table and dataset names,
        never client ids or sequence numbers."""
        sites = {
            name: (mtype, labels)
            for _, _, mtype, name, _, labels in self._call_sites()
        }
        expected = {
            "dlrover_stream_fenced_applies_total": (
                "counter", ["table"],
            ),
            "dlrover_stream_stale_epoch_rejects_total": (
                "counter", ["table"],
            ),
            "dlrover_stream_barriers_total": (
                "counter", ["dataset"],
            ),
            "dlrover_stream_barrier_seconds": ("histogram", None),
            "dlrover_stream_watermark_records": (
                "gauge", ["dataset"],
            ),
            "dlrover_stream_replayed_applies_total": (
                "counter", ["table"],
            ),
        }
        problems = {}
        for name, want in expected.items():
            got = sites.get(name)
            if got != want:
                problems[name] = (got, want)
        assert not problems, problems


class TestSpanNameHygiene:
    """Audit every literal ``obs.span(...)`` / ``obs.event(...)``
    name in the framework and tools: dotted lowercase namespaces
    (``serve.requeue``, ``remediation.decision``, ``rdzv.start`` —
    never camelCase, never a bare un-namespaced word), so the trace
    store's plane attribution and obs_report's renderers can key on a
    stable naming contract."""

    SPAN_NAME_RE = r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$"
    # The plane each subsystem's spans/events must namespace under.
    PLANE_PREFIXES = {
        os.path.join("dlrover_tpu", "serving"): ("serve.",),
        os.path.join("dlrover_tpu", "master", "remediation.py"): (
            "remediation.",
        ),
        os.path.join("dlrover_tpu", "master", "rendezvous.py"): (
            "rdzv.",
        ),
        os.path.join("dlrover_tpu", "pool"): ("pool.",),
        os.path.join("dlrover_tpu", "obs", "stall.py"): ("stall.",),
    }

    def _call_sites(self):
        import ast

        sites = []
        for root in ("dlrover_tpu", "tools"):
            for dirpath, _, files in os.walk(os.path.join(REPO, root)):
                if "__pycache__" in dirpath:
                    continue
                for fname in files:
                    if not fname.endswith(".py"):
                        continue
                    fpath = os.path.join(dirpath, fname)
                    with open(fpath, encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=fpath)
                    for node in ast.walk(tree):
                        if not (
                            isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr in ("span", "event")
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id in ("obs", "tracer")
                        ):
                            continue
                        if not (
                            node.args
                            and isinstance(node.args[0], ast.Constant)
                            and isinstance(node.args[0].value, str)
                        ):
                            continue  # dynamic name: not auditable
                        sites.append(
                            (
                                os.path.relpath(fpath, REPO),
                                node.lineno,
                                node.args[0].value,
                            )
                        )
        return sites

    def test_span_names_are_dotted_lowercase_namespaces(self):
        import re

        sites = self._call_sites()
        # The framework emits plenty of spans/events; an empty audit
        # means the walker broke, not that the code is clean.
        assert len(sites) >= 30, sites
        problems = []
        for rel, line, name in sites:
            where = f"{rel}:{line}"
            if not re.match(self.SPAN_NAME_RE, name):
                problems.append(
                    f"{where}: span/event name {name!r} is not a "
                    "dotted lowercase namespace"
                )
        assert not problems, "\n".join(problems)

    def test_planes_use_their_namespace(self):
        sites = self._call_sites()
        problems = []
        for rel, line, name in sites:
            for subpath, prefixes in self.PLANE_PREFIXES.items():
                if not rel.startswith(subpath):
                    continue
                if not name.startswith(prefixes):
                    problems.append(
                        f"{rel}:{line}: {name!r} outside the "
                        f"{prefixes} namespace(s) of its plane"
                    )
        assert not problems, "\n".join(problems)

    def test_serving_and_remediation_planes_are_audited(self):
        """The walker must actually SEE the cross-plane span names
        the trace store and drill assertions key on — a rename or a
        move to dynamic names would silently drop them from the
        audit."""
        names = {name for _, _, name in self._call_sites()}
        for required in (
            "serve.submit", "serve.requeue", "serve.drain",
            "remediation.decision", "remediation.drain_replica",
            "rdzv.start", "rdzv.complete",
            "stall.incident", "stall.resolved",
        ):
            assert required in names, (required, sorted(names))

    def test_stall_trace_spans_keep_the_namespace(self):
        """The correlator mints its incident timeline via
        ``traces.add_span`` (the walker above only sees
        ``obs.span``/``obs.event``), so audit those literal names
        directly: every span in obs/stall.py must live under the
        ``stall.`` namespace the trace store's plane attribution
        routes on."""
        import ast
        import re

        fpath = os.path.join(REPO, "dlrover_tpu", "obs", "stall.py")
        with open(fpath, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=fpath)
        names = []
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_span"
            ):
                continue
            # add_span(trace_id, name, ...) — name is the second
            # positional argument.
            if len(node.args) > 1 and isinstance(
                node.args[1], ast.Constant
            ):
                names.append((node.lineno, node.args[1].value))
        # Root + progress + capture + resolved at minimum; an empty
        # audit means the walker broke, not that the code is clean.
        assert len(names) >= 4, names
        for line, name in names:
            assert re.match(self.SPAN_NAME_RE, name), (line, name)
            assert name.startswith("stall."), (line, name)
        # And the trace store must actually route that namespace to
        # a plane — otherwise stall.incident timelines render as
        # "unknown" in obs_report --trace.
        from dlrover_tpu.obs.trace_store import _plane_of

        assert _plane_of("stall.incident") == "stall"


class TestMasterExposition:
    """Acceptance: the master exposes Prometheus text metrics (node
    states, relaunch counts, rendezvous rounds, step throughput) over
    HTTP and the MetricsRequest RPC."""

    @pytest.fixture()
    def master(self):
        m = JobMaster(
            port=0, node_num=2, rdzv_timeout=1.0, metrics_port=0,
            collect_interval=999.0,
        )
        m.prepare()
        yield m
        m.stop()

    def test_metrics_http_and_rpc(self, master):
        client = RpcClient(master.addr)
        client.report(msg.NodeAddressRequest(node_id=0, node_ip="h0"))
        client.report(msg.NodeAddressRequest(node_id=1, node_ip="h1"))
        for rank in (0, 1):
            client.get(
                msg.JoinRendezvousRequest(
                    node_id=rank, node_rank=rank, local_world_size=4,
                    rdzv_name=RendezvousName.TRAINING,
                )
            )
        world = client.get(
            msg.CommWorldRequest(
                node_id=0, rdzv_name=RendezvousName.TRAINING
            )
        )
        assert world.world  # round froze -> rdzv metrics recorded
        client.report(msg.StepReport(node_id=0, step=1, tokens=512))
        time.sleep(0.05)
        client.report(msg.StepReport(node_id=0, step=3, tokens=1024))
        # a worker dies and is relaunched -> relaunch counter moves
        client.report(
            msg.NodeFailureReport(
                node_id=1, error_data="out of memory",
                level="process_error", restart_count=0,
            )
        )
        # Push one snapshot through the registry reporter (the
        # periodic loop is parked at collect_interval=999).
        master.metric_collector.collect_once()

        url = f"http://127.0.0.1:{master.metrics_server.port}/metrics"
        body = urllib.request.urlopen(url, timeout=5).read().decode()
        assert 'dlrover_job_workers{state="alive"} 1' in body
        assert 'dlrover_job_workers{state="pending"} 1' in body
        assert "dlrover_node_relaunch_total" in body
        assert 'reason="oom"' in body
        assert (
            'dlrover_rendezvous_rounds_total{name="elastic-training"}'
            in body
        )
        assert "dlrover_job_steps_per_second" in body
        assert "dlrover_job_tokens_per_second" in body
        # Same payload over the control-plane RPC.
        rpc_body = client.get(msg.MetricsRequest()).text
        assert "dlrover_node_events_total" in rpc_body
        # healthz + 404
        health = urllib.request.urlopen(
            url.replace("/metrics", "/healthz"), timeout=5
        )
        assert health.status == 200
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                url.replace("/metrics", "/nope"), timeout=5
            )

    def test_collector_stop_joins_thread(self, master):
        thread = master.metric_collector._thread
        assert thread is not None and thread.is_alive()
        master.stop()
        assert master.metric_collector._thread is None
        assert not thread.is_alive()
        assert master.metrics_server is None


class TestCollectorFailurePaths:
    def test_collect_once_survives_raising_reporter(self):
        from dlrover_tpu.master.job_manager import JobManager
        from dlrover_tpu.master.metrics import (
            JobMetricCollector,
            Reporter,
        )
        from dlrover_tpu.master.speed_monitor import SpeedMonitor

        calls = []

        class Boom(Reporter):
            def report(self, snapshot):
                raise OSError("disk full")

        class Records(Reporter):
            def report(self, snapshot):
                calls.append(snapshot)

        coll = JobMetricCollector(
            "j", JobManager(), SpeedMonitor(),
            reporters=[Boom(), Records()], interval=999,
        )
        snap = coll.collect_once()  # must not raise
        # the healthy reporter still ran, after the broken one
        assert calls == [snap]

    def test_collector_loop_survives_reporter_failure(self):
        from dlrover_tpu.master.job_manager import JobManager
        from dlrover_tpu.master.metrics import (
            JobMetricCollector,
            Reporter,
        )
        from dlrover_tpu.master.speed_monitor import SpeedMonitor

        seen = threading.Event()

        class Boom(Reporter):
            def report(self, snapshot):
                seen.set()
                raise RuntimeError("reporter died")

        coll = JobMetricCollector(
            "j", JobManager(), SpeedMonitor(),
            reporters=[Boom()], interval=0.01,
        )
        coll.start()
        try:
            assert seen.wait(5.0)
            seen.clear()
            assert seen.wait(5.0), (
                "loop died after a reporter exception"
            )
        finally:
            coll.stop()
        assert coll._thread is None


class TestTooling:
    def test_obs_report_selftest(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "obs_report.py"),
             "--selftest"],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "obs selftest ok" in proc.stdout

    def test_obs_report_renders_trace_file(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        events = [
            {"name": n, "ts": t}
            for n, t in TestTimeline.MARKS
        ] + [{"name": "ckpt.save_memory", "ts": 141.0, "dur_s": 0.4}]
        path.write_text(
            "".join(json.dumps(e) + "\n" for e in events)
        )
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "obs_report.py"),
             str(path)],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "recovery timeline" in proc.stdout
        assert "failure-detect" in proc.stdout
        assert "ckpt.save_memory" in proc.stdout

    def test_no_prometheus_or_otel_imports(self):
        """The stdlib-only contract: nothing in the framework, tools,
        or examples may import prometheus_client or opentelemetry."""
        banned = ("prometheus_client", "opentelemetry")
        offenders = []
        for root in ("dlrover_tpu", "tools", "examples"):
            for dirpath, _, files in os.walk(os.path.join(REPO, root)):
                for fname in files:
                    if not fname.endswith(".py"):
                        continue
                    fpath = os.path.join(dirpath, fname)
                    with open(fpath, encoding="utf-8") as f:
                        src = f.read()
                    for mod in banned:
                        if (
                            f"import {mod}" in src
                            or f"from {mod}" in src
                        ):
                            offenders.append((fpath, mod))
        assert not offenders, (
            f"stdlib-only observability contract broken: {offenders}"
        )
