"""Cross-pod coworker transport: separate OS processes standing in
for CPU pods stream preprocessed batches over the typed-RPC layer into
the training host's shm ring (VERDICT r3 item 3; ref
atorch/data/coworker_dataset.py:16,25-40 — coworker PODS, not sibling
processes).
"""

import multiprocessing as mp
import os
import signal
import time
import uuid

import numpy as np
import pytest

from dlrover_tpu.data.ingest import BatchIngestServer


@pytest.fixture(autouse=True)
def _isolated_job(monkeypatch):
    monkeypatch.setenv(
        "DLROVER_TPU_JOB_NAME", f"pod{uuid.uuid4().hex[:8]}"
    )
    yield


def _pod_batches(pod_id, n_batches=6, dim=8):
    for i in range(n_batches):
        yield {
            "x": np.full((4, dim), pod_id * 100 + i, np.float32),
            "ids": np.arange(4, dtype=np.int64) + pod_id * 1000 + i,
        }


def _pod_main(ingest_addr, pod_id, job_name):
    os.environ["DLROVER_TPU_JOB_NAME"] = job_name
    from dlrover_tpu.data.ingest import run_remote_coworker

    run_remote_coworker(ingest_addr, _pod_batches, pod_id=pod_id)


def _failing_batches(pod_id):
    yield {"x": np.ones((2, 2), np.float32)}
    raise RuntimeError("synthetic remote preprocessing failure")


def _failing_pod_main(ingest_addr, pod_id, job_name):
    os.environ["DLROVER_TPU_JOB_NAME"] = job_name
    from dlrover_tpu.data.ingest import run_remote_coworker

    try:
        run_remote_coworker(
            ingest_addr, _failing_batches, pod_id=pod_id
        )
    except RuntimeError:
        pass  # the error-end was already delivered


def _fetch(indices):
    return {
        "idx": np.asarray(indices, np.int64),
        "x": np.asarray(indices, np.float32) * 0.5,
    }


def _sharded_pod_main(
    ingest_addr, master_addr, pod_id, job_name, slow_s
):
    os.environ["DLROVER_TPU_JOB_NAME"] = job_name
    from dlrover_tpu.data.coworker import make_sharded_batches
    from dlrover_tpu.data.ingest import run_remote_coworker

    base = make_sharded_batches(
        master_addr, "ds", batch_size=4, fetch_fn=_fetch,
        node_id=pod_id,
    )

    def throttled(worker_id):
        for batch in base(worker_id):
            if slow_s:
                time.sleep(slow_s)
            yield batch

    run_remote_coworker(ingest_addr, throttled, pod_id=pod_id)


class TestRemoteIngest:
    def test_two_pods_stream_all_batches_over_rpc(self):
        """Every batch from two 'pods' (separate spawn processes,
        gRPC transport) arrives intact through the training host's
        ring; throughput is recorded as a sanity number."""
        ingest = BatchIngestServer(
            name=f"ing{uuid.uuid4().hex[:6]}",
            num_slots=4,
            slot_bytes=1 << 16,
        ).start()
        ctx = mp.get_context("spawn")
        job = os.environ["DLROVER_TPU_JOB_NAME"]
        pods = [
            ctx.Process(
                target=_pod_main, args=(ingest.addr, w, job)
            )
            for w in range(2)
        ]
        try:
            t0 = time.time()
            for p in pods:
                p.start()
            got = list(ingest.batches(expected_pods=2, timeout=120))
            dt = time.time() - t0
            assert len(got) == 12  # 2 pods x 6 batches
            # payload integrity: every (pod, i) constant block arrived
            seen = sorted(float(b["x"][0, 0]) for b in got)
            want = sorted(
                float(p * 100 + i) for p in range(2) for i in range(6)
            )
            assert seen == want
            # throughput sanity (includes pod spawn + jax-free import)
            print(f"remote ingest: {len(got) / dt:.1f} batches/s")
            assert len(got) / dt > 0.5
            for p in pods:
                p.join(timeout=30)
                assert p.exitcode == 0
        finally:
            for p in pods:
                if p.is_alive():
                    p.terminate()
            ingest.stop()

    def test_backpressure_blocks_producer_not_loses_batches(self):
        """A tiny ring (1 slot) forces accepted=False acks; the pod
        backs off and retries — nothing is dropped."""
        ingest = BatchIngestServer(
            name=f"ing{uuid.uuid4().hex[:6]}",
            num_slots=1,
            slot_bytes=1 << 16,
            put_timeout=0.05,
        ).start()
        ctx = mp.get_context("spawn")
        job = os.environ["DLROVER_TPU_JOB_NAME"]
        pod = ctx.Process(target=_pod_main, args=(ingest.addr, 0, job))
        try:
            pod.start()
            got = []
            for batch in ingest.batches(expected_pods=1, timeout=120):
                got.append(batch)
                time.sleep(0.1)  # slow consumer
            assert len(got) == 6
            assert ingest._rejected > 0  # backpressure actually fired
            pod.join(timeout=30)
            assert pod.exitcode == 0
        finally:
            if pod.is_alive():
                pod.terminate()
            ingest.stop()

    def test_failed_pod_error_end_terminates_stream(self):
        """A pod whose preprocessing raises reports an error-end; the
        consumer must treat that as the end of the pod's stream (no
        one respawns remote pods here) instead of hanging forever."""
        ingest = BatchIngestServer(
            name=f"ing{uuid.uuid4().hex[:6]}",
            num_slots=4,
            slot_bytes=1 << 16,
        ).start()
        ctx = mp.get_context("spawn")
        job = os.environ["DLROVER_TPU_JOB_NAME"]
        pod = ctx.Process(
            target=_failing_pod_main, args=(ingest.addr, 0, job)
        )
        try:
            pod.start()
            got = list(ingest.batches(expected_pods=1, timeout=60))
            assert len(got) == 1  # the batch before the crash arrived
            pod.join(timeout=30)
        finally:
            if pod.is_alive():
                pod.terminate()
            ingest.stop()

    def test_killed_pod_recovered_by_heartbeat_watchdog(self):
        """Registered coworker pods heartbeat as DATA_WORKER nodes:
        the master's watchdog DELETEs a silently-dead pod and
        recovers its doing-shards via the node-death path — no need
        to wait out the (much longer) shard timeout."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.agent.sharding_client import (
            IndexShardingClient,
        )
        from dlrover_tpu.common.constants import (
            NodeType,
            data_worker_node_id,
        )
        from dlrover_tpu.master.master import JobMaster

        master = JobMaster(
            port=0, node_num=1, rdzv_timeout=2.0,
            # 8x the 1 s beat cadence: a loaded single-core CI machine
            # can starve a pod's beat thread for seconds — a falsely
            # killed LIVE pod is harmless for at-least-once but fails
            # the exitcode assert below.
            heartbeat_timeout=8.0, monitor_interval=1.0,
        )
        master.prepare()
        # shard timeout deliberately huge: only the heartbeat path
        # can recover within the test budget
        master.task_manager.shard_timeout = 3600.0
        ingest = BatchIngestServer(
            name=f"ing{uuid.uuid4().hex[:6]}",
            num_slots=8,
            slot_bytes=1 << 16,
        ).start()
        ctx = mp.get_context("spawn")
        job = os.environ["DLROVER_TPU_JOB_NAME"]
        try:
            setup = IndexShardingClient(
                "ds", batch_size=4,
                client=MasterClient(master.addr, node_id=0),
            )
            setup.create_dataset(
                dataset_size=32, batch_size=4,
                num_minibatches_per_shard=2,
            )
            pods = {
                0: ctx.Process(
                    target=_sharded_pod_main,
                    args=(ingest.addr, master.addr, 0, job, 0.0),
                ),
                1: ctx.Process(
                    target=_sharded_pod_main,
                    args=(ingest.addr, master.addr, 1, job, 0.5),
                ),
            }
            for p in pods.values():
                p.start()
            node1 = data_worker_node_id(1)
            # both pods registered as data workers
            deadline = time.time() + 60
            while time.time() < deadline:
                nodes = master.job_manager.list_nodes(
                    NodeType.DATA_WORKER
                )
                if len(nodes) >= 2:
                    break
                time.sleep(0.5)
            assert any(n.id == node1 for n in nodes)

            seen = []
            it = ingest.batches(expected_pods=2, timeout=120)
            killed = False
            while True:
                try:
                    batch = next(it)
                except StopIteration:
                    break
                seen.extend(batch["idx"].tolist())
                if not killed and len(seen) >= 8:
                    os.kill(pods[1].pid, signal.SIGKILL)
                    pods[1].join(timeout=10)
                    killed = True
                    ingest.ring.put_control({"end": 1})
            assert killed
            assert set(range(32)) <= set(seen)
            pods[0].join(timeout=30)
            assert pods[0].exitcode == 0
        finally:
            for p in pods.values():
                if p.is_alive():
                    p.terminate()
            ingest.stop()
            master.stop()

    @pytest.mark.slow
    def test_chaos_killed_pod_shard_redispatched_by_master(self):
        """The elastic story end to end: two pods pull index shards
        from a REAL master's dynamic sharding service and stream over
        RPC; one pod is SIGKILLed mid-stream; the master's timeout
        watchdog re-dispatches its in-flight shard, the surviving pod
        drains the dataset, and every sample index arrives at least
        once."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.agent.sharding_client import (
            IndexShardingClient,
        )
        from dlrover_tpu.master.master import JobMaster

        master = JobMaster(port=0, node_num=1, rdzv_timeout=2.0)
        master.prepare()
        # tight shard-timeout so the kill's doing-shard re-dispatches
        # within the test budget (watchdog ticks every 15 s)
        master.task_manager.shard_timeout = 5.0
        ingest = BatchIngestServer(
            name=f"ing{uuid.uuid4().hex[:6]}",
            num_slots=8,
            slot_bytes=1 << 16,
        ).start()
        ctx = mp.get_context("spawn")
        job = os.environ["DLROVER_TPU_JOB_NAME"]
        try:
            setup = IndexShardingClient(
                "ds", batch_size=4,
                client=MasterClient(master.addr, node_id=0),
            )
            setup.create_dataset(
                dataset_size=48, batch_size=4,
                num_minibatches_per_shard=2,
            )
            # pod 1 is slow, guaranteeing it holds an in-flight shard
            # when killed
            pods = {
                0: ctx.Process(
                    target=_sharded_pod_main,
                    args=(ingest.addr, master.addr, 0, job, 0.0),
                ),
                1: ctx.Process(
                    target=_sharded_pod_main,
                    args=(ingest.addr, master.addr, 1, job, 0.5),
                ),
            }
            for p in pods.values():
                p.start()

            seen = []
            it = ingest.batches(expected_pods=2, timeout=180)
            killed = False
            while True:
                try:
                    batch = next(it)
                except StopIteration:
                    break
                seen.extend(batch["idx"].tolist())
                if not killed and len(seen) >= 8:
                    os.kill(pods[1].pid, signal.SIGKILL)
                    pods[1].join(timeout=10)
                    killed = True
                    # a SIGKILLed pod sends nothing at all: stand in
                    # for its pod-supervisor and close its stream (an
                    # error-end would do the same via
                    # error_ends_stream)
                    ingest.ring.put_control({"end": 1})
            assert killed
            # at-least-once: every index delivered despite the kill
            assert set(range(48)) <= set(seen)
            pods[0].join(timeout=30)
            assert pods[0].exitcode == 0
        finally:
            for p in pods.values():
                if p.is_alive():
                    p.terminate()
            ingest.stop()
            master.stop()
