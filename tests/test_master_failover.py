"""Control-plane survivability: master warm restart, reconnecting
agents, chaos-injected RPC drills.

Covers the contract that a master death costs seconds of goodput, not
the job:

* state-store snapshots (atomic, generation-numbered, torn-write
  fallback) and the in-process JobMaster warm-restart round trip;
* task-ledger / servicer idempotence against replayed reports after
  an agent reconnect;
* the MasterClient connection supervisor (transient-vs-fatal
  classification, decorrelated backoff under the outage budget,
  reconnect re-registration) and the fixed ``retry()`` decorator;
* chaos injector determinism (same seed -> same fault schedule);
* the hermetic kill+restart drill (real master subprocess, SIGKILL
  mid-sharded-run, outage held longer than the legacy 3-retry
  window, exactly-once shard accounting, ``master.warm_restart`` in
  the recovery timeline);
* the clock-source AST audit: no ``time.time()`` in duration/deadline
  arithmetic under ``dlrover_tpu/{master,agent}/`` outside the
  explicit cross-process-timestamp allowlist.
"""

import ast
import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

from dlrover_tpu.common import chaos  # noqa: E402
from dlrover_tpu.common import messages as msg  # noqa: E402
from dlrover_tpu.common.comm import RpcError  # noqa: E402
from dlrover_tpu.agent.master_client import (  # noqa: E402
    ConnectionSupervisor,
    MasterClient,
    MasterOutageError,
    is_transient_rpc_error,
    retry,
)
from dlrover_tpu.master.master import JobMaster  # noqa: E402
from dlrover_tpu.master.state_store import (  # noqa: E402
    MasterStateStore,
    StateJournal,
)
from dlrover_tpu.master.task_manager import TaskManager  # noqa: E402


@pytest.fixture(autouse=True)
def _no_chaos_leak():
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# State store


class TestStateStore:
    def test_save_load_roundtrip_and_prune(self, tmp_path):
        store = MasterStateStore(str(tmp_path), keep=2)
        for i in range(4):
            store.save({"i": i})
        doc = store.load_latest()
        assert doc["state"] == {"i": 3}
        assert doc["seq"] == 4
        files = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
        assert len(files) == 2  # pruned to keep=2

    def test_torn_newest_falls_back_to_previous(self, tmp_path):
        store = MasterStateStore(str(tmp_path))
        store.save({"good": True})
        # A torn write from the master being SIGKILLed mid-dump.
        with open(tmp_path / "master_state-99.json", "w") as f:
            f.write('{"schema_version": 1, "state": {"tru')
        doc = store.load_latest()
        assert doc is not None
        assert doc["state"] == {"good": True}

    def test_unknown_schema_skipped(self, tmp_path):
        store = MasterStateStore(str(tmp_path))
        with open(tmp_path / "master_state-5.json", "w") as f:
            json.dump({"schema_version": 999, "state": {}}, f)
        assert store.load_latest() is None

    def test_journal_debounce_and_timer(self, tmp_path):
        writes = []
        journal = StateJournal(
            MasterStateStore(str(tmp_path)),
            lambda: {"n": len(writes)},
            min_interval=0.05,
            timer_interval=0.2,
        )
        journal.start()
        try:
            for _ in range(50):
                journal.mark_dirty()
            deadline = time.monotonic() + 5
            while journal.writes == 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert journal.writes >= 1
            # A burst of marks must not produce a write per mark.
            assert journal.writes < 10
        finally:
            journal.stop()
        assert journal.store.load_latest() is not None


# ---------------------------------------------------------------------------
# In-process warm restart


class TestWarmRestart:
    def _populated_master(self, state_dir):
        m = JobMaster(
            port=0, node_num=2, rdzv_timeout=1.0,
            state_dir=str(state_dir),
        )
        m.prepare()
        m.job_manager.register_node(node_id=0)
        m.job_manager.register_node(node_id=1)
        m.kv_store.set("coordinator/train/0/0", b"h0:1234")
        m.task_manager.create_dataset("ds", dataset_size=16, shard_size=4)
        task = m.task_manager.get_task(0, "ds")
        assert task.shard is not None
        m.elastic_rdzv.join(0, 4)
        m.elastic_rdzv.join(1, 4)
        m.elastic_rdzv.get_comm_world(0)  # freezes the world
        m.speed_monitor.collect_global_step(7, time.time(), tokens=64)
        return m, task

    def test_round_trip_restores_everything(self, tmp_path):
        m1, task = self._populated_master(tmp_path)
        round1 = m1.elastic_rdzv.round
        m1.stop()  # final flush

        m2 = JobMaster(
            port=0, node_num=2, rdzv_timeout=1.0,
            state_dir=str(tmp_path),
        )
        from dlrover_tpu import obs

        tracer = obs.configure_tracer()
        try:
            m2.prepare()
            assert m2.warm_restarted
            names = [e["name"] for e in tracer.events()]
            assert "master.warm_restart" in names
        finally:
            obs.disable_tracer()
        try:
            # Node table: both nodes back, RUNNING, with a fresh
            # heartbeat (not instantly timed out).
            nodes = {n.id: n for n in m2.job_manager.list_nodes()}
            assert set(nodes) == {0, 1}
            assert nodes[0].status == "running"
            assert nodes[0].heartbeat_time > 0
            # KV store: the JAX bootstrap key survived.
            assert m2.kv_store.get("coordinator/train/0/0") == b"h0:1234"
            # Rendezvous: same round, frozen world intact.
            assert m2.elastic_rdzv.round == round1
            _, _, world = m2.elastic_rdzv.get_comm_world(0)
            assert world == {0: 4, 1: 4}
            # Shard ledger: the in-flight shard is still DOING and
            # still owned by node 0 — not re-queued, not lost.
            ck = json.loads(m2.task_manager.get_shard_checkpoint("ds"))
            doing = {t["task_id"]: t for t in ck["doing"]}
            assert task.task_id in doing
            assert doing[task.task_id]["node_id"] == 0
            # Speed monitor progress.
            assert m2.speed_monitor.global_step == 7
        finally:
            m2.stop()

    def test_doing_shard_not_double_processed(self, tmp_path):
        """The exactly-once core: after a warm restart, the original
        owner's completion report must retire the in-flight shard; a
        second worker must never receive it."""
        m1, task = self._populated_master(tmp_path)
        m1.stop()
        m2 = JobMaster(
            port=0, node_num=2, rdzv_timeout=1.0,
            state_dir=str(tmp_path),
        )
        m2.prepare()
        try:
            # Owner reports the shard it held across the outage.
            m2.task_manager.report_task_result(
                "ds", task.task_id, True, node_id=0
            )
            # Drain the rest of the epoch; the held shard's range
            # must not come back.
            spans = [(task.shard.start, task.shard.end)]
            for node in (0, 1, 0, 1, 0, 1):
                t = m2.task_manager.get_task(node, "ds")
                if t.shard is None:
                    break
                spans.append((t.shard.start, t.shard.end))
                m2.task_manager.report_task_result(
                    "ds", t.task_id, True, node_id=node
                )
            seen = sorted(spans)
            flat = [r for s, e in seen for r in range(s, e)]
            assert sorted(flat) == list(range(16))  # exactly once
        finally:
            m2.stop()

    def test_urgent_mark_skips_debounce(self, tmp_path):
        """Completion acks flush at write latency, not the debounce:
        a journal with a long min_interval still writes promptly on
        an urgent mark."""
        journal = StateJournal(
            MasterStateStore(str(tmp_path)),
            lambda: {"x": 1},
            min_interval=30.0,
            timer_interval=30.0,
        )
        journal.start()
        try:
            journal.mark_dirty(urgent=True)
            deadline = time.monotonic() + 5
            while journal.writes == 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert journal.writes >= 1
        finally:
            journal.stop(final_flush=False)

    def test_failed_restore_resets_to_true_cold_start(self, tmp_path):
        """A snapshot that fails restore half-way must not leave a
        mixed state: every component resets (no node table without
        its kv bootstrap keys)."""
        from dlrover_tpu.master.job_manager import JobManager

        jm = JobManager()
        jm.register_node(node_id=0)
        store = MasterStateStore(str(tmp_path))
        store.save({
            "job_manager": jm.to_snapshot(),
            "elastic_rdzv": {"round": 3},
            "check_rdzv": {},
            "task_manager": {},
            # kv_store restores AFTER job_manager and rendezvous:
            # poison it so the restore dies half-way through.
            "kv_store": {"key": 12345},  # not base64 text
            "speed_monitor": {},
        })

        m = JobMaster(
            port=0, node_num=2, rdzv_timeout=1.0,
            state_dir=str(tmp_path),
        )
        m.prepare()
        try:
            assert not m.warm_restarted
            assert m.job_manager.list_nodes() == []
            assert m.elastic_rdzv.round == 0
        finally:
            m.stop()

    def test_cold_start_without_snapshot(self, tmp_path):
        m = JobMaster(
            port=0, node_num=1, rdzv_timeout=1.0,
            state_dir=str(tmp_path / "empty"),
        )
        m.prepare()
        try:
            assert not m.warm_restarted
        finally:
            m.stop()

    def test_trainer_resume_folds_doing_into_todo(self):
        """The OTHER restore path (trainer-driven shard-checkpoint
        restore of a fresh job) must keep its legacy semantics: the
        checkpoint's doing-owners are gone, so their shards re-queue
        immediately."""
        tm1 = TaskManager()
        tm1.create_dataset("ds", dataset_size=8, shard_size=4)
        t = tm1.get_task(3, "ds")
        content = tm1.get_shard_checkpoint("ds")

        tm2 = TaskManager()
        tm2.create_dataset("ds", dataset_size=8, shard_size=4)
        assert tm2.restore_shard_checkpoint("ds", content)
        # Both shards (incl. the one node 3 was doing) dispatchable.
        spans = []
        for _ in range(2):
            task = tm2.get_task(9, "ds")
            assert task.shard is not None
            spans.append((task.shard.start, task.shard.end))
        assert (t.shard.start, t.shard.end) in spans


# ---------------------------------------------------------------------------
# Idempotence against replayed reports


class TestLedgerIdempotence:
    def _manager(self):
        tm = TaskManager()
        tm.create_dataset("ds", dataset_size=12, shard_size=4)
        return tm

    def test_duplicate_success_report_noop(self):
        tm = self._manager()
        t = tm.get_task(0, "ds")
        tm.report_task_result("ds", t.task_id, True, node_id=0)
        # The retried RPC lands again after a reconnect.
        tm.report_task_result("ds", t.task_id, True, node_id=0)
        spans = set()
        while True:
            task = tm.get_task(0, "ds")
            if task.shard is None:
                break
            spans.add((task.shard.start, task.shard.end))
            tm.report_task_result("ds", task.task_id, True, node_id=0)
        assert (t.shard.start, t.shard.end) not in spans
        assert len(spans) == 2

    def test_stale_failure_replay_cannot_steal_reassigned_shard(self):
        tm = self._manager()
        t = tm.get_task(0, "ds")
        # Node 0 dies; its shard re-queues and node 1 picks it up.
        tm.recover_node_tasks(0)
        t2 = tm.get_task(1, "ds")
        assert (t2.shard.start, t2.shard.end) == (
            t.shard.start, t.shard.end
        )
        # Node 0's delayed failure report replays after reconnect: it
        # must neither re-queue the shard (double dispatch) nor yank
        # it from node 1.
        tm.report_task_result("ds", t2.task_id, False, node_id=0)
        ck = json.loads(tm.get_shard_checkpoint("ds"))
        doing = {d["task_id"]: d for d in ck["doing"]}
        assert doing[t2.task_id]["node_id"] == 1
        # And node 1 can still complete it.
        tm.report_task_result("ds", t2.task_id, True, node_id=1)
        ck = json.loads(tm.get_shard_checkpoint("ds"))
        assert t2.task_id not in {d["task_id"] for d in ck["doing"]}

    def test_stale_success_from_old_owner_ignored(self):
        tm = self._manager()
        t = tm.get_task(0, "ds")
        tm.recover_node_tasks(0)
        t2 = tm.get_task(1, "ds")
        # Old owner claims success for work node 1 now owns: a lie we
        # cannot verify — the shard stays with node 1.
        tm.report_task_result("ds", t2.task_id, True, node_id=0)
        ck = json.loads(tm.get_shard_checkpoint("ds"))
        assert t2.task_id in {d["task_id"] for d in ck["doing"]}

    def test_duplicate_failure_report_single_relaunch(self):
        """Servicer-level: a replayed NodeFailureReport after an agent
        reconnect must not double-relaunch or double-count."""
        m = JobMaster(port=0, node_num=2, rdzv_timeout=1.0)
        m.prepare()
        try:
            m.job_manager.register_node(node_id=0)
            m.job_manager.register_node(node_id=1)
            first = m.servicer._report_failure(
                msg.NodeFailureReport(
                    node_id=1, error_data="oom", level="process_error"
                )
            )
            assert first.action == "relaunch_node"
            plans = len(m.job_manager.scaler.executed_plans)
            replay = m.servicer._report_failure(
                msg.NodeFailureReport(
                    node_id=1, error_data="oom", level="process_error"
                )
            )
            # Same verdict, no second relaunch, budget not re-spent.
            assert replay.action == "relaunch_node"
            assert len(m.job_manager.scaler.executed_plans) == plans
            assert m.job_manager.get_node(1).relaunch_count == 1
        finally:
            m.stop()


# ---------------------------------------------------------------------------
# retry() and the connection supervisor


class TestRetryDecorator:
    def test_no_sleep_after_final_attempt(self, monkeypatch):
        sleeps = []
        monkeypatch.setattr(time, "sleep", sleeps.append)

        calls = []

        @retry(times=3, interval=1.0)
        def boom():
            calls.append(1)
            raise RpcError("nope")

        with pytest.raises(RpcError):
            boom()
        assert len(calls) == 3
        # The fix: 2 sleeps between 3 attempts, none after the last.
        assert len(sleeps) == 2

    def test_sleeps_are_jittered_within_bounds(self, monkeypatch):
        sleeps = []
        monkeypatch.setattr(time, "sleep", sleeps.append)

        @retry(times=3, interval=1.0)
        def boom():
            raise RpcError("nope")

        with pytest.raises(RpcError):
            boom()
        for i, s in enumerate(sleeps, start=1):
            assert 0.5 * i <= s <= 1.5 * i

    def test_outage_error_not_re_retried(self, monkeypatch):
        sleeps = []
        monkeypatch.setattr(time, "sleep", sleeps.append)
        calls = []

        @retry(times=3)
        def budget_spent():
            calls.append(1)
            raise MasterOutageError("budget gone")

        with pytest.raises(MasterOutageError):
            budget_spent()
        assert len(calls) == 1
        assert not sleeps


class _FakeGrpcError(Exception):
    def __init__(self, code):
        self._code = code

    def code(self):
        return self._code


class TestErrorClassification:
    def test_transient_kinds(self):
        import grpc

        # Make the fake quack like grpc.RpcError for isinstance.
        class Fake(_FakeGrpcError, grpc.RpcError):
            pass

        assert is_transient_rpc_error(
            Fake(grpc.StatusCode.UNAVAILABLE)
        )
        assert is_transient_rpc_error(
            Fake(grpc.StatusCode.DEADLINE_EXCEEDED)
        )
        assert not is_transient_rpc_error(
            Fake(grpc.StatusCode.INVALID_ARGUMENT)
        )
        assert is_transient_rpc_error(chaos.ChaosDropError("x"))
        assert is_transient_rpc_error(ConnectionResetError())
        # Server answered: a handler bug, not an outage.
        assert not is_transient_rpc_error(RpcError("handler failed"))
        assert not is_transient_rpc_error(MasterOutageError("x"))


class TestConnectionSupervisor:
    def _supervisor(self, budget=5.0, sleeps=None):
        return ConnectionSupervisor(
            outage_budget=budget,
            backoff_base=0.01,
            backoff_cap=0.05,
            sleep=(sleeps.append if sleeps is not None else (lambda s: None)),
        )

    def test_rides_out_transient_failures(self):
        sleeps = []
        sup = self._supervisor(sleeps=sleeps)
        recon = []
        sup.on_reconnect.append(lambda: recon.append(1))
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] < 4:
                raise ConnectionError("master down")
            return 42

        assert sup.call(flaky, what="test") == 42
        assert state["n"] == 4
        assert len(sleeps) == 3
        assert sup.outages == 1
        assert sup.reconnects == 1
        assert recon == [1]  # fired exactly once per outage

    def test_budget_exhaustion_raises_outage_error(self):
        sup = ConnectionSupervisor(
            outage_budget=0.15, backoff_base=0.01, backoff_cap=0.03
        )

        def always_down():
            raise ConnectionError("master down")

        t0 = time.monotonic()
        with pytest.raises(MasterOutageError):
            sup.call(always_down, what="test")
        assert time.monotonic() - t0 >= 0.1

    def test_max_wait_caps_a_single_call(self):
        """A failure report must not pin its caller (which has a dead
        trainer to restart) to the whole outage budget."""
        sup = ConnectionSupervisor(
            outage_budget=60.0, backoff_base=0.01, backoff_cap=0.03
        )

        def always_down():
            raise ConnectionError("master down")

        t0 = time.monotonic()
        with pytest.raises(MasterOutageError):
            sup.call(always_down, what="test", max_wait=0.2)
        assert time.monotonic() - t0 < 5.0

    def test_fatal_error_propagates_immediately(self):
        sleeps = []
        sup = self._supervisor(sleeps=sleeps)
        with pytest.raises(RpcError):
            sup.call(lambda: (_ for _ in ()).throw(RpcError("bug")),
                     what="test")
        assert not sleeps
        assert sup.outages == 0

    def test_backoff_is_decorrelated_and_capped(self):
        sleeps = []
        sup = self._supervisor(budget=60.0, sleeps=sleeps)
        state = {"n": 0}

        def down_then_up():
            state["n"] += 1
            if state["n"] <= 30:
                raise ConnectionError("down")
            return 1

        sup.call(down_then_up, what="test")
        assert all(0.0 < s <= 0.05 for s in sleeps)
        # Jittered: not all identical.
        assert len(set(round(s, 6) for s in sleeps)) > 1

    def test_client_reregisters_after_reconnect(self):
        """End-to-end against a real master: drop the connection
        state mid-session, verify the client re-announces itself."""
        m = JobMaster(port=0, node_num=1, rdzv_timeout=1.0)
        m.prepare()
        client = None
        try:
            client = MasterClient(m.addr, node_id=0)
            client.supervisor.backoff_base = 0.05
            client.register_node()
            # Simulate an outage having been observed: the next
            # successful SUPERVISED call must re-register.
            client.supervisor._outage_since = time.monotonic()
            assert client.kv_get("nope") is None
            assert client.supervisor.reconnects == 1
            # The node is (still) known to the master.
            assert m.job_manager.get_node(0) is not None
            # The heartbeat path recovers OUTSIDE the supervisor (its
            # loop owns per-tick failure metrics): the explicit hook
            # re-registers idempotently.
            client.notify_master_recovered()
            assert m.job_manager.get_node(0).status == "running"
        finally:
            if client is not None:
                client.close()
            m.stop()


# ---------------------------------------------------------------------------
# Chaos injector


class TestChaosInjector:
    def test_same_seed_same_schedule(self):
        def schedule(seed):
            inj = chaos.ChaosInjector(
                seed=seed, drop_rate=0.3, error_rate=0.1,
                latency_ms=2.0, node_id=0,
            )
            return [inj.decide("get") for _ in range(300)]

        assert schedule(42) == schedule(42)
        assert schedule(42) != schedule(43)

    def test_rates_zero_and_one(self):
        inj = chaos.ChaosInjector(seed=1, drop_rate=0.0, node_id=0)
        assert all(
            inj.decide("get")[0] == "pass" for _ in range(50)
        )
        inj = chaos.ChaosInjector(seed=1, drop_rate=1.0, node_id=0)
        with pytest.raises(chaos.ChaosDropError):
            inj.before_client_call("get", object())

    def test_partition_node_always_cut(self):
        inj = chaos.ChaosInjector(
            seed=1, partition_nodes=(3,), node_id=3
        )
        with pytest.raises(chaos.ChaosPartitionError):
            inj.before_client_call("report", object())
        # Other nodes pass.
        inj2 = chaos.ChaosInjector(
            seed=1, partition_nodes=(3,), node_id=0
        )
        inj2.before_client_call("report", object())

    def test_from_env_parsing(self):
        env = {
            "DLROVER_TPU_CHAOS_SEED": "9",
            "DLROVER_TPU_CHAOS_DROP_RATE": "0.25",
            "DLROVER_TPU_CHAOS_LATENCY_MS": "7",
            "DLROVER_TPU_CHAOS_PARTITION_NODES": "1, 2",
            "DLROVER_TPU_CHAOS_KILL_AT": "TaskRequest:3",
        }
        inj = chaos.ChaosInjector.from_env(env)
        assert inj.seed == 9
        assert inj.drop_rate == 0.25
        assert inj.latency_ms == 7.0
        assert inj.partition_nodes == frozenset((1, 2))
        assert inj.kill_at == ("TaskRequest", 3)

    def test_env_gate_off_by_default(self, monkeypatch):
        monkeypatch.delenv("DLROVER_TPU_CHAOS", raising=False)
        chaos.reset()
        assert chaos.get_injector() is None


# ---------------------------------------------------------------------------
# The hermetic master-failover drill (acceptance)


def _import_chaos_drill():
    if TOOLS not in sys.path:
        sys.path.insert(0, TOOLS)
    import chaos_drill

    return chaos_drill


class TestMasterFailoverDrill:
    def test_kill_restart_drill_survives_long_outage(self):
        """Real master subprocess SIGKILLed mid-sharded-run with the
        outage held open for 8s (> the legacy 3-retry ~6s window):
        the agent reconnects, re-registers, no shard is processed
        twice, and the replacement master warm-restarts (the
        master.warm_restart event anchors the recovery timeline).
        run_drill raises on any contract violation."""
        cd = _import_chaos_drill()
        report = cd.run_drill(
            seed=11,
            total_records=48,
            batch_size=4,
            kill_after_tasks=3,
            drop_rate=0.05,
            latency_ms=1.0,
            down_seconds=8.0,
            reconnect_budget=90.0,
        )
        assert report["warm_restart_events"] >= 1
        assert report["reconnects"] >= 1
        # Outage (kill -> serving replacement) is bounded: held 8s on
        # purpose, recovered well inside the reconnect budget.
        assert 8.0 <= report["outage_s"] < 45.0
        assert report["shards_processed"] == 12

    def test_chaos_drill_selftest_smoke(self):
        """The CI smoke the tier-1 set runs: seeded, hermetic, fast."""
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(TOOLS, "chaos_drill.py"),
                "--selftest",
            ],
            capture_output=True,
            text=True,
            timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "chaos drill selftest ok" in proc.stdout


# ---------------------------------------------------------------------------
# Clock-source audit


class _TimeTimeVisitor(ast.NodeVisitor):
    def __init__(self):
        self.stack = []
        self.hits = []

    def _visit_func(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node):
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "time"
            and isinstance(f.value, ast.Name)
            and f.value.id == "time"
        ):
            self.hits.append(
                (node.lineno, self.stack[-1] if self.stack else "<module>")
            )
        self.generic_visit(node)


class TestClockSourceAudit:
    """``time.time()`` under dlrover_tpu/{master,agent}/ is forbidden
    outside this allowlist of genuine cross-process wall timestamps
    (report/event ``ts`` fields exchanged over RPC or files). Every
    duration or deadline must use ``time.monotonic()`` — an NTP step
    fired a HangDetector false positive once (PR 4); the same bug
    class lived in kv waits, rendezvous timers, and the heartbeat
    sweep."""

    ALLOWED = {
        # Wall timestamps attached to RPC payloads / event streams
        # that cross process boundaries:
        ("dlrover_tpu/master/servicer.py", "_report_step"),
        ("dlrover_tpu/master/servicer.py", "_report_failure"),
        ("dlrover_tpu/master/servicer.py", "_report_diagnostics"),
        ("dlrover_tpu/master/metrics.py", "snapshot"),
        ("dlrover_tpu/master/ps_manager.py", "check_liveness"),
        ("dlrover_tpu/master/master.py", "_on_node_event"),
        ("dlrover_tpu/master/master.py", "_maybe_warm_restart"),
        ("dlrover_tpu/master/speed_monitor.py", "collect_node_step"),
        ("dlrover_tpu/master/speed_monitor.py", "remove_running_node"),
        ("dlrover_tpu/master/state_store.py", "save"),
        # Rendezvous-round trace spans anchor on wall time (the
        # trace store's timelines are cross-process artifacts; the
        # round's TIMER math stays monotonic — see
        # _start_rdzv_time).
        ("dlrover_tpu/master/rendezvous.py", "join"),
        ("dlrover_tpu/master/rendezvous.py", "_try_complete"),
        ("dlrover_tpu/agent/monitor.py", "write_metrics"),
        ("dlrover_tpu/agent/monitor.py", "mark_phase"),
        ("dlrover_tpu/agent/master_client.py", "heartbeat"),
        ("dlrover_tpu/agent/master_client.py", "report_step"),
        ("dlrover_tpu/agent/master_client.py", "report_metrics_snapshot"),
        ("dlrover_tpu/agent/master_client.py", "report_diagnostics"),
    }

    def _scan(self):
        sites = []
        for sub in ("master", "agent"):
            root = os.path.join(REPO, "dlrover_tpu", sub)
            for dirpath, _, files in os.walk(root):
                if "__pycache__" in dirpath:
                    continue
                for fname in files:
                    if not fname.endswith(".py"):
                        continue
                    fpath = os.path.join(dirpath, fname)
                    with open(fpath, encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=fpath)
                    visitor = _TimeTimeVisitor()
                    visitor.visit(tree)
                    rel = os.path.relpath(fpath, REPO)
                    for lineno, func in visitor.hits:
                        sites.append((rel, func, lineno))
        return sites

    def test_no_wall_clock_outside_allowlist(self):
        sites = self._scan()
        # Sanity: the walker sees the allowlisted cross-process
        # timestamp sites; zero hits means it broke.
        assert len(sites) >= 5, sites
        violations = [
            f"{rel}:{lineno} in {func}() uses time.time() — use "
            "time.monotonic() for durations/deadlines, or add a "
            "cross-process-timestamp allowlist entry"
            for rel, func, lineno in sites
            if (rel, func) not in self.ALLOWED
        ]
        assert not violations, "\n".join(violations)

    def test_allowlist_has_no_stale_entries(self):
        live = {(rel, func) for rel, func, _ in self._scan()}
        stale = sorted(e for e in self.ALLOWED if e not in live)
        assert not stale, (
            f"allowlist entries no longer present (prune them): {stale}"
        )


# ---------------------------------------------------------------------------
# Chaos kill-at wiring (server side)


class TestChaosKillAt:
    def test_kill_at_counts_per_message_type(self):
        inj = chaos.ChaosInjector(
            seed=0, kill_at=("TaskRequest", 2), node_id=0
        )
        # Do not actually exit the test process.
        inj_exit = []

        real_exit = os._exit
        try:
            os._exit = lambda code: inj_exit.append(code)
            inj.on_server_request(msg.TaskRequest())
            assert not inj_exit
            inj.on_server_request(msg.HeartbeatRequest())
            assert not inj_exit  # other types don't count
            inj.on_server_request(msg.TaskRequest())
            assert inj_exit == [chaos.KILL_EXIT_CODE]
        finally:
            os._exit = real_exit
