"""Tests for common primitives: node model, messages, RPC transport."""

import threading

import pytest

from dlrover_tpu.common import messages
from dlrover_tpu.common.comm import (
    RpcClient,
    RpcDispatcher,
    RpcError,
    RpcServer,
)
from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.node import Node, NodeResource


class TestNode:
    def test_status_transitions(self):
        node = Node(type=NodeType.WORKER, id=0)
        assert node.status == NodeStatus.INITIAL
        assert node.update_status(NodeStatus.PENDING)
        assert node.update_status(NodeStatus.RUNNING)
        assert node.start_time > 0
        # Illegal: RUNNING -> PENDING
        assert not node.update_status(NodeStatus.PENDING)
        assert node.update_status(NodeStatus.FAILED)
        assert node.finish_time > 0

    def test_relaunch_policy(self):
        node = Node(type=NodeType.WORKER, id=1, max_relaunch_count=2)
        node.exit_reason = "oom"
        assert node.should_relaunch()
        node.inc_relaunch_count()
        node.inc_relaunch_count()
        assert not node.should_relaunch()
        node2 = Node(type=NodeType.WORKER, id=2)
        node2.exit_reason = "fatal_error"
        assert not node2.should_relaunch()

    def test_roundtrip_dict(self):
        node = Node(
            type=NodeType.WORKER,
            id=3,
            rank=1,
            config_resource=NodeResource(cpu=4, chips=4, tpu_type="v5p"),
        )
        node2 = Node.from_dict(node.to_dict())
        assert node2.id == 3
        assert node2.config_resource.chips == 4


class TestMessages:
    def test_roundtrip_nested(self):
        req = messages.Task(
            task_id=7,
            task_type="training",
            shard=messages.Shard(name="ds", start=10, end=20),
        )
        out = messages.deserialize(messages.serialize(req))
        assert isinstance(out, messages.Task)
        assert out.shard.end == 20

    def test_unknown_fields_dropped(self):
        d = messages.encode_to_dict(messages.TaskRequest(node_id=1))
        d["future_field"] = 123
        out = messages.decode_from_dict(d)
        assert out.node_id == 1

    def test_dict_payload(self):
        resp = messages.CommWorldResponse(round=2, world={0: 4, 1: 4})
        out = messages.deserialize(messages.serialize(resp))
        assert out.world == {0: 4, 1: 4}

    def test_unknown_type_raises(self):
        with pytest.raises(ValueError):
            messages.decode_from_dict({"_t": "Nonexistent"})


class TestRpc:
    def test_get_report_roundtrip(self):
        dispatcher = RpcDispatcher()
        seen = []

        def handle_task(req: messages.TaskRequest):
            return messages.Task(task_id=42, task_type="training")

        def handle_step(req: messages.StepReport):
            seen.append(req.step)
            return None

        dispatcher.register_get(messages.TaskRequest, handle_task)
        dispatcher.register_report(messages.StepReport, handle_step)
        server = RpcServer(dispatcher, port=0)
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            task = client.get(messages.TaskRequest(node_id=0))
            assert task.task_id == 42
            client.report(messages.StepReport(node_id=0, step=5))
            assert seen == [5]
            # Unhandled type surfaces as RpcError, not a crash.
            with pytest.raises(RpcError):
                client.get(messages.KVStoreGetRequest(key="x"))
            client.close()
        finally:
            server.stop(0)

    def test_concurrent_clients(self):
        dispatcher = RpcDispatcher()
        lock = threading.Lock()
        counter = {"n": 0}

        def handle_add(req: messages.KVStoreAddRequest):
            with lock:
                counter["n"] += req.amount
                return messages.KVStoreAddResponse(value=counter["n"])

        dispatcher.register_get(messages.KVStoreAddRequest, handle_add)
        server = RpcServer(dispatcher, port=0)
        server.start()
        try:
            client = RpcClient(f"127.0.0.1:{server.port}")
            threads = [
                threading.Thread(
                    target=lambda: client.get(
                        messages.KVStoreAddRequest(key="c", amount=1)
                    )
                )
                for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert counter["n"] == 8
            client.close()
        finally:
            server.stop(0)


class TestLogging:
    """Role/rank-aware log format + opt-in JSON-lines mode."""

    def _record(self, msg="hello"):
        import logging

        return logging.LogRecord(
            "dlrover_tpu.test", logging.INFO, "f.py", 42, msg, (), None
        )

    def test_text_format_carries_role_and_rank(self, monkeypatch):
        from dlrover_tpu.common import log as log_mod

        monkeypatch.setenv("DLROVER_TPU_ROLE", "worker")
        monkeypatch.setenv("JAX_PROCESS_INDEX", "3")
        out = log_mod._make_formatter().format(self._record())
        assert "[worker/3]" in out
        assert "hello" in out

    def test_text_format_without_env_uses_placeholder(self, monkeypatch):
        from dlrover_tpu.common import log as log_mod

        for var in ("DLROVER_TPU_ROLE", "JAX_PROCESS_INDEX",
                    "DLROVER_TPU_NODE_RANK", "DLROVER_TPU_LOG_JSON"):
            monkeypatch.delenv(var, raising=False)
        out = log_mod._make_formatter().format(self._record())
        assert "[-]" in out

    def test_json_mode_emits_machine_readable_lines(self, monkeypatch):
        import json as json_mod

        from dlrover_tpu.common import log as log_mod

        monkeypatch.setenv("DLROVER_TPU_LOG_JSON", "1")
        monkeypatch.setenv("DLROVER_TPU_ROLE", "evaluator")
        monkeypatch.setenv("DLROVER_TPU_NODE_RANK", "1")
        monkeypatch.delenv("JAX_PROCESS_INDEX", raising=False)
        rec = json_mod.loads(
            log_mod._make_formatter().format(self._record("json msg"))
        )
        assert rec["msg"] == "json msg"
        assert rec["role"] == "evaluator"
        assert rec["rank"] == 1
        assert rec["level"] == "INFO"
        assert rec["logger"] == "dlrover_tpu.test"
        assert rec["line"] == 42

    def test_reconfigure_switches_live_handlers(self, monkeypatch):
        from dlrover_tpu.common import log as log_mod

        monkeypatch.setenv("DLROVER_TPU_LOG_JSON", "1")
        log_mod.reconfigure()
        try:
            fmts = [
                type(h.formatter).__name__
                for h in log_mod.default_logger.handlers
            ]
            assert fmts == ["_JsonFormatter"]
        finally:
            monkeypatch.delenv("DLROVER_TPU_LOG_JSON")
            log_mod.reconfigure()
        fmts = [
            type(h.formatter).__name__
            for h in log_mod.default_logger.handlers
        ]
        assert fmts == ["_TextFormatter"]
