"""Static TPU tiling-floor audit of every Pallas kernel (VERDICT r4
weak #2, the (1, E) lesson).

Interpret-mode CPU tests execute kernels without enforcing Mosaic's
tiling constraints — the round-3 fused-norm backward shipped three
rounds of green tests while uncompilable on real TPU because its
dg/db partials used (1, E) blocks, below the 8-sublane f32 floor
(docs/ROOFLINE.md epilogue). Real-chip compilation
(tools/tpu_kernel_smoke.py) is the ground truth, but the tunnel is
not always there; this audit catches the same bug CLASS offline by
intercepting ``pl.pallas_call`` and checking every BlockSpec against
the floors that bit us:

* second-minor (sublane) block dim: unless it spans the full array
  dim, it must be a positive multiple of the dtype's sublane tile
  (f32: 8, bf16: 16, int8/fp8: 32) — the (1, E) bug and the
  "unloweable 23-row block" case;
* minor (lane) block dim: unless it spans the full array dim, a
  multiple of 128.

The audit drives each public kernel entry (forward AND backward, f32
and bf16) at the same shape families the on-chip smoke uses, plus the
known-awkward shapes (odd sequence lengths, short suffixes).
"""

import contextlib
import functools

import jax
import jax.numpy as jnp
import pytest
from jax.experimental import pallas as pl

from tests.test_flash_attention import _rand_qkv


def _sublane_floor(dtype) -> int:
    itemsize = jnp.dtype(dtype).itemsize
    return {4: 8, 2: 16, 1: 32}.get(itemsize, 8)


def _check_block(name, block_shape, full_shape, dtype, violations):
    if block_shape is None or len(full_shape) < 2:
        return
    bs = tuple(block_shape)
    if len(bs) < 2:
        return
    sub, minor = bs[-2], bs[-1]
    fsub, fminor = full_shape[-2], full_shape[-1]
    floor = _sublane_floor(dtype)
    if sub is not None and sub != fsub and (sub < 1 or sub % floor):
        violations.append(
            f"{name}: sublane block dim {sub} (full {fsub}, "
            f"{jnp.dtype(dtype).name}) not a multiple of {floor}"
        )
    if minor is not None and minor != fminor and minor % 128:
        violations.append(
            f"{name}: lane block dim {minor} (full {fminor}) not a "
            "multiple of 128"
        )


@contextlib.contextmanager
def record_violations():
    """Patch pl.pallas_call to audit every BlockSpec against the
    arrays actually passed at call time. Yields the violation list;
    its ``.audited`` attribute counts inspected BlockSpecs so tests
    can assert the interception actually fired (a silently-broken
    patch would otherwise pass everything)."""

    class _Violations(list):
        audited = 0

    violations = _Violations()
    orig = pl.pallas_call

    def patched(kernel, **kw):
        inner = orig(kernel, **kw)
        in_specs = kw.get("in_specs")
        if "grid_spec" in kw and in_specs is None:
            # Specs carried inside a grid_spec object are invisible to
            # this audit; fail loudly so the audit is extended rather
            # than silently skipping the kernel (the failure mode this
            # file exists to prevent).
            violations.append(
                "pallas_call used grid_spec=...; the tiling audit "
                "cannot see its BlockSpecs — extend record_violations"
            )
        kname = getattr(kernel, "__name__", str(kernel))
        # functools.partial kernels: name of the wrapped fn.
        if isinstance(kernel, functools.partial):
            kname = getattr(kernel.func, "__name__", kname)

        def call(*args):
            if in_specs is not None:
                flat_specs = jax.tree.leaves(
                    in_specs,
                    is_leaf=lambda s: s is None
                    or isinstance(s, pl.BlockSpec),
                )
                flat_args = list(args)
                for i, (spec, arg) in enumerate(
                    zip(flat_specs, flat_args)
                ):
                    if not isinstance(spec, pl.BlockSpec):
                        continue
                    violations.audited += 1
                    _check_block(
                        f"{kname}[in{i}]", spec.block_shape,
                        arg.shape, arg.dtype, violations,
                    )
            out_shape = kw.get("out_shape")
            out_specs = kw.get("out_specs")
            if out_specs is not None and out_shape is not None:
                flat_out = jax.tree.leaves(
                    out_specs,
                    is_leaf=lambda s: s is None
                    or isinstance(s, pl.BlockSpec),
                )
                flat_shapes = jax.tree.leaves(
                    out_shape,
                    is_leaf=lambda s: hasattr(s, "shape"),
                )
                for i, (spec, sds) in enumerate(
                    zip(flat_out, flat_shapes)
                ):
                    if not isinstance(spec, pl.BlockSpec):
                        continue
                    violations.audited += 1
                    _check_block(
                        f"{kname}[out{i}]", spec.block_shape,
                        sds.shape, sds.dtype, violations,
                    )
            return inner(*args)

        return call

    pl.pallas_call = patched
    try:
        yield violations
    finally:
        pl.pallas_call = orig


def _qkv(b, t, h, d, dtype):
    # Shared fixture from the flash tests; cast AFTER generation so
    # f32 and bf16 runs audit the same value distribution.
    return tuple(
        x.astype(dtype)
        for x in _rand_qkv(jax.random.PRNGKey(0), b, t, h, d)
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("t", [256, 520])
def test_flash_square_fwd_bwd_blocks(dtype, t):
    from dlrover_tpu.ops.flash_attention import flash_attention

    q, k, v = _qkv(1, t, 2, 64, dtype)
    with record_violations() as viol:
        def loss(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=True, interpret=True
                ).astype(jnp.float32) ** 2
            )

        jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert not viol, "\n".join(viol)
    assert viol.audited > 0, "pallas_call interception never fired"


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("tq,tk,off", [(23, 96, 0), (32, 160, 128)])
def test_flash_rect_fwd_bwd_blocks(dtype, tq, tk, off):
    from dlrover_tpu.ops.flash_attention import flash_attention_rect

    q = _qkv(1, tq, 2, 64, dtype)[0]
    _, k, v = _qkv(1, tk, 2, 64, dtype)
    with record_violations() as viol:
        def loss(q, k, v):
            return jnp.sum(
                flash_attention_rect(
                    q, k, v, causal=True, q_offset=off,
                    interpret=True,
                ).astype(jnp.float32) ** 2
            )

        jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert not viol, "\n".join(viol)
    assert viol.audited > 0, "pallas_call interception never fired"


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_flash_windowed_blocks(dtype):
    from dlrover_tpu.ops.flash_attention import flash_attention

    q, k, v = _qkv(1, 512, 2, 64, dtype)
    with record_violations() as viol:
        def loss(q, k, v):
            return jnp.sum(
                flash_attention(
                    q, k, v, causal=True, window=100, interpret=True
                ).astype(jnp.float32) ** 2
            )

        jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert not viol, "\n".join(viol)
    assert viol.audited > 0, "pallas_call interception never fired"


def test_prefix_lm_blocks():
    from dlrover_tpu.ops.prefix_lm import prefix_lm_attention

    q, k, v = _qkv(1, 128, 2, 64, jnp.float32)
    with record_violations() as viol:
        def loss(q, k, v):
            return jnp.sum(
                prefix_lm_attention(
                    q, k, v, prefix_len=37, interpret=True
                ) ** 2
            )

        jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert not viol, "\n".join(viol)
    assert viol.audited > 0, "pallas_call interception never fired"


@pytest.mark.parametrize("e", [768, 1024])
def test_fused_norm_blocks(e):
    """The kernel family that carried the actual r4 bug: its dg/db
    accumulator blocks must stay at the (8, E) fix, never (1, E)."""
    from dlrover_tpu.ops.layer_norm import fused_layer_norm

    x = jax.random.normal(jax.random.PRNGKey(1), (64, e))
    g = jnp.ones((e,))
    b = jnp.zeros((e,))
    with record_violations() as viol:
        def loss(x, g, b):
            return jnp.sum(
                fused_layer_norm(x, g, b, interpret=True) ** 2
            )

        jax.grad(loss, argnums=(0, 1, 2))(x, g, b)
    assert not viol, "\n".join(viol)
    assert viol.audited > 0, "pallas_call interception never fired"


def test_quantization_blocks():
    from dlrover_tpu.ops.quantization import (
        dequantize_blockwise,
        dequantize_blockwise_4bit,
        quantize_blockwise,
        quantize_blockwise_4bit,
    )

    x = jax.random.normal(jax.random.PRNGKey(2), (4096,))
    with record_violations() as viol:
        qv, scale, shape = quantize_blockwise(x)
        dequantize_blockwise(qv, scale, shape)
        q4, s4, shape4 = quantize_blockwise_4bit(x)
        dequantize_blockwise_4bit(q4, s4, shape4)
    assert not viol, "\n".join(viol)
    assert viol.audited > 0, "pallas_call interception never fired"


def test_audit_catches_the_r4_bug_shape():
    """Meta-test: the recorder must actually flag the (1, E) block
    that slipped through three rounds of interpret-green tests."""
    viol: list = []
    _check_block(
        "dg_db[out0]", (1, 768), (16384, 768), jnp.float32, viol
    )
    assert viol and "sublane block dim 1" in viol[0]
    # ... and accept the (8, E) fix.
    ok: list = []
    _check_block(
        "dg_db[out0]", (8, 768), (16384, 768), jnp.float32, ok
    )
    assert not ok
