"""Persistent autotune/trial cache: round-trip, corruption tolerance,
hit/miss accounting, and key stability (the tier-1 selftest the CI
satellite of the tune-cache PR wires in — fast, jax-free)."""

import json
import os

import pytest

from dlrover_tpu.accelerate import tune_cache as tc
from dlrover_tpu.common.runmeta import trial_fingerprint
from dlrover_tpu.obs.metrics import get_registry


@pytest.fixture()
def cache(tmp_path):
    return tc.TuneCache(str(tmp_path / "cache.jsonl"))


class TestRoundTrip:
    def test_record_and_trials(self, cache):
        cache.record("k1", {"pins": {"A": 1}}, 100.0)
        cache.record("k1", {"pins": {"A": 2}}, 120.0,
                     extra={"compile_s": 3.2})
        cache.record("k2", "other-key-config", 5.0)
        t1 = cache.trials("k1")
        assert [t["throughput"] for t in t1] == [100.0, 120.0]
        assert t1[1]["extra"] == {"compile_s": 3.2}
        assert [t["key"] for t in cache.trials()] == ["k1", "k1", "k2"]

    def test_best_ignores_failed_and_newest_wins_ties(self, cache):
        cache.record("k", {"pins": {}}, None, failed=True)
        assert cache.best("k") is None  # only a failed trial
        cache.record("k", {"pins": {"A": 1}}, 50.0)
        cache.record("k", {"pins": {"A": 2}}, 50.0)  # tie, newer
        cache.record("k", {"pins": {"A": 3}}, 10.0)
        best = cache.best("k")
        assert best["config"]["pins"] == {"A": 2}

    def test_failed_marker_from_none_throughput(self, cache):
        rec = cache.record("k", "cfg", None)
        assert rec["failed"] is True and rec["throughput"] is None

    def test_unwritable_path_degrades_without_raising(self, tmp_path):
        bad = tc.TuneCache(str(tmp_path))  # a directory: open() fails
        assert bad.record("k", "cfg", 1.0) is None

    def test_unserializable_config_degrades(self, cache):
        assert cache.record("k", object(), 1.0) is None
        assert cache.trials("k") == []


class TestCorruptionTolerance:
    def test_corrupt_and_alien_lines_skipped(self, cache):
        cache.record("k", "good1", 1.0)
        with open(cache.path, "a") as f:
            f.write('{"torn": \n')  # half-written line
            f.write("[1, 2, 3]\n")  # not an object
            f.write('{"no_key_field": true}\n')  # alien record
            f.write("\n")
        cache.record("k", "good2", 2.0)
        assert [t["config"] for t in cache.trials("k")] == [
            "good1", "good2",
        ]

    def test_missing_file_is_empty(self, tmp_path):
        c = tc.TuneCache(str(tmp_path / "nope.jsonl"))
        assert c.trials("k") == []
        assert c.best("k") is None


class TestResolveAndMetrics:
    def test_resolve_semantics(self, tmp_path, monkeypatch):
        p = str(tmp_path / "c.jsonl")
        assert tc.resolve(False) is None
        assert tc.resolve(p).path == p
        c = tc.TuneCache(p)
        assert tc.resolve(c) is c
        monkeypatch.setenv(tc.ENV_PATH, p)
        assert tc.resolve(None).path == p
        for off in ("0", "off", "OFF", "none"):
            monkeypatch.setenv(tc.ENV_PATH, off)
            assert tc.resolve(None) is None
        monkeypatch.delenv(tc.ENV_PATH)
        assert tc.resolve(None).path == tc.default_path()

    def test_lookup_counts_hits_and_misses(self, cache):
        reg = get_registry()
        hits = reg.get("dlrover_tune_cache_hits_total")
        misses = reg.get("dlrover_tune_cache_misses_total")
        h0, m0 = hits.value(), misses.value()
        assert cache.lookup("k") == []
        assert misses.value() == m0 + 1 and hits.value() == h0
        cache.record("k", "cfg", 1.0)
        assert len(cache.lookup("k")) == 1
        assert hits.value() == h0 + 1 and misses.value() == m0 + 1


class TestTrialFingerprint:
    def test_order_insensitive_and_value_sensitive(self):
        a = trial_fingerprint({"x": 1, "y": [2, 3], "z": "s"})
        b = trial_fingerprint({"z": "s", "y": [2, 3], "x": 1})
        assert a == b and len(a) == 16
        assert a != trial_fingerprint({"x": 1, "y": [2, 4], "z": "s"})

    def test_non_json_values_stringified_stably(self):
        class Weird:
            def __str__(self):
                return "weird"

        assert trial_fingerprint({"d": Weird()}) == trial_fingerprint(
            {"d": "weird"}
        )


def test_records_are_single_lines_of_json(cache):
    """The O_APPEND single-line contract concurrent writers rely on."""
    cache.record("k", {"pins": {"A": "1"}}, 1.0)
    cache.record("k2", "c", None, failed=True)
    with open(cache.path) as f:
        lines = f.read().splitlines()
    assert len(lines) == 2
    for line in lines:
        json.loads(line)  # each line parses standalone


def test_env_disable_is_honored_by_consumers(tmp_path, monkeypatch):
    monkeypatch.setenv(tc.ENV_PATH, "0")
    assert tc.cache_disabled()
    assert tc.resolve() is None
