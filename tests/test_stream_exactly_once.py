"""Streaming exactly-once: replay fence, barrier-consistent state,
and the chaos PS-plane scope.

Strategy mirrors test_ps_elastic.py (real in-process PS RPC servers +
a real PsManager) and test_master_failover.py (real JobMaster round
trips through a MasterStateStore): the fence/ledger contracts are
asserted against the real wire path, not mocks, and the acceptance
soak (``tools/stream_soak.py``) rides as a subprocess smoke.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from dlrover_tpu.common import chaos
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.ps_manager import PsManager
from dlrover_tpu.sparse.ps_client import DistributedKvClient
from dlrover_tpu.sparse.ps_server import PsServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

DIM = 4
DIMS = {"emb": DIM}


def _start_ps(node_id, tmp_path, num_partitions=16):
    ps = PsServer(
        node_id=node_id,
        checkpoint_dir=str(tmp_path / "sparse_ckpt"),
        embedding_dims=DIMS,
        num_partitions=num_partitions,
        seed=node_id * 100,
    )
    ps.start()
    return ps


def _row_counts(server, partitions):
    """key -> apply count under all-ones SGD at lr=1.0 (init noise is
    ±0.05, far below the 0.5 rounding boundary)."""
    dump = server._dump_table("emb", list(partitions), 0, False)
    if dump.keys is None:
        return {}
    keys = dump.keys.to_numpy()
    values = dump.values.to_numpy().reshape(keys.size, DIM)
    return {
        int(k): int(round(-float(row.mean())))
        for k, row in zip(keys, values)
    }


class TestReplayFenceRestore:
    """A replayed apply window must be absorbed exactly once by a
    fleet where some partitions survived (fence dedup) and some were
    restored from the barrier flush (re-absorb)."""

    def test_replay_after_ps_kill_is_exactly_once(self, tmp_path):
        mgr = PsManager(num_partitions=16)
        servers = {}
        try:
            for i in (0, 1):
                servers[i] = _start_ps(i, tmp_path)
                mgr.register_ps(i, servers[i].addr)
            client = DistributedKvClient(
                lambda: mgr.partition_map, DIMS,
                retry_interval=0.05, client_id=0,
            )
            client.epoch = 1
            # Six disjoint batches of 8 keys, one fence seq each.
            batches = [
                np.arange(i * 8, (i + 1) * 8, dtype=np.int64)
                for i in range(6)
            ]
            ones = np.ones((8, DIM), np.float32)
            replay_log = []
            for step, keys in enumerate(batches[:4], start=1):
                seq = client.apply_gradients(
                    "emb", keys, ones, step=step,
                    optimizer="sgd", lr=1.0,
                )
                replay_log.append((seq, keys, step))
            # Barrier cut: flush stamped with epoch + ledger HWM;
            # every partition's fence file records the cut.
            mgr.flush_all(step=4, epoch=1, hwm={"0": 32})
            for step, keys in enumerate(batches[4:], start=5):
                seq = client.apply_gradients(
                    "emb", keys, ones, step=step,
                    optimizer="sgd", lr=1.0,
                )
                replay_log.append((seq, keys, step))

            # SIGKILL-equivalent: PS 0 dies with its post-barrier
            # applies unflushed; the survivor restores its partitions
            # from the barrier-cut delta files.
            servers[0].stop()
            mgr.remove_ps(0)

            # The trainer's failover replay: the whole post-barrier
            # window, original fence seqs. Survivor partitions dedup,
            # restored partitions re-absorb.
            for seq, keys, step in replay_log:
                client.apply_gradients(
                    "emb", keys, ones, step=step,
                    optimizer="sgd", lr=1.0, apply_seq=seq,
                )
            counts = _row_counts(servers[1], range(16))
            expected = {int(k): 1 for b in batches for k in b}
            assert counts == expected
            client.close()
        finally:
            for ps in servers.values():
                ps.stop()

    def test_stale_epoch_apply_is_rejected(self, tmp_path):
        mgr = PsManager(num_partitions=16)
        server = _start_ps(0, tmp_path)
        try:
            mgr.register_ps(0, server.addr)
            mgr.flush_all(step=1, epoch=3, hwm={})
            assert server.fence_epoch == 3
            rpc = RpcClient(server.addr)
            try:
                with pytest.raises(Exception, match="fence epoch"):
                    rpc.get(msg.PsApplyRequest(
                        table="emb",
                        optimizer="sgd",
                        keys=msg.Tensor.from_numpy(
                            np.arange(4, dtype=np.int64)
                        ),
                        grads=msg.Tensor.from_numpy(
                            np.ones((4, DIM), np.float32)
                        ),
                        step=9,
                        lr=1.0,
                        map_version=mgr.partition_map.version,
                        epoch=2,  # pre-restore zombie writer
                        client_id=0,
                        apply_seq=99,
                    ))
            finally:
                rpc.close()
            # Unfenced applies (client_id < 0) stay untouched by the
            # epoch fence — the non-streaming sparse path must not
            # start failing once a stream barrier has ever run.
            rpc = RpcClient(server.addr)
            try:
                rpc.get(msg.PsApplyRequest(
                    table="emb",
                    optimizer="sgd",
                    keys=msg.Tensor.from_numpy(
                        np.arange(4, dtype=np.int64)
                    ),
                    grads=msg.Tensor.from_numpy(
                        np.ones((4, DIM), np.float32)
                    ),
                    step=9,
                    lr=1.0,
                    map_version=mgr.partition_map.version,
                ))
            finally:
                rpc.close()
        finally:
            server.stop()

    def test_fence_rides_partition_moves(self, tmp_path):
        """A live PS-to-PS rebalance must carry the fence state with
        the rows: after partitions move, a replayed seq is still a
        duplicate on the new owner."""
        mgr = PsManager(num_partitions=16)
        servers = {0: _start_ps(0, tmp_path)}
        try:
            mgr.register_ps(0, servers[0].addr)
            client = DistributedKvClient(
                lambda: mgr.partition_map, DIMS,
                retry_interval=0.05, client_id=0,
            )
            client.epoch = 1
            keys = np.arange(32, dtype=np.int64)
            seq = client.apply_gradients(
                "emb", keys, np.ones((32, DIM), np.float32),
                step=1, optimizer="sgd", lr=1.0,
            )
            # Scale up: half the partitions move PS-to-PS (freeze ->
            # pull -> publish), dumps carrying part_seqs/fence_epoch.
            servers[1] = _start_ps(1, tmp_path)
            mgr.register_ps(1, servers[1].addr)
            client.apply_gradients(
                "emb", keys, np.ones((32, DIM), np.float32),
                step=1, optimizer="sgd", lr=1.0, apply_seq=seq,
            )
            counts = {}
            for ps_id, server in servers.items():
                counts.update(_row_counts(
                    server, mgr.partition_map.partitions_of(ps_id)
                ))
            assert counts == {int(k): 1 for k in keys}
            client.close()
        finally:
            for ps in servers.values():
                ps.stop()


class TestStreamingLedgerWarmRestart:
    """The streaming shard ledger — per-partition offsets, completion
    watermarks, barrier records, and the PS partition map — survives a
    real JobMaster bounce through the MasterStateStore journal."""

    def _master(self, state_dir):
        m = JobMaster(
            port=0, node_num=2, rdzv_timeout=1.0,
            state_dir=str(state_dir),
        )
        m.prepare()
        return m

    def test_round_trip_preserves_stream_state(self, tmp_path):
        m1 = self._master(tmp_path)
        try:
            m1.task_manager.create_dataset(
                "stream", dataset_size=24, shard_size=4,
                storage_type="streaming", num_stream_partitions=2,
            )
            # The PS partition map is recoverable state too: a master
            # bounce must not forget which PS owns which partitions.
            m1.ps_manager.register_ps(0, "127.0.0.1:1")
            map_version = m1.ps_manager.partition_map.version
            dispatched = []
            for _ in range(3):
                t = m1.task_manager.get_task(0, "stream")
                dispatched.append(t)
            # Complete out of order: t3 parks beyond the t2 gap, so
            # one partition's watermark must NOT advance past t2.
            m1.task_manager.report_task_result(
                "stream", dispatched[0].task_id, True, node_id=0
            )
            m1.task_manager.report_task_result(
                "stream", dispatched[2].task_id, True, node_id=0
            )
            barrier = m1.task_manager.record_barrier(
                "stream", epoch=1, step=3,
                flush_gen=7, flushed_rows=42,
            )
            frontier = m1.task_manager.ledger_watermarks("stream")
        finally:
            m1.stop()  # final journal flush

        m2 = self._master(tmp_path)
        try:
            assert m2.warm_restarted
            # Barrier record restored atomically with the ledger.
            rec = m2.task_manager.last_barrier("stream")
            assert rec is not None
            assert rec["epoch"] == 1
            assert rec["flush_gen"] == 7
            assert rec["flushed_rows"] == 42
            assert rec["watermarks"] == barrier["watermarks"]
            # Frontier (offsets + parked watermark gap) restored.
            assert (
                m2.task_manager.ledger_watermarks("stream") == frontier
            )
            # PS partition map adopted, not re-derived.
            pmap = m2.ps_manager.partition_map
            assert pmap.version == map_version
            assert pmap.ps_addrs == {0: "127.0.0.1:1"}

            # The in-flight shard is still owned by node 0; draining
            # the stream covers every record exactly once.
            seen = []
            for t in dispatched:
                seen.extend(t.shard.record_indices)
            m2.task_manager.report_task_result(
                "stream", dispatched[1].task_id, True, node_id=0
            )
            while True:
                t = m2.task_manager.get_task(0, "stream")
                if t.shard is None:
                    break
                seen.extend(t.shard.record_indices)
                m2.task_manager.report_task_result(
                    "stream", t.task_id, True, node_id=0
                )
            assert sorted(seen) == list(range(24))
            assert (
                m2.task_manager.ledger_watermarks("stream")["records"]
                == 24
            )
        finally:
            m2.stop()


class TestChaosScope:
    """DLROVER_TPU_CHAOS_SCOPE narrows client-side faults to one RPC
    plane without disturbing the seeded schedule."""

    def test_ps_scope_spares_the_control_plane(self):
        inj = chaos.ChaosInjector(
            seed=3, drop_rate=1.0, node_id=0, scope="ps"
        )
        # Master-plane request: the draw happens, the fault does not.
        inj.before_client_call("get", msg.TaskRequest())
        with pytest.raises(chaos.ChaosDropError):
            inj.before_client_call("get", msg.PsStatsRequest())

    def test_master_scope_spares_the_ps_plane(self):
        inj = chaos.ChaosInjector(
            seed=3, drop_rate=1.0, node_id=0, scope="master"
        )
        inj.before_client_call("get", msg.PsStatsRequest())
        with pytest.raises(chaos.ChaosDropError):
            inj.before_client_call("get", msg.TaskRequest())

    def test_scoping_does_not_shift_the_schedule(self):
        """Same seed => identical per-index decisions whether or not
        a scope filters some of them out: the decision log (the
        drills' replay key) must not depend on the scope."""
        def decisions(scope):
            inj = chaos.ChaosInjector(
                seed=42, drop_rate=0.3, node_id=0, scope=scope
            )
            reqs = [msg.TaskRequest(), msg.PsStatsRequest()] * 50
            for req in reqs:
                try:
                    inj.before_client_call("get", req)
                except chaos.ChaosDropError:
                    pass
            return list(inj.decisions)

        assert decisions("all") == decisions("ps")

    def test_from_env_and_validation(self):
        inj = chaos.ChaosInjector.from_env(
            {"DLROVER_TPU_CHAOS_SCOPE": "ps"}
        )
        assert inj.scope == "ps"
        assert chaos.ChaosInjector.from_env({}).scope == "all"
        with pytest.raises(ValueError):
            chaos.ChaosInjector(scope="workers")


class TestStreamSoakSelftest:
    def test_stream_soak_selftest_smoke(self):
        """The acceptance drill the tier-1 set runs: real master + PS
        subprocesses, PS SIGKILL + master SIGKILL + rebalance, every
        record id applied exactly once."""
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(TOOLS, "stream_soak.py"),
                "--selftest",
            ],
            capture_output=True,
            text=True,
            timeout=180,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "stream soak selftest ok" in proc.stdout
