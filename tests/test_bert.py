"""BERT-family encoder: bidirectional backbone + MLM + classification.

Parity target: the reference trains HF BERT through auto_accelerate
with fused-attention module replacement
(/root/reference/atorch/atorch/auto/opt_lib/module_replace_optimization.py);
here the encoder is the native GPT backbone with causal=False
(models/bert.py) and the same kernels apply.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import bert, gpt

MASK_ID = 3


@pytest.fixture(scope="module")
def cfg():
    return bert.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return bert.init_params(jax.random.PRNGKey(0), cfg)


def test_encoder_is_bidirectional(cfg, params):
    """Changing a LATE token must change EARLY hidden states — the
    property a causal decoder cannot have."""
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (1, cfg.block_size), 8, cfg.vocab_size
    )
    changed = tokens.at[0, -1].set(4)
    h0 = gpt.backbone(params, tokens, cfg)
    h1 = gpt.backbone(params, changed, cfg)
    assert not np.allclose(
        np.asarray(h0[0, 0]), np.asarray(h1[0, 0]), atol=1e-6
    )
    # Sanity: the same probe on a causal config shows NO early change.
    import dataclasses

    causal_cfg = dataclasses.replace(cfg, causal=True)
    c0 = gpt.backbone(params, tokens, causal_cfg)
    c1 = gpt.backbone(params, changed, causal_cfg)
    np.testing.assert_allclose(
        np.asarray(c0[0, 0]), np.asarray(c1[0, 0]), atol=1e-6
    )


def test_mask_tokens_distribution(cfg):
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (64, 256), 8, cfg.vocab_size
    )
    corrupted, labels, w = bert.mask_tokens(
        jax.random.PRNGKey(3), tokens, cfg.vocab_size, MASK_ID
    )
    np.testing.assert_array_equal(np.asarray(labels), np.asarray(tokens))
    sel = np.asarray(w) > 0
    rate = sel.mean()
    assert 0.12 < rate < 0.18  # ~15%
    masked = np.asarray(corrupted)[sel]
    orig = np.asarray(tokens)[sel]
    frac_mask = (masked == MASK_ID).mean()
    frac_kept = (masked == orig).mean()
    assert 0.75 < frac_mask < 0.85  # ~80% [MASK]
    assert 0.07 < frac_kept < 0.14  # ~10% kept
    # Unselected positions are untouched.
    np.testing.assert_array_equal(
        np.asarray(corrupted)[~sel], np.asarray(tokens)[~sel]
    )


def test_mlm_training_decreases_loss(cfg):
    params = bert.init_params(jax.random.PRNGKey(4), cfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(5), (8, cfg.block_size), 8, cfg.vocab_size
    )
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, key):
        corrupted, labels, w = bert.mask_tokens(
            key, tokens, cfg.vocab_size, MASK_ID
        )
        loss, grads = jax.value_and_grad(
            lambda p: bert.mlm_loss_fn(p, corrupted, labels, w, cfg)
        )(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    key = jax.random.PRNGKey(6)
    losses = []
    for i in range(10):
        key, sub = jax.random.split(key)
        params, opt_state, loss = step(params, opt_state, sub)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_classifier_fine_tune_separable(cfg):
    """Two synthetic classes separable from token content: class =
    whether token 16 or 17 dominates the sequence."""
    n_classes = 2
    params = bert.init_classifier_params(
        jax.random.PRNGKey(7), cfg, n_classes
    )
    key = jax.random.PRNGKey(8)
    B = 16
    labels = jnp.arange(B) % 2
    fill = jnp.where(labels[:, None] == 0, 16, 17)
    noise = jax.random.randint(
        key, (B, cfg.block_size), 8, cfg.vocab_size
    )
    keep = jax.random.uniform(
        jax.random.PRNGKey(9), (B, cfg.block_size)
    ) < 0.5
    tokens = jnp.where(keep, fill, noise)

    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: bert.classifier_loss_fn(p, tokens, labels, cfg)
        )(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(15):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7
    preds = jnp.argmax(
        bert.classifier_logits(params, tokens, cfg), axis=-1
    )
    assert float(jnp.mean((preds == labels).astype(jnp.float32))) >= 0.9


def test_mlm_sharded_step(cfg):
    """MLM step on a data x tensor mesh with the shared logical-axis
    rules — the auto_accelerate compatibility proof."""
    from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
    from dlrover_tpu.trainer.step import make_sharded_init, shard_batch

    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    opt = optax.adamw(1e-3)
    init, _ = make_sharded_init(
        mesh,
        functools.partial(bert.init_params, cfg=cfg),
        bert.param_logical_axes(cfg),
        opt,
    )
    params, opt_state = init(jax.random.PRNGKey(10))
    tokens = jax.random.randint(
        jax.random.PRNGKey(11), (8, cfg.block_size), 8, cfg.vocab_size
    )
    corrupted, labels, w = bert.mask_tokens(
        jax.random.PRNGKey(12), tokens, cfg.vocab_size, MASK_ID
    )
    corrupted, labels = shard_batch(mesh, corrupted, labels)

    def loss_fn(p):
        return bert.mlm_loss_fn(p, corrupted, labels, w, cfg)

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(params)
    assert jnp.isfinite(loss)
    assert all(
        bool(jnp.all(jnp.isfinite(g))) for g in jax.tree.leaves(grads)
    )


def test_flash_and_plain_attention_agree_bidirectional(cfg, params):
    """The non-causal flash kernel path must match the XLA fallback on
    the encoder (the module-replace parity check, kernel-level)."""
    tokens = jax.random.randint(
        jax.random.PRNGKey(13), (2, cfg.block_size), 8, cfg.vocab_size
    )
    from dlrover_tpu.ops.flash_attention import flash_attention

    plain = gpt.forward(params, tokens, cfg)
    flash = gpt.forward(
        params, tokens, cfg,
        attn_fn=functools.partial(
            flash_attention, causal=False, interpret=True
        ),
    )
    np.testing.assert_allclose(
        np.asarray(plain), np.asarray(flash), atol=2e-4, rtol=2e-4
    )
