"""Monitors, metric collector, profiler."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.agent.monitor import (
    ResourceMonitor,
    TrainingMonitor,
    current_resource_stats,
)
from dlrover_tpu.master.job_manager import JobManager
from dlrover_tpu.master.metrics import (
    JobMetricCollector,
    JsonFileReporter,
)
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.utils.profiler import (
    profile_fn,
    summarize,
    transformer_component_flops,
)


class FakeClient:
    def __init__(self):
        self.resources = []
        self.steps = []

    def report_resource(self, **kw):
        self.resources.append(kw)

    def report_step(self, step, tokens=0):
        self.steps.append((step, tokens))


def test_resource_stats_sampled():
    stats = current_resource_stats()
    assert stats["memory_mb"] > 0  # psutil is available here


def test_resource_monitor_reports():
    client = FakeClient()
    mon = ResourceMonitor(client, interval=999)
    out = mon.report_once()
    assert client.resources and client.resources[0] == out


def test_training_monitor_relays_new_steps(tmp_path):
    path = str(tmp_path / "metrics.json")
    client = FakeClient()
    mon = TrainingMonitor(client, metrics_file=path, interval=999)
    assert mon.report_once() is None  # no file yet
    TrainingMonitor.write_metrics(5, tokens=1000, path=path)
    assert mon.report_once() == 5
    assert client.steps == [(5, 1000)]
    # same step again: not re-reported
    assert mon.report_once() is None
    TrainingMonitor.write_metrics(6, tokens=2000, path=path)
    assert mon.report_once() == 6


def test_metric_collector_snapshot(tmp_path):
    jm = JobManager()
    jm.register_node(node_id=0)
    jm.register_node(node_id=1)
    jm.handle_failure_report(1, "CUDA out of memory", "process_error", 0)
    sm = SpeedMonitor()
    path = str(tmp_path / "metrics.jsonl")
    coll = JobMetricCollector(
        "jobZ", jm, sm, reporters=[JsonFileReporter(path)], interval=999
    )
    snap = coll.collect_once()
    assert snap.workers_alive == 1
    assert snap.workers_pending == 1  # OOM replacement
    assert snap.failure_counts.get("oom") == 1
    with open(path) as f:
        on_disk = json.loads(f.readline())
    assert on_disk["job_name"] == "jobZ"


def test_profile_fn_costs_and_timing():
    def fn(x):
        return x @ x

    x = jnp.ones((256, 256), jnp.float32)
    prof = profile_fn(fn, x, iters=3)
    # 2*M*N*K flops for the matmul
    assert prof.flops == pytest.approx(2 * 256**3, rel=0.1)
    assert prof.wall_time_s > 0
    assert prof.arithmetic_intensity > 0
    assert "GFLOP" in summarize(prof, "matmul")


def test_transformer_component_flops_sums_to_model():
    from dlrover_tpu.models import gpt

    cfg = gpt.GPTConfig.nano()
    comp = transformer_component_flops(
        cfg.n_layer, cfg.n_embd, cfg.block_size, cfg.vocab_size
    )
    total_per_token = sum(comp.values()) / cfg.block_size
    model_estimate = gpt.flops_per_token(cfg)
    assert total_per_token == pytest.approx(model_estimate, rel=0.05)


def test_training_monitor_reports_token_deltas_and_restarts(tmp_path):
    """Cumulative token counts become per-report deltas; a restart at
    a lower step re-baselines instead of going silent."""
    path = str(tmp_path / "metrics.json")
    client = FakeClient()
    mon = TrainingMonitor(client, metrics_file=path, interval=999)
    TrainingMonitor.write_metrics(1, tokens=1000, path=path)
    mon.report_once()
    TrainingMonitor.write_metrics(2, tokens=2500, path=path)
    mon.report_once()
    assert client.steps == [(1, 1000), (2, 1500)]  # deltas
    # restart: resume at step 1 with fresh cumulative counter
    TrainingMonitor.write_metrics(1, tokens=800, path=path)
    assert mon.report_once() == 1
    assert client.steps[-1] == (1, 800)


def test_json_file_reporter_appends_and_failure_is_contained(tmp_path):
    """A JsonFileReporter writing to a dead path raises from report();
    collect_once must contain it (warn + keep going) and still feed
    every other reporter."""
    good_path = str(tmp_path / "metrics.jsonl")
    bad = JsonFileReporter(str(tmp_path / "no_such_dir" / "m.jsonl"))
    good = JsonFileReporter(good_path)
    jm = JobManager()
    jm.register_node(node_id=0)
    coll = JobMetricCollector(
        "jobF", jm, SpeedMonitor(),
        reporters=[bad, good], interval=999,
    )
    with pytest.raises(OSError):
        bad.report(coll.snapshot())  # the reporter itself raises...
    snap = coll.collect_once()  # ...but the collector survives it
    assert snap.workers_alive == 1
    # and the healthy reporter appended one line per collect
    coll.collect_once()
    with open(good_path) as f:
        lines = [json.loads(line) for line in f]
    assert len(lines) == 2
    assert all(rec["job_name"] == "jobF" for rec in lines)


def test_mark_phase_mirrors_to_obs_tracer(tmp_path, monkeypatch):
    """Phase marks feed the recovery-timeline reconstructor through
    the obs tracer, independent of the phases file."""
    from dlrover_tpu import obs
    from dlrover_tpu.obs.timeline import reconstruct_recovery_timeline

    monkeypatch.delenv("DLROVER_TPU_PHASES_FILE", raising=False)
    tracer = obs.configure_tracer()
    try:
        for mark in ("proc_start", "dist_ready", "built",
                     "restore_done", "first_step_done"):
            TrainingMonitor.mark_phase(mark)
        events = tracer.events()
        t_fail = events[0]["ts"] - 1.0
        tl = reconstruct_recovery_timeline(events, t_failure=t_fail)
        assert tl is not None and tl.complete
        assert tl.phases["failure-detect"] >= 1.0
    finally:
        obs.disable_tracer()
