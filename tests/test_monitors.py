"""Monitors, metric collector, profiler."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.agent.monitor import (
    ResourceMonitor,
    TrainingMonitor,
    current_resource_stats,
)
from dlrover_tpu.master.job_manager import JobManager
from dlrover_tpu.master.metrics import (
    JobMetricCollector,
    JsonFileReporter,
)
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.utils.profiler import (
    profile_fn,
    summarize,
    transformer_component_flops,
)


class FakeClient:
    def __init__(self):
        self.resources = []
        self.steps = []

    def report_resource(self, **kw):
        self.resources.append(kw)

    def report_step(self, step, tokens=0):
        self.steps.append((step, tokens))


def test_resource_stats_sampled():
    stats = current_resource_stats()
    assert stats["memory_mb"] > 0  # psutil is available here


def test_resource_monitor_reports():
    client = FakeClient()
    mon = ResourceMonitor(client, interval=999)
    out = mon.report_once()
    assert client.resources and client.resources[0] == out


def test_training_monitor_relays_new_steps(tmp_path):
    path = str(tmp_path / "metrics.json")
    client = FakeClient()
    mon = TrainingMonitor(client, metrics_file=path, interval=999)
    assert mon.report_once() is None  # no file yet
    TrainingMonitor.write_metrics(5, tokens=1000, path=path)
    assert mon.report_once() == 5
    assert client.steps == [(5, 1000)]
    # same step again: not re-reported
    assert mon.report_once() is None
    TrainingMonitor.write_metrics(6, tokens=2000, path=path)
    assert mon.report_once() == 6


def test_metric_collector_snapshot(tmp_path):
    jm = JobManager()
    jm.register_node(node_id=0)
    jm.register_node(node_id=1)
    jm.handle_failure_report(1, "CUDA out of memory", "process_error", 0)
    sm = SpeedMonitor()
    path = str(tmp_path / "metrics.jsonl")
    coll = JobMetricCollector(
        "jobZ", jm, sm, reporters=[JsonFileReporter(path)], interval=999
    )
    snap = coll.collect_once()
    assert snap.workers_alive == 1
    assert snap.workers_pending == 1  # OOM replacement
    assert snap.failure_counts.get("oom") == 1
    with open(path) as f:
        on_disk = json.loads(f.readline())
    assert on_disk["job_name"] == "jobZ"


def test_profile_fn_costs_and_timing():
    def fn(x):
        return x @ x

    x = jnp.ones((256, 256), jnp.float32)
    prof = profile_fn(fn, x, iters=3)
    # 2*M*N*K flops for the matmul
    assert prof.flops == pytest.approx(2 * 256**3, rel=0.1)
    assert prof.wall_time_s > 0
    assert prof.arithmetic_intensity > 0
    assert "GFLOP" in summarize(prof, "matmul")


def test_transformer_component_flops_sums_to_model():
    from dlrover_tpu.models import gpt

    cfg = gpt.GPTConfig.nano()
    comp = transformer_component_flops(
        cfg.n_layer, cfg.n_embd, cfg.block_size, cfg.vocab_size
    )
    total_per_token = sum(comp.values()) / cfg.block_size
    model_estimate = gpt.flops_per_token(cfg)
    assert total_per_token == pytest.approx(model_estimate, rel=0.05)


def test_training_monitor_reports_token_deltas_and_restarts(tmp_path):
    """Cumulative token counts become per-report deltas; a restart at
    a lower step re-baselines instead of going silent."""
    path = str(tmp_path / "metrics.json")
    client = FakeClient()
    mon = TrainingMonitor(client, metrics_file=path, interval=999)
    TrainingMonitor.write_metrics(1, tokens=1000, path=path)
    mon.report_once()
    TrainingMonitor.write_metrics(2, tokens=2500, path=path)
    mon.report_once()
    assert client.steps == [(1, 1000), (2, 1500)]  # deltas
    # restart: resume at step 1 with fresh cumulative counter
    TrainingMonitor.write_metrics(1, tokens=800, path=path)
    assert mon.report_once() == 1
    assert client.steps[-1] == (1, 800)


def test_json_file_reporter_appends_and_failure_is_contained(tmp_path):
    """A JsonFileReporter writing to a dead path raises from report();
    collect_once must contain it (warn + keep going) and still feed
    every other reporter."""
    good_path = str(tmp_path / "metrics.jsonl")
    bad = JsonFileReporter(str(tmp_path / "no_such_dir" / "m.jsonl"))
    good = JsonFileReporter(good_path)
    jm = JobManager()
    jm.register_node(node_id=0)
    coll = JobMetricCollector(
        "jobF", jm, SpeedMonitor(),
        reporters=[bad, good], interval=999,
    )
    with pytest.raises(OSError):
        bad.report(coll.snapshot())  # the reporter itself raises...
    snap = coll.collect_once()  # ...but the collector survives it
    assert snap.workers_alive == 1
    # and the healthy reporter appended one line per collect
    coll.collect_once()
    with open(good_path) as f:
        lines = [json.loads(line) for line in f]
    assert len(lines) == 2
    assert all(rec["job_name"] == "jobF" for rec in lines)


def test_recovery_seconds_uses_crossing_time_not_poll_time():
    """A late recovery_seconds() poll must report when the throughput
    window first regained 90% of pre-failure speed (the crossing
    sample's timestamp), not how long ago the poll happened."""
    import time as _time

    sm = SpeedMonitor(window=4)
    sm.add_running_node(0)
    sm.add_running_node(1)
    t0 = _time.time()
    for i in range(4):  # healthy: 100 tokens/s
        sm.collect_global_step(i, t0 + i, tokens=100)
    sm.remove_running_node(1)  # failure: snapshots 100 tok/s baseline
    assert sm._pre_failure_tput == pytest.approx(100.0)
    t_fail = sm._last_failure_time
    # Recovery happens "in the future" relative to the poll: samples
    # are stamped ~100s after the failure, crossing on the last one.
    base = t_fail + 100.0
    for i in range(4):  # limp along at 10 tokens/s
        sm.collect_global_step(10 + i, base + i, tokens=10)
    assert sm.recovery_seconds() is None  # not recovered yet
    for i in range(4):  # back to full speed
        sm.collect_global_step(20 + i, base + 4 + i, tokens=100)
    rec = sm.recovery_seconds()
    assert rec is not None
    # The crossing was recorded at a sample timestamp ~104-108s after
    # the failure; a poll-time answer would be ~0s here.
    assert 100.0 <= rec <= 110.0
    assert sm.recovery_seconds() == pytest.approx(rec)  # sticky


def test_remove_running_node_snapshot_is_single_lock():
    """The pre-failure throughput snapshot happens in the same lock
    acquisition as the failure bookkeeping, so it reflects the window
    at the failure instant (here: the healthy 100 tok/s window)."""
    sm = SpeedMonitor(window=4)
    sm.add_running_node(0)
    t = 1000.0
    for i in range(4):
        sm.collect_global_step(i, t + i, tokens=100)
    sm.remove_running_node(0)
    assert sm._pre_failure_tput == pytest.approx(100.0)
    # A node never marked running must not re-arm failure tracking.
    sm.reset_failure_tracking()
    sm.remove_running_node(99)
    assert sm._pre_failure_tput is None


def test_recovery_not_vouched_by_pre_failure_window():
    """A window still dominated by healthy pre-failure samples must
    not claim recovery the moment the first post-failure report
    lands — only post-failure samples vouch for the crossing."""
    import time as _time

    sm = SpeedMonitor(window=6)
    sm.add_running_node(0)
    t0 = _time.time()
    for i in range(6):  # full healthy window at 100 tok/s
        sm.collect_global_step(i, t0 + i, tokens=100)
    sm.remove_running_node(0)
    fail_t = sm._last_failure_time
    # One slow post-failure sample: the healthy samples still in the
    # deque would put the full-window tput way above 90%.
    sm.collect_global_step(20, fail_t + 30.0, tokens=10 * 30)
    assert sm.recovery_seconds() is None
    sm.collect_global_step(21, fail_t + 60.0, tokens=10 * 30)
    assert sm.recovery_seconds() is None  # post tput = 10/s, not 90
    # Ramp back up: not recovered until the post-failure window
    # itself sustains >= 90 tok/s (the slow samples must age out).
    for k, ts in enumerate((90.0, 120.0, 150.0, 180.0)):
        sm.collect_global_step(22 + k, fail_t + ts, tokens=100 * 30)
    assert sm.recovery_seconds() is None  # window still 82 tok/s
    sm.collect_global_step(26, fail_t + 210.0, tokens=100 * 30)
    rec = sm.recovery_seconds()
    assert rec == pytest.approx(210.0, abs=1.0)


def test_resource_monitor_trace_tail_defers_past_event_cap(
    tmp_path, monkeypatch
):
    """A burst larger than the per-snapshot cap is split across
    snapshots, never dropped."""
    trace = tmp_path / "trace.jsonl"
    monkeypatch.setenv("DLROVER_TPU_TRACE_FILE", str(trace))
    client = SnapshotFakeClient()
    mon = ResourceMonitor(
        client, interval=999, metrics_file=str(tmp_path / "m.json")
    )
    mon.MAX_EVENTS_PER_SNAPSHOT = 3
    with open(trace, "w") as f:
        for i in range(5):
            f.write(json.dumps({"name": f"e{i}", "ts": float(i)}) + "\n")
    mon.report_once()
    mon.report_once()
    got = [
        [e["name"] for e in s["events"]] for s in client.snapshots
    ]
    assert got == [["e0", "e1", "e2"], ["e3", "e4"]]


def test_resource_monitor_skips_pre_restart_trace_history(
    tmp_path, monkeypatch
):
    """A restarted agent must not re-ship (and double-count) the
    trace lines its previous incarnation already sent."""
    trace = tmp_path / "trace.jsonl"
    trace.write_text(
        json.dumps({"name": "old.event", "ts": 1.0}) + "\n"
    )
    monkeypatch.setenv("DLROVER_TPU_TRACE_FILE", str(trace))
    client = SnapshotFakeClient()
    mon = ResourceMonitor(
        client, interval=999, metrics_file=str(tmp_path / "m.json")
    )
    with open(trace, "a") as f:
        f.write(json.dumps({"name": "new.event", "ts": 2.0}) + "\n")
    mon.report_once()
    names = [e["name"] for e in client.snapshots[0]["events"]]
    assert names == ["new.event"]


def test_hang_detector_emits_obs(tmp_path):
    from dlrover_tpu import obs
    from dlrover_tpu.agent.hang_detector import HangDetector

    tracer = obs.configure_tracer()
    try:
        path = str(tmp_path / "metrics.json")
        det = HangDetector(
            hang_timeout=0.01, startup_grace=999.0, metrics_file=path
        )
        TrainingMonitor.write_metrics(1, path=path)
        assert det.check() is False  # first step = progress
        counter = obs.get_registry().get("dlrover_hang_detect_total")
        before = counter.value()
        import time as _time

        _time.sleep(0.05)
        assert det.check() is True
        assert det.check() is True  # still hung
        assert counter.value() == before + 1  # one hang, one count
        hangs = [
            e for e in tracer.events()
            if e["name"] == "agent.hang_detected"
        ]
        assert len(hangs) == 1
        assert hangs[0]["seconds_since_progress"] >= 0.01
        assert hangs[0]["last_step"] == 1
        # Progress re-arms the detector for the next hang.
        TrainingMonitor.write_metrics(2, path=path)
        assert det.check() is False
        _time.sleep(0.05)
        assert det.check() is True
        assert counter.value() == before + 2
    finally:
        obs.disable_tracer()


def test_write_metrics_records_recent_step_times(tmp_path):
    path = str(tmp_path / "metrics.json")
    TrainingMonitor.write_metrics(1, tokens=100, path=path,
                                  step_time=0.2)
    TrainingMonitor.write_metrics(2, tokens=220, path=path,
                                  step_time=0.3)
    with open(path) as f:
        data = json.load(f)
    assert data["recent_step_times"] == [0.2, 0.3]


class SnapshotFakeClient(FakeClient):
    def __init__(self):
        super().__init__()
        self.snapshots = []

    def report_metrics_snapshot(self, **kw):
        self.snapshots.append(kw)


def test_resource_monitor_ships_deduped_snapshots(tmp_path):
    """Each step time is shipped exactly once across snapshots; the
    tokens/s rate appears once two reads bracket a token delta."""
    path = str(tmp_path / "metrics.json")
    client = SnapshotFakeClient()
    mon = ResourceMonitor(client, interval=999, metrics_file=path)
    TrainingMonitor.write_metrics(1, tokens=100, path=path,
                                  step_time=0.2)
    TrainingMonitor.write_metrics(2, tokens=300, path=path,
                                  step_time=0.3)
    mon.report_once()
    assert len(client.snapshots) == 1
    snap = client.snapshots[0]
    assert snap["step_times"] == [0.2, 0.3]
    assert snap["host"] == mon.host
    assert "dlrover_hang_detect_total" in snap["registry"]
    assert "tokens_per_s" not in snap["resource"]  # no prior read
    TrainingMonitor.write_metrics(3, tokens=500, path=path,
                                  step_time=0.4)
    mon.report_once()
    snap = client.snapshots[1]
    assert snap["step_times"] == [0.4]  # only the new one
    assert snap["resource"]["tokens_per_s"] > 0
    mon.report_once()  # no trainer progress
    assert client.snapshots[2]["step_times"] == []


def test_resource_monitor_snapshot_includes_ring_events_once(tmp_path):
    from dlrover_tpu import obs

    obs.configure_tracer()
    try:
        client = SnapshotFakeClient()
        mon = ResourceMonitor(
            client, interval=999,
            metrics_file=str(tmp_path / "m.json"),
        )
        with obs.span("agent.some_span"):
            obs.event("agent.some_event")
        mon.report_once()
        names = [
            e["name"] for e in client.snapshots[0]["events"]
        ]
        # Arrival order delivers the span even though its mono stamp
        # (span start) predates the inner event's.
        assert "agent.some_span" in names
        assert "agent.some_event" in names
        mon.report_once()
        assert client.snapshots[1]["events"] == []  # exactly once
    finally:
        obs.disable_tracer()


def test_resource_monitor_tails_shared_trace_file(
    tmp_path, monkeypatch
):
    """With DLROVER_TPU_TRACE_FILE set, the snapshot events come from
    the host's shared trace file — the trainer process appends there
    too, which is how its spans reach the master."""
    trace = tmp_path / "trace.jsonl"
    monkeypatch.setenv("DLROVER_TPU_TRACE_FILE", str(trace))
    client = SnapshotFakeClient()
    mon = ResourceMonitor(
        client, interval=999, metrics_file=str(tmp_path / "m.json")
    )
    # "Trainer process" writes two events + one torn line.
    with open(trace, "w") as f:
        f.write(json.dumps({"name": "trainer.step", "ts": 1.0}) + "\n")
        f.write(json.dumps(
            {"name": "ckpt.save_memory", "ts": 2.0, "dur_s": 0.5}
        ) + "\n")
        f.write('{"name": "torn')
    mon.report_once()
    names = [e["name"] for e in client.snapshots[0]["events"]]
    assert names == ["trainer.step", "ckpt.save_memory"]
    # The torn line completes later and ships exactly once.
    with open(trace, "a") as f:
        f.write('_done", "ts": 3.0}\n')
    mon.report_once()
    names = [e["name"] for e in client.snapshots[1]["events"]]
    assert names == ["torn_done"]
    mon.report_once()
    assert client.snapshots[2]["events"] == []


def test_mark_phase_mirrors_to_obs_tracer(tmp_path, monkeypatch):
    """Phase marks feed the recovery-timeline reconstructor through
    the obs tracer, independent of the phases file."""
    from dlrover_tpu import obs
    from dlrover_tpu.obs.timeline import reconstruct_recovery_timeline

    monkeypatch.delenv("DLROVER_TPU_PHASES_FILE", raising=False)
    tracer = obs.configure_tracer()
    try:
        for mark in ("proc_start", "dist_ready", "built",
                     "restore_done", "first_step_done"):
            TrainingMonitor.mark_phase(mark)
        events = tracer.events()
        t_fail = events[0]["ts"] - 1.0
        tl = reconstruct_recovery_timeline(events, t_failure=t_fail)
        assert tl is not None and tl.complete
        assert tl.phases["failure-detect"] >= 1.0
    finally:
        obs.disable_tracer()
