"""Bayesian-optimization strategy search (ref bayes_opt_sg.py:35).

The contract test: on the 8-device strategy space, BO must find the
known-best strategy while evaluating strictly fewer candidates than
exhaustive search would.
"""

import math

import numpy as np

from dlrover_tpu.accelerate.bayes_search import (
    BayesStrategySearch,
    encode_strategy,
)
from dlrover_tpu.accelerate.strategy import (
    Strategy,
    candidate_strategies,
)


def _space():
    """60-candidate space: 10 mesh factorizations x mb x remat."""
    return candidate_strategies(
        8,
        micro_batch_sizes=(4, 8, 16),
        remats=(True, False),
    )


def _true_throughput(s: Strategy) -> float:
    """Synthetic-but-structured objective, smooth in the encoding:
    peaked at fsdp=4/data=2, mb=8, remat off."""
    d = s.mesh_dict
    x = math.log2(max(d.get("fsdp", 1), 1))
    y = math.log2(max(d.get("tensor", 1), 1))
    mb = math.log2(s.micro_batch_size)
    score = 100.0 * math.exp(
        -((x - 2.0) ** 2) / 2 - (y**2) / 2 - ((mb - 3.0) ** 2) / 4
    )
    if s.remat:
        score *= 0.8
    return score


class TestBayesSearch:
    def test_finds_best_with_fewer_evals_than_exhaustive(self):
        cands = _space()
        true_best = max(cands, key=_true_throughput)
        # cost prior loosely anti-correlated with the objective, the
        # way the memory model is: it seeds, not decides.
        prior = [-_true_throughput(c) * 0.5 + i * 0.01
                 for i, c in enumerate(cands)]
        budget = len(cands) // 3
        search = BayesStrategySearch(cands, cost_prior=prior, seed=1)
        while search.should_continue(budget):
            c = search.suggest()
            search.observe(c, _true_throughput(c))
        assert search.evaluated_count() <= budget
        assert search.evaluated_count() < len(cands)
        best = search.best_strategy()
        assert _true_throughput(best) >= 0.95 * _true_throughput(
            true_best
        )

    def test_adversarial_prior_still_converges(self):
        """Even when the cost model seeds the WORST candidates first,
        the GP recovers within a modest budget."""
        cands = _space()
        true_best = max(cands, key=_true_throughput)
        prior = [_true_throughput(c) for c in cands]  # worst first
        search = BayesStrategySearch(cands, cost_prior=prior, seed=2)
        budget = len(cands) // 2
        while search.should_continue(budget):
            c = search.suggest()
            search.observe(c, _true_throughput(c))
        best = search.best_strategy()
        assert _true_throughput(best) >= 0.9 * _true_throughput(
            true_best
        )

    def test_failures_observed_as_avoided_points(self):
        cands = _space()
        search = BayesStrategySearch(cands, seed=3)
        # first two candidates fail (e.g. OOM)
        for _ in range(2):
            c = search.suggest()
            search.observe(c, None)
        assert search.best_strategy() is None
        c = search.suggest()
        search.observe(c, 10.0)
        assert search.best_strategy() == c
        assert search.best_throughput() == 10.0

    def test_never_suggests_evaluated_candidate(self):
        cands = _space()[:10]
        search = BayesStrategySearch(cands, seed=4)
        seen = []
        while search.should_continue(len(cands)):
            c = search.suggest()
            assert c not in seen
            seen.append(c)
            search.observe(c, float(len(seen)))
        assert len(seen) == len(cands)

    def test_encoding_distinguishes_strategies(self):
        cands = _space()
        encs = {tuple(encode_strategy(c)) for c in cands}
        assert len(encs) == len(cands)

    def test_gp_interpolates(self):
        from dlrover_tpu.accelerate.bayes_search import _GP

        rng = np.random.default_rng(0)
        X = rng.normal(size=(20, 3))
        y = (X**2).sum(1)
        gp = _GP(length_scale=1.0)
        gp.fit(X, y)
        mu, sigma = gp.predict(X)
        np.testing.assert_allclose(mu, y, atol=0.3)
        assert (sigma < 0.3).all()

    def test_encoding_covers_overlap_knobs(self):
        base = Strategy(mesh_shape=(("data", 8),))
        ov = Strategy(mesh_shape=(("data", 8),), overlap_reduce=True)
        ov_big = Strategy(
            mesh_shape=(("data", 8),),
            overlap_reduce=True,
            reduce_bucket_mb=16.0,
        )
        assert not np.array_equal(
            encode_strategy(base), encode_strategy(ov)
        )
        assert not np.array_equal(
            encode_strategy(ov), encode_strategy(ov_big)
        )


class TestObserveDedupe:
    """Re-observed cached trials and duplicated candidate grids must
    not double-weight the GP, and suggest must never re-propose an
    already-evaluated point while untried candidates remain."""

    def _dup_space(self):
        cands = _space()[:6]
        # the same strategies again, at different indices
        return cands + list(cands[:3])

    def test_duplicate_candidates_collapse_to_one_observation(self):
        cands = self._dup_space()
        search = BayesStrategySearch(cands, seed=0)
        search.observe(cands[0], 5.0)
        search.observe(cands[6], 7.0)  # identical to cands[0]
        assert search.evaluated_count() == 1
        assert len(search._observed) == 1
        assert search.best_throughput() == 7.0  # latest wins

    def test_suggest_skips_duplicates_of_evaluated(self):
        cands = self._dup_space()
        search = BayesStrategySearch(cands, seed=1)
        seen = []
        while search.should_continue(len(cands)):
            c = search.suggest()
            assert c not in seen, "re-proposed an evaluated point"
            seen.append(c)
            search.observe(c, float(len(seen)))
        # every DISTINCT candidate evaluated exactly once
        assert len(seen) == 6

    def test_reobserve_success_clears_stale_failure(self):
        cands = _space()[:4]
        search = BayesStrategySearch(cands, seed=2)
        search.observe(cands[0], None)
        assert search.best_strategy() is None
        search.observe(cands[0], 3.0)  # a later real measurement
        assert search.best_strategy() == cands[0]


class TestWarmStart:
    def test_replays_only_known_candidates(self):
        cands = _space()[:8]
        outside = _space()[10]
        search = BayesStrategySearch(cands, seed=0)
        n = search.warm_start(
            [
                (cands[1], 5.0),
                (cands[2], None),  # cached OOM -> avoided point
                (outside, 99.0),  # not in this grid: skipped
            ]
        )
        assert n == 2
        assert search.evaluated_count() == 2
        assert search.best_strategy() == cands[1]
        # the cached failure is a zero point, not a winner
        assert search.best_throughput() == 5.0

    def test_warm_cache_reaches_same_best_with_fewer_evals(self):
        """The counting-evaluator contract: a search warm-started from
        a previous run's observations reaches the same best strategy
        with STRICTLY fewer fresh evaluations."""
        cands = _space()
        budget = len(cands) // 3

        def run(warm_obs):
            search = BayesStrategySearch(cands, seed=3)
            search.warm_start(warm_obs)
            evals = 0
            while search.should_continue(budget):
                c = search.suggest()
                search.observe(c, _true_throughput(c))
                evals += 1
            return search, evals

        cold, cold_evals = run([])
        warm_obs = [
            (cands[i], t) for i, t in cold._observed.items()
        ]
        warm, warm_evals = run(warm_obs)
        assert warm_evals < cold_evals
        assert warm_evals == 0  # fully warm: zero fresh dry-runs
        assert warm.best_strategy() == cold.best_strategy()

    def test_partial_warm_start_still_counts_against_budget(self):
        cands = _space()[:10]
        search = BayesStrategySearch(cands, seed=4)
        search.warm_start([(cands[0], 1.0), (cands[1], 2.0)])
        evals = 0
        while search.should_continue(5):
            c = search.suggest()
            assert c not in (cands[0], cands[1])
            search.observe(c, 0.5)
            evals += 1
        assert evals == 3  # budget 5 minus 2 cached
