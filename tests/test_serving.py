"""Serving plane: KV block pool, continuous-batching scheduler,
router ledger/failover, replica_unhealthy detector, remediation
serving ladder, and the hermetic replica-kill acceptance drill.

The load-bearing correctness claim is *recompute-exactness*: greedy
decode through the continuous-batching scheduler — staggered
admission, chunked/padded prefill, preemption, requeue across
replicas — must produce bitwise the SAME tokens as the monolithic
``generate.generate`` path, because failover correctness (a killed
replica's requests recomputed elsewhere) rests on it.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dlrover_tpu.serving.kv_pool import KVBlockPool
from dlrover_tpu.serving.router import ServingRouter
from dlrover_tpu.serving.scheduler import (
    ContinuousBatchingScheduler,
    ServeRequest,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from dlrover_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(7), cfg)
    return params, cfg


def _greedy_reference(params, cfg, prompt, max_new):
    import jax.numpy as jnp

    from dlrover_tpu.models import generate

    out = generate.generate(
        params, cfg, jnp.asarray([prompt], jnp.int32),
        max_new_tokens=max_new, temperature=0.0,
    )
    return np.asarray(out)[0, len(prompt):].tolist()


class TestKVBlockPool:
    def test_alloc_extend_release_accounting(self):
        pool = KVBlockPool(lanes=2, max_len=32, block_size=8)
        assert pool.total_blocks == 8
        lane = pool.allocate("a", 9)  # 2 blocks
        assert lane == 0
        assert pool.blocks_in_use() == 2
        assert pool.extend("a", 16)  # still 2 blocks
        assert pool.blocks_in_use() == 2
        assert pool.extend("a", 17)  # 3rd block
        assert pool.blocks_in_use() == 3
        assert pool.utilization() == pytest.approx(3 / 8)
        pool.release("a")
        assert pool.blocks_in_use() == 0
        assert pool.free_lane_count() == 2
        pool.release("a")  # replay-safe

    def test_budget_gates_admission_and_growth(self):
        pool = KVBlockPool(
            lanes=4, max_len=32, block_size=8, total_blocks=3
        )
        assert pool.allocate("a", 8) is not None   # 1 block
        assert pool.allocate("b", 16) is not None  # 2 blocks
        # Budget exhausted despite free lanes.
        assert pool.allocate("c", 1) is None
        assert not pool.extend("a", 9)
        pool.release("b")
        assert pool.extend("a", 9)

    def test_youngest_is_preemption_victim(self):
        pool = KVBlockPool(lanes=3, max_len=16, block_size=8)
        pool.allocate("a", 4)
        pool.allocate("b", 4)
        assert pool.youngest() == "b"
        pool.release("b")
        assert pool.youngest() == "a"

    def test_double_admit_raises_and_too_long_rejected(self):
        pool = KVBlockPool(lanes=2, max_len=16, block_size=8)
        assert pool.allocate("a", 4) is not None
        with pytest.raises(KeyError):
            pool.allocate("a", 4)
        assert pool.allocate("b", 17) is None  # > max_len


class TestLanePrefill:
    def test_chunked_padded_lane_prefill_matches_monolithic(
        self, tiny_model
    ):
        """Padded lane-granular chunk prefill fills the lane's cache
        and produces the same last-position logits as the monolithic
        llama_prefill — including a ragged final chunk."""
        import jax
        import jax.numpy as jnp

        from dlrover_tpu.models import generate, llama

        params, cfg = tiny_model
        lanes, T = 3, 32
        prompt = np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(1), (1, 7), 0, cfg.vocab_size
            )
        )
        # Monolithic reference into its own single-lane cache.
        ref_cache = generate._cache_for(
            cfg, 1, T, cfg.n_kv_head
        )
        ref_logits, ref_cache = generate.llama_prefill(
            params, ref_cache, jnp.asarray(prompt), cfg
        )
        # Chunked (chunk 4, final chunk 3 padded to 4) into lane 1 of
        # a shared 3-lane cache.
        cache = generate._cache_for(cfg, lanes, T, cfg.n_kv_head)
        chunk = 4
        start = 0
        last = None
        while start < prompt.shape[1]:
            c = min(chunk, prompt.shape[1] - start)
            buf = np.zeros((1, chunk), np.int32)
            buf[0, :c] = prompt[0, start:start + c]
            last, cache = generate.llama_lane_prefill_chunk(
                params, cache, jnp.asarray(buf), 1, start, cfg
            )
            last_real = c
            start += c
        got = np.asarray(last[0, last_real - 1])
        np.testing.assert_allclose(
            got, np.asarray(ref_logits[0]), rtol=1e-4, atol=1e-4
        )
        # The lane's cache region for the prompt matches; the OTHER
        # lanes stayed untouched (zeros).
        np.testing.assert_allclose(
            np.asarray(cache.k[:, 1, :7]),
            np.asarray(ref_cache.k[:, 0, :7]),
            rtol=1e-5, atol=1e-5,
        )
        assert float(jnp.abs(cache.k[:, 0]).sum()) == 0.0
        assert float(jnp.abs(cache.k[:, 2]).sum()) == 0.0


class TestScheduler:
    def test_continuous_batching_matches_generate(self, tiny_model):
        """Staggered greedy requests through admission / chunked
        prefill / ragged decode == per-request generate.generate."""
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=3, block_size=4, prefill_chunk=4,
            max_len=32,
        )
        rng = np.random.default_rng(0)
        reqs = []
        for i in range(6):
            plen = int(rng.integers(3, 12))
            prompt = rng.integers(
                0, cfg.vocab_size, size=plen
            ).tolist()
            reqs.append(
                ServeRequest(
                    request_id=f"r{i}", prompt=prompt,
                    max_new_tokens=6,
                )
            )
            assert sched.submit(reqs[-1])
        done = {}
        for _ in range(200):
            for c in sched.step():
                done[c.request_id] = c
            if len(done) == len(reqs):
                break
        assert len(done) == len(reqs)
        for r in reqs:
            want = _greedy_reference(
                params, cfg, r.prompt, r.max_new_tokens
            )
            assert done[r.request_id].tokens == want, r.request_id
            assert done[r.request_id].finish_reason == "length"
        stats = sched.stats()
        assert stats["completed_total"] == 6
        assert stats["kv"]["blocks_in_use"] == 0
        assert stats["ttft_p99_s"] > 0

    def test_prefill_spanning_decode_ticks_not_clobbered(
        self, tiny_model
    ):
        """Regression: while one lane DECODES, another lane's chunked
        prefill spans several steps — the decode step's cache scatter
        must not touch the prefilling lane (unmasked, every decode
        tick wrote a garbage key at position 0 of EVERY lane,
        corrupting the long prompt and breaking exact failover)."""
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=2, block_size=4, prefill_chunk=4,
            prefill_budget=4, max_len=32,
        )
        short = ServeRequest(
            request_id="short", prompt=[1, 2, 3], max_new_tokens=8
        )
        rng = np.random.default_rng(9)
        long_prompt = rng.integers(
            0, cfg.vocab_size, size=12
        ).tolist()
        long = ServeRequest(
            request_id="long", prompt=long_prompt, max_new_tokens=4
        )
        sched.submit(short)
        sched.submit(long)
        done = {}
        for _ in range(100):
            for c in sched.step():
                done[c.request_id] = c
            if len(done) == 2:
                break
        assert len(done) == 2
        for r in (short, long):
            want = _greedy_reference(
                params, cfg, r.prompt, r.max_new_tokens
            )
            assert done[r.request_id].tokens == want, r.request_id

    def test_padded_final_chunk_at_cache_end_not_clamped(
        self, tiny_model
    ):
        """Regression: max_len NOT a multiple of prefill_chunk, with
        a prompt whose padded final chunk window crosses max_len —
        dynamic_update_slice silently clamps a crossing write start,
        shifting the chunk onto wrong positions; the physical cache
        must carry chunk-multiple slack instead."""
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=1, block_size=4, prefill_chunk=16,
            max_len=24,
        )
        rng = np.random.default_rng(21)
        prompt = rng.integers(0, cfg.vocab_size, size=20).tolist()
        req = ServeRequest(
            request_id="edge", prompt=prompt, max_new_tokens=4
        )
        assert sched.submit(req)
        done = {}
        for _ in range(60):
            for c in sched.step():
                done[c.request_id] = c
            if done:
                break
        assert done["edge"].tokens == _greedy_reference(
            params, cfg, prompt, 4
        )

    def test_admission_bounded_by_lanes(self, tiny_model):
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=2, block_size=8, prefill_chunk=8,
            max_len=32,
        )
        for i in range(5):
            sched.submit(
                ServeRequest(
                    request_id=f"q{i}", prompt=[1, 2, 3],
                    max_new_tokens=4,
                )
            )
        sched.step()
        assert sched.active() <= 2
        assert sched.queue_depth() == 3

    def test_prefill_budget_protects_decode(self, tiny_model):
        """A long prompt advances at most prefill_budget tokens per
        step, so it takes multiple steps to reach decode."""
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=2, block_size=8, prefill_chunk=4,
            prefill_budget=4, max_len=48,
        )
        sched.submit(
            ServeRequest(
                request_id="long", prompt=list(range(1, 17)),
                max_new_tokens=2,
            )
        )
        sched.step()
        seq = next(iter(sched._by_lane.values()))
        assert seq.phase == "prefill"
        assert seq.prefilled == 4
        for _ in range(3):
            sched.step()
        assert (
            not sched._by_lane
            or next(iter(sched._by_lane.values())).phase == "decode"
        )

    def test_preemption_requeues_and_recomputes_exactly(
        self, tiny_model
    ):
        """With a starved block budget, growth preempts the youngest
        sequence; the preempted request still completes with the
        exact greedy reference tokens (recompute preemption)."""
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=2, block_size=4, prefill_chunk=4,
            max_len=32, total_blocks=6,
        )
        rng = np.random.default_rng(3)
        reqs = []
        for i in range(2):
            prompt = rng.integers(0, cfg.vocab_size, size=7).tolist()
            reqs.append(
                ServeRequest(
                    request_id=f"p{i}", prompt=prompt,
                    max_new_tokens=8,
                )
            )
            sched.submit(reqs[-1])
        done = {}
        for _ in range(300):
            for c in sched.step():
                done[c.request_id] = c
            if len(done) == len(reqs):
                break
        assert len(done) == len(reqs)
        assert sched.stats()["preempted_total"] >= 1
        for r in reqs:
            want = _greedy_reference(
                params, cfg, r.prompt, r.max_new_tokens
            )
            assert done[r.request_id].tokens == want

    def test_oversized_and_empty_requests_fail_cleanly(
        self, tiny_model
    ):
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=2, block_size=8, max_len=16,
        )
        sched.submit(
            ServeRequest(request_id="big", prompt=[1] * 12,
                         max_new_tokens=8)
        )
        sched.submit(
            ServeRequest(request_id="empty", prompt=[],
                         max_new_tokens=4)
        )
        sched.submit(
            ServeRequest(request_id="zero", prompt=[1, 2],
                         max_new_tokens=0)
        )
        failed = {c.request_id: c for c in sched.step()}
        assert failed["big"].error
        assert failed["empty"].error
        # max_new_tokens < 1 fails cleanly instead of generating one
        # token anyway at the prefill handoff.
        assert "max_new_tokens" in failed["zero"].error
        assert failed["zero"].tokens == []
        assert sched.stats()["failed_total"] == 3

    def test_eos_finishes_early(self, tiny_model):
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=1, block_size=8, max_len=32,
        )
        prompt = [5, 6, 7]
        ref = _greedy_reference(params, cfg, prompt, 8)
        eos = ref[2]  # the 3rd greedy token becomes "EOS"
        sched.eos_id = eos
        sched.submit(
            ServeRequest(request_id="e", prompt=prompt,
                         max_new_tokens=8)
        )
        done = []
        for _ in range(50):
            done.extend(sched.step())
            if done:
                break
        assert done[0].tokens == ref[:3]
        assert done[0].finish_reason == "eos"

    def test_duplicate_submit_of_resident_request_is_dropped(
        self, tiny_model
    ):
        """Regression: a router requeue can hand this replica back a
        request_id it STILL holds resident (reconnect
        re-registration requeues a live replica's in-flight work);
        re-submitting must dedupe, not crash the pool's
        already-resident guard, and the request completes once."""
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=2, block_size=8, max_len=32,
        )
        req = ServeRequest(
            request_id="dup", prompt=[1, 2, 3], max_new_tokens=6
        )
        assert sched.submit(req)
        sched.step()  # admitted + resident now
        assert sched.submit(req)  # duplicate: dropped, no raise
        assert sched.submit(req)
        assert sched.queue_depth() == 0
        done = []
        for _ in range(30):
            done.extend(sched.step())
            if done:
                break
        assert [c.request_id for c in done] == ["dup"]
        assert done[0].tokens == _greedy_reference(
            params, cfg, req.prompt, 6
        )

    def test_drain_returns_unfinished(self, tiny_model):
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=1, block_size=8, max_len=32,
        )
        for i in range(3):
            sched.submit(
                ServeRequest(
                    request_id=f"d{i}", prompt=[1, 2, 3],
                    max_new_tokens=16,
                )
            )
        sched.step()  # admits one, leaves two queued
        drained = sched.drain()
        assert sorted(r.request_id for r in drained) == [
            "d0", "d1", "d2"
        ]
        assert sched.active() == 0
        assert sched.pool.blocks_in_use() == 0


class FakeJobManager:
    def __init__(self):
        self.ensured = []
        self.retired = []

    def ensure_role(self, node_type, count, resource=None):
        self.ensured.append((node_type, count))
        return []

    def retire_node(self, node_id):
        self.retired.append(node_id)


class TestRouter:
    def _router(self, **config):
        clk = [1000.0]
        cfg = {"progress_timeout_s": 5.0, "scale_cooldown_s": 0.0}
        cfg.update(config)
        router = ServingRouter(
            job_manager=FakeJobManager(),
            clock=lambda: clk[0],
            config=cfg,
        )
        return router, clk

    def test_ledger_lifecycle_and_idempotent_submit(self):
        router, clk = self._router()
        router.register_replica(1, "a")
        rid = router.submit([1, 2], max_new_tokens=4,
                            request_id="x")
        assert rid == "x"
        assert router.submit([9, 9], request_id="x") == "x"
        assert router.counters()["requests"] == 1
        items = router.pull(1, max_items=2)
        assert [i.request_id for i in items] == ["x"]
        assert router.result("x")["state"] == "dispatched"
        assert router.complete(1, "x", [4, 5, 6, 7])
        rec = router.result("x")
        assert rec["state"] == "done"
        assert rec["tokens"] == [4, 5, 6, 7]
        # Duplicate completion dropped, first result kept.
        assert not router.complete(1, "x", [0])
        assert router.result("x")["tokens"] == [4, 5, 6, 7]

    def test_auto_ids_never_collide_with_caller_tokens(self):
        """Regression: a caller-supplied idempotence token shaped
        like an auto id ('req-2') must not be overwritten when the
        anonymous sequence reaches the same number."""
        router, clk = self._router()
        router.register_replica(1, "a")
        assert router.submit([1, 2, 3], request_id="req-2") == "req-2"
        others = [router.submit([9, 9]) for _ in range(3)]
        assert len(set(others) | {"req-2"}) == 4
        assert router.result("req-2")["state"] == "queued"
        assert router.counters()["requests"] == 4
        # The original caller's prompt rides its own ledger entry.
        items = router.pull(1, max_items=4)
        by_id = {i.request_id: i for i in items}
        assert by_id["req-2"].prompt == [1, 2, 3]

    def test_replica_gone_requeues_in_flight(self):
        router, clk = self._router()
        router.register_replica(1, "a")
        router.register_replica(2, "b")
        rids = [router.submit([i], max_new_tokens=2)
                for i in range(3)]
        assert len(router.pull(1, max_items=3)) == 3
        n = router.replica_gone(1)
        assert n == 3
        assert router.replica_gone(1) == 0  # idempotent
        # The survivor picks all three back up: zero drops.
        again = router.pull(2, max_items=5)
        assert sorted(i.request_id for i in again) == sorted(rids)
        for i in again:
            router.complete(2, i.request_id, [1, 2])
        assert router.counters()["done"] == 3
        assert all(
            router.result(r)["requeues"] == 1 for r in rids
        )

    def test_reregistration_requeues_old_incarnation(self):
        router, clk = self._router()
        router.register_replica(1, "a")
        router.submit([1], request_id="r")
        assert router.pull(1, max_items=1)
        router.register_replica(1, "a")  # fresh process
        assert router.result("r")["state"] == "queued"

    def test_unhealthy_and_drain_semantics(self):
        router, clk = self._router()
        router.register_replica(1, "a")
        router.register_replica(2, "b")
        router.submit([1], request_id="r")
        router.pull(1, max_items=1)
        clk[0] += 6.0
        facts = router.unhealthy_replicas()
        # Replica 2 is idle-and-empty: not flagged. Replica 1 holds
        # work without progress: flagged.
        assert [f["replica_id"] for f in facts] == [1]
        assert router.drain_replica(1, "test") == 1
        # Draining replicas are never fed.
        assert router.pull(1, max_items=1) == []
        # ...and stay unhealthy until they come back.
        clk[0] += 10.0
        assert [
            f["replica_id"] for f in router.unhealthy_replicas()
        ] == [1]
        router.register_replica(1, "a")
        assert router.unhealthy_replicas() == []

    def test_autoscale_grow_on_backlog_and_shrink_idle(self):
        router, clk = self._router(
            backlog_per_replica=2.0, min_replicas=1,
            max_replicas=4,
        )
        router.register_replica(1, "a")
        for i in range(5):
            router.submit([i], max_new_tokens=2)
        assert router.maybe_autoscale() == "grow"
        from dlrover_tpu.common.constants import NodeType

        assert router.job_manager.ensured == [
            (NodeType.REPLICA, 2)
        ]
        # Drain the queue; with two idle replicas and no traffic the
        # router shrinks back toward min_replicas.
        items = router.pull(1, max_items=5)
        for it in items:
            router.complete(1, it.request_id, [1])
        router.register_replica(2, "b")
        clk[0] += 120.0
        assert router.maybe_autoscale() == "shrink"
        assert router.job_manager.retired == [2]

    def test_autoscale_grow_counts_draining_replicas(self):
        """Regression: ensure_role counts ALL alive replica nodes,
        so the grow target must include draining replicas — a
        ready-count target no-ops exactly when a drain halved
        capacity."""
        router, clk = self._router(
            backlog_per_replica=2.0, min_replicas=1,
            max_replicas=4,
        )
        router.register_replica(1, "a")
        router.register_replica(2, "b")
        router.drain_replica(2, "test")
        for i in range(5):
            router.submit([i], max_new_tokens=2)
        assert router.maybe_autoscale() == "grow"
        from dlrover_tpu.common.constants import NodeType

        # 2 registered (1 ready + 1 draining) -> target 3, so
        # ensure_role actually launches a node.
        assert router.job_manager.ensured == [
            (NodeType.REPLICA, 3)
        ]

    def test_wire_roundtrip(self):
        from dlrover_tpu.common import messages as msg

        item = msg.ServeWorkItem(
            request_id="w", prompt=[1, 2, 3], max_new_tokens=4,
            temperature=0.5,
        )
        resp = msg.ServePullResponse(items=[item])
        decoded = msg.deserialize(msg.serialize(resp))
        assert decoded.items[0].request_id == "w"
        assert decoded.items[0].prompt == [1, 2, 3]
        assert decoded.items[0].temperature == 0.5


class TestHandoffPayload:
    def _payload(self, rid="h", plen=3):
        import numpy as np

        from dlrover_tpu.serving.handoff import HandoffPayload

        zeros = np.arange(
            2 * 8 * 2 * 4, dtype=np.float32
        ).reshape(2, 8, 2, 4)
        return HandoffPayload(
            request_id=rid,
            prompt=list(range(1, plen + 1)),
            max_new_tokens=4,
            temperature=0.0,
            first_token=7,
            k=zeros,
            v=zeros + 1.0,
            ttft_s=0.25,
            phases={"dispatch": 0.01, "prefill": 0.2,
                    "first_decode": 0.04},
            trace={"trace_id": "t", "span_id": "s"},
        )

    def test_pack_unpack_roundtrip_bitwise(self):
        import numpy as np

        from dlrover_tpu.serving import handoff as hmod

        p = self._payload()
        got = hmod.unpack(hmod.pack(p))
        assert got.request_id == p.request_id
        assert got.prompt == p.prompt
        assert got.first_token == 7
        assert got.phases == p.phases
        np.testing.assert_array_equal(got.k, p.k)
        np.testing.assert_array_equal(got.v, p.v)
        assert hmod.payload_nbytes(hmod.pack(p)) == p.nbytes()

    def test_handoff_rides_the_completion_wire(self):
        """The packed payload survives the msgpack RPC envelope
        (ServeCompletedReport up, ServeWorkItem down) with its KV
        bytes bitwise intact — no pickle anywhere."""
        import numpy as np

        from dlrover_tpu.common import messages as msg
        from dlrover_tpu.serving import handoff as hmod

        wire = hmod.pack(self._payload())
        up = msg.deserialize(
            msg.serialize(
                msg.ServeCompletedReport(
                    replica_id=1, request_id="h",
                    finish_reason="handoff", handoff=wire,
                )
            )
        )
        down = msg.deserialize(
            msg.serialize(
                msg.ServeWorkItem(
                    request_id="h", handoff=up.handoff
                )
            )
        )
        got = hmod.unpack(down.handoff)
        np.testing.assert_array_equal(got.k, self._payload().k)
        assert got.first_token == 7


class TestSchedulerRoles:
    def test_unknown_role_rejected(self, tiny_model):
        params, cfg = tiny_model
        with pytest.raises(ValueError, match="role"):
            ContinuousBatchingScheduler(
                params, cfg, lanes=1, role="turbo"
            )

    def test_decode_role_fails_raw_prompts_loudly(self, tiny_model):
        params, cfg = tiny_model
        sched = ContinuousBatchingScheduler(
            params, cfg, lanes=1, block_size=8, max_len=32,
            role="decode",
        )
        sched.submit(
            ServeRequest(request_id="raw", prompt=[1, 2],
                         max_new_tokens=4)
        )
        failed = {c.request_id: c for c in sched.step()}
        assert "cannot prefill" in failed["raw"].error
        with pytest.raises(ValueError, match="prefill-role"):
            ContinuousBatchingScheduler(
                params, cfg, lanes=1, block_size=8, max_len=32,
                role="prefill",
            ).submit_handoff(object())

    @pytest.mark.slow
    def test_disagg_pipeline_bitwise_matches_generate(
        self, tiny_model
    ):
        """(slow: ~25s of compiles; tier-1 gets the same bitwise
        guarantee end-to-end over RPC from
        test_disagg_interference_drill.)

        The tentpole correctness claim at scheduler level: a
        prefill-role scheduler exports KV handoffs, a decode-role
        scheduler (with a DIFFERENT block size — payloads are
        self-describing) imports them, and every greedy continuation
        is bitwise the colocated ``generate.generate`` tokens.
        Covers multi-chunk prompts, max_new_tokens=1 finishing on
        the prefill replica outright, and the import-wait 'handoff'
        phase on completions."""
        params, cfg = tiny_model
        pre = ContinuousBatchingScheduler(
            params, cfg, lanes=2, block_size=4, prefill_chunk=4,
            max_len=32, role="prefill",
        )
        dec = ContinuousBatchingScheduler(
            params, cfg, lanes=3, block_size=8, prefill_chunk=8,
            max_len=32, role="decode",
        )
        rng = np.random.default_rng(5)
        reqs = []
        for i in range(4):
            plen = int(rng.integers(3, 12))
            prompt = rng.integers(
                0, cfg.vocab_size, size=plen
            ).tolist()
            reqs.append(
                ServeRequest(
                    request_id=f"r{i}", prompt=prompt,
                    max_new_tokens=6,
                )
            )
            assert pre.submit(reqs[-1])
        one = ServeRequest(
            request_id="one", prompt=[3, 1, 4], max_new_tokens=1
        )
        assert pre.submit(one)
        done = {}
        for _ in range(400):
            for c in pre.step():
                if c.finish_reason == "handoff":
                    assert dec.submit_handoff(c.handoff)
                else:
                    done[c.request_id] = c
            for c in dec.step():
                done[c.request_id] = c
            if len(done) == len(reqs) + 1:
                break
        assert len(done) == len(reqs) + 1
        # max_new_tokens=1 finished ON the prefill scheduler (its
        # only token comes from prefill; nothing to hand off).
        assert done["one"].finish_reason == "length"
        assert done["one"].tokens == _greedy_reference(
            params, cfg, one.prompt, 1
        )
        assert "handoff" not in done["one"].phases
        for r in reqs:
            want = _greedy_reference(
                params, cfg, r.prompt, r.max_new_tokens
            )
            assert done[r.request_id].tokens == want, r.request_id
            assert "handoff" in done[r.request_id].phases
        assert pre.stats()["handoffs_exported"] == len(reqs)
        assert dec.stats()["handoffs_imported"] == len(reqs)
        assert pre.stats()["role"] == "prefill"
        # Both pools fully drained.
        assert pre.pool.blocks_in_use() == 0
        assert dec.pool.blocks_in_use() == 0

    def test_handoff_import_gates_on_block_budget(self, tiny_model):
        """A handoff import pays the SAME pool accounting as raw
        admission: with no block budget it stays queued (and a
        too-long one fails cleanly)."""
        import numpy as np

        from dlrover_tpu.serving.handoff import HandoffPayload

        params, cfg = tiny_model
        dec = ContinuousBatchingScheduler(
            params, cfg, lanes=2, block_size=8, max_len=32,
            total_blocks=3, role="decode",
        )
        L = cfg.n_layer
        kv = np.zeros((L, 16, cfg.n_kv_head, cfg.head_dim),
                      np.float32)

        def payload(rid, plen, max_new=4):
            return HandoffPayload(
                request_id=rid, prompt=list(range(plen)),
                max_new_tokens=max_new, temperature=0.0,
                first_token=1, k=kv[:, :16], v=kv[:, :16],
            )

        assert dec.submit_handoff(payload("a", 14))  # 2 blocks
        assert dec.submit_handoff(payload("b", 14))  # budget-blocked
        dec.step()
        assert dec.active() == 1
        assert dec.queue_depth() == 1  # b waits for blocks
        too_long = payload("c", 14, max_new=32)  # 14+32 > max_len
        dec2 = ContinuousBatchingScheduler(
            params, cfg, lanes=2, block_size=8, max_len=32,
            role="decode",
        )
        assert dec2.submit_handoff(too_long)
        failed = {c.request_id: c for c in dec2.step()}
        assert "exceeds replica capacity" in failed["c"].error

    def test_drain_requeues_queued_handoffs_as_prompts(
        self, tiny_model
    ):
        import numpy as np

        from dlrover_tpu.serving.handoff import HandoffPayload

        params, cfg = tiny_model
        dec = ContinuousBatchingScheduler(
            params, cfg, lanes=1, block_size=8, max_len=32,
            role="decode",
        )
        kv = np.zeros(
            (cfg.n_layer, 8, cfg.n_kv_head, cfg.head_dim),
            np.float32,
        )
        dec.submit_handoff(
            HandoffPayload(
                request_id="q", prompt=[1, 2, 3],
                max_new_tokens=4, temperature=0.5,
                first_token=1, k=kv, v=kv,
            )
        )
        drained = dec.drain()
        assert [r.request_id for r in drained] == ["q"]
        assert drained[0].prompt == [1, 2, 3]
        assert drained[0].temperature == 0.5


class FakeLabeledJobManager(FakeJobManager):
    def ensure_role(self, node_type, count, resource=None,
                    labels=None):
        self.ensured.append((node_type, count, labels))
        return []


class TestRouterDisagg:
    def _router(self, **config):
        clk = [1000.0]
        cfg = {"progress_timeout_s": 5.0, "scale_cooldown_s": 0.0}
        cfg.update(config)
        router = ServingRouter(
            job_manager=FakeLabeledJobManager(),
            clock=lambda: clk[0],
            config=cfg,
        )
        return router, clk

    def _wire(self, rid, plen=3, nbytes_scale=1):
        import numpy as np

        from dlrover_tpu.serving import handoff as hmod

        kv = np.zeros((2, 8 * nbytes_scale, 2, 4), np.float32)
        return hmod.pack(
            hmod.HandoffPayload(
                request_id=rid, prompt=list(range(plen)),
                max_new_tokens=4, temperature=0.0,
                first_token=1, k=kv, v=kv,
                ttft_s=0.1,
                phases={"dispatch": 0.0, "prefill": 0.08,
                        "first_decode": 0.02},
            )
        )

    def test_two_stage_lifecycle(self):
        router, clk = self._router()
        router.register_replica(1, "pre", role="prefill")
        router.register_replica(2, "dec", role="decode")
        rid = router.submit([1, 2, 3], request_id="x")
        assert router.pull(2, max_items=2) == []  # raw never to dec
        assert router.pull(1, max_items=2)
        assert router.result("x")["state"] == "prefilling"
        assert router.complete(1, "x", [], handoff=self._wire("x"))
        assert router.result("x")["state"] == "handoff"
        assert router.snapshot()["handoff_queue_depth"] == 1
        assert router.pull(1, max_items=1) == []  # handoff never to pre
        out = router.pull(2, max_items=1)
        assert out and out[0].handoff
        assert router.result("x")["state"] == "decoding"
        # payload left the master at dispatch (bounded RAM)
        assert router.counters()["handoff_bytes"] == 0
        clk[0] += 1.0
        assert router.complete(
            2, "x", [1, 2, 3, 4], ttft_s=0.1, tpot_s=0.01,
            finish_reason="length",
            phases={"dispatch": 0.0, "prefill": 0.08,
                    "first_decode": 0.02, "handoff": 0.01,
                    "decode": 0.05},
        )
        rec = router.result("x")
        assert rec["state"] == "done"
        assert "handoff" in rec["phases"]
        total = sum(
            rec["phases"][k]
            for k in ("queue", "dispatch", "prefill", "first_decode")
        )
        assert rec["phases"]["ttft_total"] == pytest.approx(
            total, abs=1e-6
        )
        # A late duplicate handoff from a stale replica is dropped.
        assert not router.complete(
            1, "x", [], handoff=self._wire("x")
        )

    def test_requeue_semantics_per_role(self):
        """A prefill-replica death recomputes the prompt; a
        decode-replica death re-prefills; a STAGED handoff (owned by
        the master) survives either death."""
        router, clk = self._router()
        router.register_replica(1, "pre", role="prefill")
        router.register_replica(2, "dec", role="decode")
        for rid in ("a", "b", "c"):
            router.submit([1, 2], request_id=rid)
        assert len(router.pull(1, max_items=3)) == 3
        # a stays prefilling on 1; b reaches handoff; c reaches dec.
        router.complete(1, "b", [], handoff=self._wire("b"))
        router.complete(1, "c", [], handoff=self._wire("c"))
        assert [r.request_id for r in router.pull(2, max_items=1)] \
            == ["b"]
        assert router.result("a")["state"] == "prefilling"
        assert router.result("b")["state"] == "decoding"
        assert router.result("c")["state"] == "handoff"
        # Decode replica dies: b re-prefills (KV lost with it).
        assert router.replica_gone(2) == 1
        assert router.result("b")["state"] == "queued"
        # Prefill replica dies: a requeues; the STAGED c survives.
        assert router.replica_gone(1) == 1
        assert router.result("a")["state"] == "queued"
        assert router.result("c")["state"] == "handoff"
        # A mixed replica can serve both stages: raw first, then
        # staged handoffs.
        router.register_replica(3, "mix", role="mixed")
        first = router.pull(3, max_items=2)
        assert sorted(r.request_id for r in first) == ["a", "b"]
        assert all(r.handoff is None for r in first)
        nxt = router.pull(3, max_items=1)
        assert nxt[0].request_id == "c" and nxt[0].handoff
        assert router.result("c")["state"] == "decoding"

    def test_dispatched_payload_not_pinned_by_ledger(self):
        """Regression (review): the KV payload attached to the work
        item at decode dispatch must not stay referenced off the
        finished (or requeued) ledger record — a retained reference
        would pin up to ledger_retention payloads of dead KV bytes
        in master RAM, silently breaking the handoff_max_bytes
        bound."""
        router, clk = self._router()
        router.register_replica(1, "pre", role="prefill")
        router.register_replica(2, "dec", role="decode")
        router.submit([1, 2], request_id="a")
        router.submit([3, 4], request_id="b")
        router.pull(1, max_items=2)
        router.complete(1, "a", [], handoff=self._wire("a"))
        router.complete(1, "b", [], handoff=self._wire("b"))
        out = router.pull(2, max_items=2)
        assert all(r.handoff for r in out)
        router.complete(2, "a", [1, 2], finish_reason="length")
        assert router._requests["a"].req.handoff is None
        # ...and on the re-prefill requeue path too.
        router.replica_gone(2)
        assert router._requests["b"].req.handoff is None

    def test_oversize_payload_fails_terminally(self):
        """Regression (review): a payload bigger than the WHOLE
        handoff_max_bytes budget can never be staged; requeueing it
        would re-prefill -> overflow forever in a pure
        prefill+decode fleet, so it must fail with the reason
        surfaced to the caller."""
        router, clk = self._router(handoff_max_bytes=100.0)
        router.register_replica(1, "pre", role="prefill")
        router.submit([1, 2], request_id="huge")
        router.pull(1, max_items=1)
        assert router.complete(
            1, "huge", [], handoff=self._wire("huge", nbytes_scale=4)
        )
        rec = router.result("huge")
        assert rec["state"] == "failed"
        assert "handoff_max_bytes" in rec["error"]
        assert router.snapshot()["handoff_queue_depth"] == 0
        assert router.counters()["failed"] == 1

    def test_handoff_overflow_falls_back_to_recompute(self):
        # Base payload is 1024 B: one fits the 1500 B budget alone,
        # two do not — the second OVERFLOWS (requeued to the prompt
        # stage for recompute once staging drains, never dropped).
        router, clk = self._router(handoff_max_bytes=1500.0)
        router.register_replica(1, "pre", role="prefill")
        router.register_replica(2, "dec", role="decode")
        router.submit([1, 2], request_id="a")
        router.submit([3, 4], request_id="big")
        router.pull(1, max_items=2)
        assert router.complete(1, "a", [], handoff=self._wire("a"))
        assert router.complete(
            1, "big", [], handoff=self._wire("big")
        )
        rec = router.result("big")
        assert rec["state"] == "queued"
        assert rec["requeues"] == 1
        assert router.snapshot()["handoff_queue_depth"] == 1
        # Once a decode pull drains the store, the recompute's next
        # handoff stages cleanly.
        router.pull(2, max_items=1)
        router.pull(1, max_items=1)
        assert router.complete(
            1, "big", [], handoff=self._wire("big")
        )
        assert router.result("big")["state"] == "handoff"

    def test_handoff_accepted_after_requeue_race(self):
        """A requeue (re-registration) can beat the original prefill
        replica's handoff report; the prefill IS done, so the late
        handoff wins over the queued copy."""
        router, clk = self._router()
        router.register_replica(1, "pre", role="prefill")
        router.submit([1, 2], request_id="r")
        router.pull(1, max_items=1)
        router.register_replica(1, "pre", role="prefill")  # requeues r
        assert router.result("r")["state"] == "queued"
        assert router.complete(1, "r", [], handoff=self._wire("r"))
        assert router.result("r")["state"] == "handoff"
        # The stale queued copy cannot be double-dispatched.
        assert router.pull(1, max_items=2) == []

    def test_per_role_autoscale_grow_and_shrink(self):
        from dlrover_tpu.common.constants import NodeType

        router, clk = self._router(
            backlog_per_replica=2.0,
            handoff_backlog_per_decode=2.0,
            min_prefill=1, max_prefill=4,
            min_decode=1, max_decode=4,
        )
        router.register_replica(1, "pre", role="prefill")
        router.register_replica(2, "dec", role="decode")
        # Raw backlog grows the PREFILL role (labeled target).
        for i in range(5):
            router.submit([i], request_id=f"q{i}")
        assert router.maybe_autoscale() == "grow"
        assert (
            NodeType.REPLICA, 2, {"serving_role": "prefill"}
        ) in router.job_manager.ensured
        # Staged-handoff backlog grows the DECODE role.
        router.job_manager.ensured.clear()
        pulled = router.pull(1, max_items=5)
        for r in pulled:
            router.complete(1, r.request_id,
                            [], handoff=self._wire(r.request_id))
        assert router.snapshot()["handoff_queue_depth"] == 5
        assert router.maybe_autoscale() == "grow"
        assert (
            NodeType.REPLICA, 2, {"serving_role": "decode"}
        ) in router.job_manager.ensured
        # KV pressure on decode replicas also grows decode.
        router.job_manager.ensured.clear()
        for r in router.pull(2, max_items=5):
            router.complete(
                2, r.request_id, [1, 2], finish_reason="length"
            )
        router.report_stats(
            2, {"tokens_generated": 10, "kv": {"utilization": 0.97}}
        )
        assert router.maybe_autoscale() == "grow"
        assert (
            NodeType.REPLICA, 2, {"serving_role": "decode"}
        ) in router.job_manager.ensured
        # Idle roles shrink toward their floors, one per tick.
        router.report_stats(
            2, {"tokens_generated": 10, "kv": {"utilization": 0.1}}
        )
        router.register_replica(3, "pre2", role="prefill")
        clk[0] += 120.0
        assert router.maybe_autoscale() == "shrink"
        assert router.job_manager.retired == [3]

    def test_unhealthy_facts_carry_role(self):
        router, clk = self._router()
        router.register_replica(1, "pre", role="prefill")
        router.submit([1], request_id="r")
        router.pull(1, max_items=1)
        clk[0] += 6.0
        facts = router.unhealthy_replicas()
        assert facts and facts[0]["role"] == "prefill"


class TestEnsureRoleLabels:
    def test_labeled_targets_are_independent(self):
        from dlrover_tpu.common.constants import (
            NodeType,
            replica_node_id,
        )
        from dlrover_tpu.master.job_manager import (
            JobManager,
            Scaler,
        )

        jm = JobManager(scaler=Scaler())
        pre = jm.ensure_role(
            NodeType.REPLICA, 2,
            labels={"serving_role": "prefill"},
        )
        assert len(pre) == 2
        assert [n.id for n in pre] == [
            replica_node_id(0), replica_node_id(1)
        ]
        for n in pre:
            n.update_status("running")
        # A decode target of 2 counts ZERO of the prefill nodes and
        # claims the next free namespaced ids.
        dec = jm.ensure_role(
            NodeType.REPLICA, 2,
            labels={"serving_role": "decode"},
        )
        assert len(dec) == 2
        assert [n.id for n in dec] == [
            replica_node_id(2), replica_node_id(3)
        ]
        assert all(
            n.labels == {"serving_role": "decode"} for n in dec
        )
        # Re-asking for 2 prefill is a no-op; unlabeled count sees
        # all four alive... once the decode pair runs.
        for n in dec:
            n.update_status("running")
        assert jm.ensure_role(
            NodeType.REPLICA, 2,
            labels={"serving_role": "prefill"},
        ) == []
        assert jm.ensure_role(NodeType.REPLICA, 4) == []

    def test_replacement_inherits_role_labels(self):
        from dlrover_tpu.common.constants import (
            NodeType,
            replica_node_id,
        )
        from dlrover_tpu.master.job_manager import (
            JobManager,
            Scaler,
        )

        jm = JobManager(scaler=Scaler())
        node = jm.register_node(
            node_type=NodeType.REPLICA,
            node_id=replica_node_id(0),
            labels={"serving_role": "prefill"},
        )
        repl = jm.launch_replacement(
            node, reason="test", node_id=replica_node_id(1)
        )
        assert repl.labels == {"serving_role": "prefill"}


class TestReplicaUnhealthyDetector:
    def _monitor(self, serving):
        from dlrover_tpu.obs.health import HealthMonitor
        from dlrover_tpu.obs.timeseries import TimeSeriesStore

        clk = [2000.0]
        monitor = HealthMonitor(
            TimeSeriesStore(clock=lambda: clk[0]),
            serving=serving,
            clock=lambda: clk[0],
        )
        return monitor, clk

    def test_verdict_severity_and_resolution(self):
        facts = []

        class Provider:
            def unhealthy_replicas(self):
                return list(facts)

        monitor, clk = self._monitor(Provider())
        facts.append(
            {
                "replica_id": 4000001, "addr": "rep-1",
                "state": "ready", "stale_s": 6.0,
                "timeout_s": 5.0, "dispatched": 2,
            }
        )
        verdicts = monitor.evaluate_once()
        v = [x for x in verdicts
             if x.detector == "replica_unhealthy"]
        assert len(v) == 1 and v[0].severity == "warn"
        assert v[0].node_id == 4000001
        facts[0]["stale_s"] = 12.0  # past 2x the timeout
        v = [
            x for x in monitor.evaluate_once()
            if x.detector == "replica_unhealthy"
        ]
        assert v[0].severity == "critical"
        # Draining replicas are critical regardless of ratio.
        facts[0].update(state="draining", stale_s=6.0)
        v = [
            x for x in monitor.evaluate_once()
            if x.detector == "replica_unhealthy"
        ]
        assert v[0].severity == "critical"
        facts.clear()
        assert monitor.evaluate_once() == []
        assert any(
            h.resolved for h in monitor.history()
            if h.detector == "replica_unhealthy"
        )

    def test_broken_provider_does_not_kill_tick(self):
        class Broken:
            def unhealthy_replicas(self):
                raise RuntimeError("boom")

        monitor, _ = self._monitor(Broken())
        assert monitor.evaluate_once() == []


class FakeHealth:
    """Minimal health surface the remediation engine consumes."""

    def __init__(self):
        self.verdicts = []
        self._stamps = {}

    def active_verdicts(self):
        return list(self.verdicts)

    def action_stamp(self, key):
        return self._stamps.get(key)

    def stamp_action(self, key, ts):
        self._stamps[key] = ts


class FakeServicer:
    def __init__(self):
        self.pushed = []

    def push_action(self, node_id, action, dedupe_key=None):
        self.pushed.append((node_id, action))
        return True

    def restart_peers(self, exclude_id, dedupe_prefix=None):
        raise AssertionError(
            "a replica remediation must never bounce training peers"
        )


class FakeServing:
    def __init__(self):
        self.drained = []

    def drain_replica(self, node_id, reason=""):
        self.drained.append((node_id, reason))
        return 1


class TestServingRemediationLadder:
    """drain -> restart -> replace, driven by a persistently-sick
    replica_unhealthy verdict through real governor machinery."""

    def _engine(self):
        from dlrover_tpu.common.constants import NodeType
        from dlrover_tpu.master.job_manager import JobManager, Scaler
        from dlrover_tpu.master.remediation import RemediationEngine

        clk = [5000.0]
        jm = JobManager(scaler=Scaler())
        node = jm.register_node(
            node_type=NodeType.REPLICA, node_id=4000001,
            addr="rep-1",
        )
        assert node.type == NodeType.REPLICA
        health = FakeHealth()
        servicer = FakeServicer()
        serving = FakeServing()
        engine = RemediationEngine(
            health=health,
            job_manager=jm,
            servicer=servicer,
            serving=serving,
            min_nodes=1,
            clock=lambda: clk[0],
            config={
                "hysteresis_ticks": 2,
                "recovery_ticks": 2,
                "cooldown_s": 0.0,
                "blast_window_s": 10.0,
                "blast_max_actions": 5.0,
                "probation_s": 60.0,
            },
        )
        return engine, health, servicer, serving, jm, clk

    def _verdict(self):
        from dlrover_tpu.obs.health import (
            SEVERITY_CRITICAL,
            HealthVerdict,
        )

        return HealthVerdict(
            detector="replica_unhealthy",
            severity=SEVERITY_CRITICAL,
            message="replica stalled",
            node_id=4000001,
            host="rep-1",
        )

    def _fail_probation(self, engine, clk):
        """Advance past the probation deadline with the verdict
        still active; one tick finalizes the failure."""
        clk[0] += 61.0
        engine.tick_once()

    def test_ladder_progression(self):
        from dlrover_tpu.common.constants import (
            EventAction,
            NodeType,
        )
        from dlrover_tpu.master import remediation as R

        engine, health, servicer, serving, jm, clk = self._engine()
        health.verdicts = [self._verdict()]
        # Rung 0: drain after hysteresis (2 consecutive sick ticks).
        assert engine.tick_once() == []
        decisions = engine.tick_once()
        assert [d.action for d in decisions] == [
            R.ACTION_DRAIN_REPLICA
        ]
        assert decisions[0].outcome == R.OUTCOME_ACTED
        assert serving.drained == [
            (4000001, "replica_unhealthy")
        ]
        # Probation fails -> escalate to restart.
        self._fail_probation(engine, clk)
        assert decisions[0].outcome == R.OUTCOME_ESCALATED
        clk[0] += 1.0
        engine.tick_once()
        restart = [
            d for d in engine.decisions()
            if d.action == R.ACTION_RESTART_TRAINING
        ]
        assert restart and restart[-1].outcome == R.OUTCOME_ACTED
        assert (
            4000001, EventAction.RESTART_TRAINING.value
        ) in servicer.pushed
        # Probation fails again -> replace: cordon + ScalePlan
        # launching a REPLICA node, training world untouched
        # (FakeServicer.restart_peers raises if called).
        self._fail_probation(engine, clk)
        clk[0] += 1.0
        engine.tick_once()
        replace = [
            d for d in engine.decisions()
            if d.action == R.ACTION_CORDON_REPLACE
        ]
        assert replace and replace[-1].outcome == R.OUTCOME_ACTED
        # The replacement is REPLICA-NAMESPACED at the LOWEST free
        # index (ensure_role's policy — one id-allocation scheme):
        # the arriving replica process registers under exactly that
        # scheme, so it can claim the PENDING node. Index 1 is the
        # live (cordoned) subject, index 0 is free.
        from dlrover_tpu.common.constants import replica_node_id

        assert replace[-1].replacement_id == replica_node_id(0)
        repl = jm.get_node(replace[-1].replacement_id)
        assert repl is not None and repl.type == NodeType.REPLICA
        assert jm.get_node(4000001).cordoned
        assert len(serving.drained) == 2  # drain rung + replace
        # Final failure: rolled back (un-cordon) and alert-only —
        # no further actions ever.
        self._fail_probation(engine, clk)
        assert replace[-1].outcome == R.OUTCOME_ROLLED_BACK
        assert not jm.get_node(4000001).cordoned
        clk[0] += 1.0
        before = len(engine.decisions())
        engine.tick_once()
        engine.tick_once()
        acted_after = [
            d for d in engine.decisions()[before:]
            if d.outcome == R.OUTCOME_ACTED
        ]
        assert acted_after == []

    def test_recovery_resets_ladder(self):
        from dlrover_tpu.master import remediation as R

        engine, health, servicer, serving, jm, clk = self._engine()
        health.verdicts = [self._verdict()]
        engine.tick_once()
        decisions = engine.tick_once()
        assert decisions[0].action == R.ACTION_DRAIN_REPLICA
        # The replica recovers: verdict resolves, probation succeeds
        # after recovery_ticks healthy ticks.
        health.verdicts = []
        clk[0] += 1.0
        engine.tick_once()
        clk[0] += 1.0
        engine.tick_once()
        assert decisions[0].outcome == R.OUTCOME_RECOVERED
        # Next conviction starts at the drain rung again.
        health.verdicts = [self._verdict()]
        clk[0] += 1.0
        engine.tick_once()
        fresh = engine.tick_once()
        assert [d.action for d in fresh] == [
            R.ACTION_DRAIN_REPLICA
        ]


class TestServicerGraceful:
    def test_serve_rpcs_without_router(self):
        """A bare servicer (no serving router) answers serve RPCs
        with 'disabled', never an exception."""
        from dlrover_tpu.common import messages as msg
        from dlrover_tpu.master.job_manager import JobManager
        from dlrover_tpu.master.rendezvous import (
            ElasticRendezvous,
            NetworkCheckRendezvous,
        )
        from dlrover_tpu.master.servicer import MasterServicer
        from dlrover_tpu.master.task_manager import TaskManager

        s = MasterServicer(
            job_manager=JobManager(),
            task_manager=TaskManager(),
            elastic_rdzv=ElasticRendezvous(),
            check_rdzv=NetworkCheckRendezvous(),
        )
        assert not s._serve_submit(
            msg.ServeSubmitRequest(prompt=[1])
        ).accepted
        assert s._serve_result(
            msg.ServeResultRequest(request_id="x")
        ).state == ""
        assert s._serve_pull(
            msg.ServePullRequest(replica_id=1)
        ).items == []
        assert not s._serve_query(
            msg.ServeQueryRequest()
        ).enabled


class TestDecodeLoopHostSyncAudit:
    def test_decode_loop_sources_free_of_host_syncs(self):
        """AST tripwire (the serving satellite of the CI audit): the
        functions that BUILD the jitted serving decode/prefill
        programs must contain no host-sync calls — float(), .item(),
        np.asarray, jax.device_get, block_until_ready. The
        scheduler's step() drains sampled tokens at its boundary by
        design; the jitted program sources must not."""
        import ast
        import inspect
        import textwrap

        from dlrover_tpu.models import generate
        from dlrover_tpu.serving.scheduler import (
            ContinuousBatchingScheduler,
        )

        FORBIDDEN_CALLS = {"float", "bool"}
        FORBIDDEN_ATTRS = {
            "item", "asarray", "device_get", "block_until_ready",
            "tolist",
        }

        def audit(fn_source, where):
            tree = ast.parse(fn_source)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Name):
                    assert f.id not in FORBIDDEN_CALLS, (
                        f"{where}:{node.lineno}: host sync "
                        f"{f.id}() in the serving decode path"
                    )
                if isinstance(f, ast.Attribute):
                    assert f.attr not in FORBIDDEN_ATTRS, (
                        f"{where}:{node.lineno}: host sync "
                        f".{f.attr}() in the serving decode path"
                    )

        from dlrover_tpu.serving import handoff as handoff_mod

        for fn, where in (
            (generate.llama_decode_step_ragged,
             "llama_decode_step_ragged"),
            (generate.llama_lane_prefill_chunk,
             "llama_lane_prefill_chunk"),
            (generate._cached_attention_ragged,
             "_cached_attention_ragged"),
            (generate._rect_attention_dense,
             "_rect_attention_dense"),
            (generate._apply_rope_gathered,
             "_apply_rope_gathered"),
            (ContinuousBatchingScheduler._build_programs,
             "ContinuousBatchingScheduler._build_programs"),
            # Disaggregation: the decode replica's jitted KV-install
            # program builder must be as host-sync-free as the
            # decode step it feeds (the EXPORT path's np.asarray is
            # the prefill replica's deliberate product and lives in
            # export_handoff, outside this audit by design).
            (handoff_mod.make_install_fn,
             "handoff.make_install_fn"),
        ):
            audit(textwrap.dedent(inspect.getsource(fn)), where)


class TestServeDrill:
    def test_replica_kill_drill_selftest(self):
        """The hermetic acceptance drill: >=2 replica subprocesses on
        the CPU mesh serve synthetic traffic through one SIGKILL with
        zero dropped requests, bounded p99, the kill visible as a
        replica_unhealthy verdict + drain + requeue, and requeued
        outputs verified against the reference model."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("DLROVER_TPU_CHAOS", None)
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "serve_drill.py"),
                "--selftest",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=240,
        )
        assert proc.returncode == 0, (
            f"serve drill failed\nstdout:\n{proc.stdout}\n"
            f"stderr:\n{proc.stderr}"
        )
        assert "serve drill selftest ok" in proc.stdout

    def test_disagg_interference_drill(self):
        """The ISSUE-15 acceptance drill: (1) with a long-prompt
        storm running, disaggregated p99 stream TPOT beats colocated
        on the same workload (virtual per-replica clocks over real
        measured step costs — per-lane TPOT histogram values); (2) a
        real 2-prefill + 1-decode subprocess fleet completes every
        request through a SIGKILL of one prefill replica (zero
        drops), outputs bitwise equal to ``generate.generate``
        through the handoff, and the request trace shows the
        prefill -> handoff -> decode hop chain."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("DLROVER_TPU_CHAOS", None)
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(REPO, "tools", "serve_drill.py"),
                "--disagg",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=420,
        )
        assert proc.returncode == 0, (
            f"disagg drill failed\nstdout:\n{proc.stdout}\n"
            f"stderr:\n{proc.stderr}"
        )
        assert "disagg drill ok" in proc.stdout


def test_scheduler_rejects_non_llama_config():
    from dlrover_tpu.models import gpt

    with pytest.raises(TypeError, match="Llama-family"):
        ContinuousBatchingScheduler(
            {}, gpt.GPTConfig(n_layer=1, n_head=2, n_embd=8), lanes=1
        )
