"""Auto TP placement planner (ref mip_tp_planner.py:1-496).

The chain DP must rediscover the Megatron pattern from first
principles (costs only), handle memory-pressure fallbacks, and emit
GSPMD-consumable PartitionSpecs that actually run on a mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlrover_tpu.accelerate.tp_planner import (
    Op,
    plan_chain,
    plan_model,
    plan_transformer_block,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.shard_map_compat import use_mesh


class TestChainDP:
    def test_mlp_discovers_column_then_row(self):
        """wi column + wo row = one psum, zero gathers — the Megatron
        optimum. The DP must find it from costs alone."""
        plan = plan_chain(
            [
                Op("wi", "matmul", (512, 2048)),
                Op("gelu", "elementwise"),
                Op("wo", "matmul", (2048, 512)),
            ],
            tensor_size=4,
            activation_bytes=1e6,
        )
        strategies = {p.name: p.strategy for p in plan}
        assert strategies["wi"] == "column"
        assert strategies["wo"] == "row"
        # elementwise runs on the sharded activation — no gather
        gelu = next(p for p in plan if p.name == "gelu")
        assert gelu.in_state == "S" and gelu.out_state == "S"

    def test_tiny_weights_prefer_replication(self):
        """When weights are tiny relative to activations, sharding
        buys nothing and the psum costs real bytes: replicate."""
        plan = plan_chain(
            [
                Op("w1", "matmul", (8, 8)),
                Op("w2", "matmul", (8, 8)),
            ],
            tensor_size=4,
            activation_bytes=1e9,
        )
        assert all(p.strategy == "replicated" for p in plan)

    def test_reduce_forces_gather_cost_accounting(self):
        """A reduce (loss) needs the replicated state; ending sharded
        must pay the gather, so a final row matmul (free psum exit)
        beats column+gather."""
        plan = plan_chain(
            [
                Op("wi", "matmul", (512, 2048)),
                Op("wo", "matmul", (2048, 512)),
                Op("loss", "reduce"),
            ],
            tensor_size=8,
            activation_bytes=1e6,
        )
        assert plan[-1].out_state == "R"
        strategies = {p.name: p.strategy for p in plan}
        assert strategies["wo"] == "row"

    def test_tensor_size_one_is_noop(self):
        plan = plan_chain(
            [Op("w", "matmul", (64, 64))], 1, 1e6
        )
        assert plan[0].spec == P(None, None)


class TestTransformerBlock:
    def test_block_matches_megatron_hand_rules(self):
        specs = plan_transformer_block(
            d_model=512, d_ff=2048, n_heads=8, tensor_size=4,
            batch_tokens=8192,
        )
        assert specs["wqkv"] == P(None, "tensor")
        assert specs["wo"] == P("tensor", None)
        assert specs["wi"] == P(None, "tensor")
        assert specs["wo_mlp"] == P("tensor", None)


class TestPlanModel:
    def test_fsdp_pass_bounds_memory(self):
        shapes = {
            "wi": (512, 2048),
            "wo": (2048, 512),
            "emb": (50304, 512),  # huge, not in the TP chain
        }
        chain = [
            Op("wi", "matmul", (512, 2048)),
            Op("gelu", "elementwise"),
            Op("wo", "matmul", (2048, 512)),
        ]
        # budget forces fsdp on the embedding
        specs = plan_model(
            shapes, chain, tensor_size=4, fsdp_size=8,
            batch_tokens=8192, hbm_budget_bytes=20e6,
        )
        assert specs["wi"] == P(None, "tensor")
        assert "fsdp" in tuple(specs["emb"])

    def test_unlimited_budget_leaves_non_chain_weights_alone(self):
        shapes = {"wi": (64, 256), "emb": (1000, 64)}
        chain = [Op("wi", "matmul", (64, 256))]
        specs = plan_model(shapes, chain, tensor_size=2)
        assert "emb" not in specs

    def test_planned_specs_run_on_a_real_mesh(self):
        """End to end: plan, shard, run an MLP under jit on the
        4-way tensor mesh and match the unsharded computation."""
        mesh = build_mesh(
            MeshConfig(tensor=4), devices=jax.devices()[:4]
        )
        d, ff, toks = 64, 256, 32
        key = jax.random.PRNGKey(0)
        k1, k2, kx = jax.random.split(key, 3)
        params = {
            "wi": jax.random.normal(k1, (d, ff)) * 0.1,
            "wo": jax.random.normal(k2, (ff, d)) * 0.1,
        }
        chain = [
            Op("wi", "matmul", (d, ff)),
            Op("gelu", "elementwise"),
            Op("wo", "matmul", (ff, d)),
        ]
        specs = plan_model(
            dict(wi=(d, ff), wo=(ff, d)), chain, tensor_size=4,
            batch_tokens=toks,
        )
        x = jax.random.normal(kx, (toks, d))

        def mlp(p, x):
            return jax.nn.gelu(x @ p["wi"]) @ p["wo"]

        want = mlp(params, x)
        sharded = {
            name: jax.device_put(
                arr, NamedSharding(mesh, specs[name])
            )
            for name, arr in params.items()
        }
        with use_mesh(mesh):
            got = jax.jit(mlp)(sharded, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
        )
