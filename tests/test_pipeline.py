"""Pipeline parallelism: GPipe schedule over the pipe mesh axis."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_train,
    split_stages,
    split_stages_interleaved,
)


def _stage_fn(params, x):
    # one "layer": linear + gelu residual, same in/out shape
    for w, b in zip(params["w"], params["b"]):
        x = x + jax.nn.gelu(x @ w + b)
    return x


def _make_params(key, n_stages, layers_per_stage, d):
    L = n_stages * layers_per_stage
    kw, kb = jax.random.split(key)
    w = jax.random.normal(kw, (L, d, d), jnp.float32) * 0.1
    b = jax.random.normal(kb, (L, d), jnp.float32) * 0.01
    return {"w": w, "b": b}


def _serial_apply(params, microbatches):
    def full(x):
        return _stage_fn(params, x)

    return jax.vmap(full)(microbatches)


@pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (2, 6)])
def test_pipeline_matches_serial(n_stages, n_micro):
    d, mb = 16, 4
    params = _make_params(jax.random.PRNGKey(0), n_stages, 2, d)
    staged = split_stages(params, n_stages)
    mesh = build_mesh(
        MeshConfig(pipe=n_stages), devices=jax.devices()[:n_stages]
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (n_micro, mb, d))

    apply_fn = pipeline_apply(mesh, _stage_fn)
    staged_sharded = jax.device_put(
        staged, NamedSharding(mesh, P("pipe"))
    )
    out = jax.jit(apply_fn)(staged_sharded, x)
    ref = _serial_apply(params, x)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_pipeline_single_stage_fallback():
    d = 8
    params = _make_params(jax.random.PRNGKey(0), 1, 2, d)
    staged = split_stages(params, 1)
    mesh = build_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, d))
    out = pipeline_apply(mesh, _stage_fn)(staged, x)
    np.testing.assert_allclose(
        out, _serial_apply(params, x), atol=1e-5
    )


def test_pipeline_gradients_match_serial():
    """jax.grad through the scan gives the reverse pipeline; grads
    must equal the serial model's."""
    n_stages, d, mb, n_micro = 4, 8, 2, 4
    params = _make_params(jax.random.PRNGKey(2), n_stages, 1, d)
    staged = split_stages(params, n_stages)
    mesh = build_mesh(
        MeshConfig(pipe=n_stages), devices=jax.devices()[:n_stages]
    )
    x = jax.random.normal(jax.random.PRNGKey(3), (n_micro, mb, d))
    apply_fn = pipeline_apply(mesh, _stage_fn)

    def pipe_loss(staged_params):
        return jnp.mean(apply_fn(staged_params, x) ** 2)

    def serial_loss(params):
        return jnp.mean(_serial_apply(params, x) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(
        jax.device_put(staged, NamedSharding(mesh, P("pipe")))
    )
    g_serial = jax.grad(serial_loss)(params)
    g_serial_staged = split_stages(g_serial, n_stages)
    for a, b in zip(
        jax.tree.leaves(g_pipe), jax.tree.leaves(g_serial_staged)
    ):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)


def _chunk_fn(params, x):
    return _stage_fn(params, x)


def _loss_fn(y, tgt):
    return jnp.mean((y - tgt) ** 2)


def _serial_loss(params, microbatches, targets):
    y = _serial_apply(params, microbatches)
    return jnp.mean(
        jax.vmap(_loss_fn)(y, targets)
    )


class Test1F1B:
    @pytest.mark.parametrize(
        "n_stages,v_chunks,n_micro",
        [(4, 1, 4), (4, 1, 8), (2, 1, 6), (2, 2, 4), (2, 2, 8),
         (4, 2, 8)],
    )
    def test_1f1b_loss_and_grad_parity(
        self, n_stages, v_chunks, n_micro
    ):
        """1F1B (and interleaved) loss + grads == serial autodiff."""
        d, mb = 8, 2
        layers = n_stages * v_chunks  # 1 layer per chunk
        params = _make_params(jax.random.PRNGKey(6), layers, 1, d)
        staged = split_stages_interleaved(params, n_stages, v_chunks)
        mesh = build_mesh(
            MeshConfig(pipe=n_stages),
            devices=jax.devices()[:n_stages],
        )
        x = jax.random.normal(
            jax.random.PRNGKey(7), (n_micro, mb, d)
        )
        tgt = jax.random.normal(
            jax.random.PRNGKey(8), (n_micro, mb, d)
        )
        step = pipeline_train(
            mesh, _chunk_fn, _loss_fn, v_chunks=v_chunks
        )
        sharded = jax.device_put(
            staged, NamedSharding(mesh, P("pipe"))
        )
        loss, grads = jax.jit(step)(sharded, x, tgt)

        ref_loss, ref_grads = jax.value_and_grad(_serial_loss)(
            params, x, tgt
        )
        np.testing.assert_allclose(
            float(loss), float(ref_loss), rtol=1e-5
        )
        ref_staged = split_stages_interleaved(
            ref_grads, n_stages, v_chunks
        )
        for a, b in zip(
            jax.tree.leaves(grads), jax.tree.leaves(ref_staged)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3
            )

    def test_1f1b_single_stage_fallback(self):
        d = 8
        params = _make_params(jax.random.PRNGKey(9), 2, 1, d)
        staged = split_stages_interleaved(params, 1, 2)
        mesh = build_mesh(
            MeshConfig(data=2), devices=jax.devices()[:2]
        )
        x = jax.random.normal(jax.random.PRNGKey(10), (4, 2, d))
        tgt = jax.random.normal(jax.random.PRNGKey(11), (4, 2, d))
        step = pipeline_train(mesh, _chunk_fn, _loss_fn, v_chunks=2)
        loss, grads = step(staged, x, tgt)
        ref_loss, ref_grads = jax.value_and_grad(_serial_loss)(
            params, x, tgt
        )
        np.testing.assert_allclose(
            float(loss), float(ref_loss), rtol=1e-5
        )
        ref_staged = split_stages_interleaved(ref_grads, 1, 2)
        for a, b in zip(
            jax.tree.leaves(grads), jax.tree.leaves(ref_staged)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3
            )

    def test_1f1b_composes_with_data_parallel(self):
        """pipe=2 x data=2, microbatch batch dim sharded over data:
        grads/loss must be the global (all-shard) means."""
        n_stages, d, mb, n_micro = 2, 8, 4, 4
        params = _make_params(jax.random.PRNGKey(15), n_stages, 1, d)
        staged = split_stages_interleaved(params, n_stages, 1)
        mesh = build_mesh(
            MeshConfig(data=2, pipe=n_stages),
            devices=jax.devices()[:4],
        )
        x = jax.random.normal(
            jax.random.PRNGKey(16), (n_micro, mb, d)
        )
        tgt = jax.random.normal(
            jax.random.PRNGKey(17), (n_micro, mb, d)
        )
        step = pipeline_train(
            mesh, _chunk_fn, _loss_fn,
            batch_spec=P(("data", "fsdp")),
        )
        sharded = jax.device_put(
            staged, NamedSharding(mesh, P("pipe"))
        )
        xs = jax.device_put(
            x, NamedSharding(mesh, P(None, ("data", "fsdp")))
        )
        ts = jax.device_put(
            tgt, NamedSharding(mesh, P(None, ("data", "fsdp")))
        )
        loss, grads = jax.jit(step)(sharded, xs, ts)
        ref_loss, ref_grads = jax.value_and_grad(_serial_loss)(
            params, x, tgt
        )
        np.testing.assert_allclose(
            float(loss), float(ref_loss), rtol=1e-5
        )
        ref_staged = split_stages_interleaved(ref_grads, n_stages, 1)
        for a, b in zip(
            jax.tree.leaves(grads), jax.tree.leaves(ref_staged)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3
            )

    def test_1f1b_rejects_indivisible_microbatches(self):
        mesh = build_mesh(
            MeshConfig(pipe=4), devices=jax.devices()[:4]
        )
        params = _make_params(jax.random.PRNGKey(0), 4, 1, 8)
        staged = split_stages_interleaved(params, 4, 1)
        x = jnp.zeros((6, 2, 8))  # 6 % 4 != 0
        step = pipeline_train(mesh, _chunk_fn, _loss_fn)
        with pytest.raises(Exception):
            jax.jit(step)(staged, x, x)

    def test_1f1b_stash_memory_beats_gpipe(self):
        """The schedule's carried state is O(n_stages) microbatch
        inputs; GPipe-via-grad stashes O(M) scan residuals. Compare
        XLA's own temp-memory accounting at M=16."""
        n_stages, d, mb, n_micro = 4, 32, 8, 16
        params = _make_params(jax.random.PRNGKey(12), n_stages, 1, d)
        mesh = build_mesh(
            MeshConfig(pipe=n_stages),
            devices=jax.devices()[:n_stages],
        )
        x = jax.random.normal(
            jax.random.PRNGKey(13), (n_micro, mb, d)
        )
        tgt = jax.random.normal(
            jax.random.PRNGKey(14), (n_micro, mb, d)
        )

        step_1f1b = pipeline_train(mesh, _chunk_fn, _loss_fn)
        staged = split_stages_interleaved(params, n_stages, 1)
        gpipe_apply = pipeline_apply(mesh, _stage_fn, remat=False)
        gpipe_staged = split_stages(params, n_stages)

        def gpipe_step(p, mbs, tgts):
            def loss(pp):
                y = gpipe_apply(pp, mbs)
                return jnp.mean(jax.vmap(_loss_fn)(y, tgts))

            return jax.value_and_grad(loss)(p)

        c1 = jax.jit(step_1f1b).lower(staged, x, tgt).compile()
        c2 = jax.jit(gpipe_step).lower(gpipe_staged, x, tgt).compile()
        m1 = c1.memory_analysis()
        m2 = c2.memory_analysis()
        if m1 is None or m2 is None:
            pytest.skip("backend lacks memory analysis")
        assert m1.temp_size_in_bytes < m2.temp_size_in_bytes, (
            m1.temp_size_in_bytes,
            m2.temp_size_in_bytes,
        )


def test_pipeline_composes_with_data_parallel():
    """pipe=2 x data=2: microbatch batch dim sharded over data."""
    n_stages, d = 2, 8
    params = _make_params(jax.random.PRNGKey(4), n_stages, 1, d)
    staged = split_stages(params, n_stages)
    mesh = build_mesh(
        MeshConfig(data=2, pipe=n_stages), devices=jax.devices()[:4]
    )
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 4, d))
    apply_fn = pipeline_apply(
        mesh, _stage_fn, batch_spec=P(("data", "fsdp"))
    )
    staged_sharded = jax.device_put(
        staged, NamedSharding(mesh, P("pipe"))
    )
    x_sharded = jax.device_put(
        x, NamedSharding(mesh, P(None, ("data", "fsdp")))
    )
    out = jax.jit(apply_fn)(staged_sharded, x_sharded)
    np.testing.assert_allclose(
        out, _serial_apply(params, x), atol=1e-4, rtol=1e-4
    )
