"""Pipeline parallelism: GPipe schedule over the pipe mesh axis."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.pipeline import pipeline_apply, split_stages


def _stage_fn(params, x):
    # one "layer": linear + gelu residual, same in/out shape
    for w, b in zip(params["w"], params["b"]):
        x = x + jax.nn.gelu(x @ w + b)
    return x


def _make_params(key, n_stages, layers_per_stage, d):
    L = n_stages * layers_per_stage
    kw, kb = jax.random.split(key)
    w = jax.random.normal(kw, (L, d, d), jnp.float32) * 0.1
    b = jax.random.normal(kb, (L, d), jnp.float32) * 0.01
    return {"w": w, "b": b}


def _serial_apply(params, microbatches):
    def full(x):
        return _stage_fn(params, x)

    return jax.vmap(full)(microbatches)


@pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (2, 6)])
def test_pipeline_matches_serial(n_stages, n_micro):
    d, mb = 16, 4
    params = _make_params(jax.random.PRNGKey(0), n_stages, 2, d)
    staged = split_stages(params, n_stages)
    mesh = build_mesh(
        MeshConfig(pipe=n_stages), devices=jax.devices()[:n_stages]
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (n_micro, mb, d))

    apply_fn = pipeline_apply(mesh, _stage_fn)
    staged_sharded = jax.device_put(
        staged, NamedSharding(mesh, P("pipe"))
    )
    out = jax.jit(apply_fn)(staged_sharded, x)
    ref = _serial_apply(params, x)
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_pipeline_single_stage_fallback():
    d = 8
    params = _make_params(jax.random.PRNGKey(0), 1, 2, d)
    staged = split_stages(params, 1)
    mesh = build_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, d))
    out = pipeline_apply(mesh, _stage_fn)(staged, x)
    np.testing.assert_allclose(
        out, _serial_apply(params, x), atol=1e-5
    )


def test_pipeline_gradients_match_serial():
    """jax.grad through the scan gives the reverse pipeline; grads
    must equal the serial model's."""
    n_stages, d, mb, n_micro = 4, 8, 2, 4
    params = _make_params(jax.random.PRNGKey(2), n_stages, 1, d)
    staged = split_stages(params, n_stages)
    mesh = build_mesh(
        MeshConfig(pipe=n_stages), devices=jax.devices()[:n_stages]
    )
    x = jax.random.normal(jax.random.PRNGKey(3), (n_micro, mb, d))
    apply_fn = pipeline_apply(mesh, _stage_fn)

    def pipe_loss(staged_params):
        return jnp.mean(apply_fn(staged_params, x) ** 2)

    def serial_loss(params):
        return jnp.mean(_serial_apply(params, x) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(
        jax.device_put(staged, NamedSharding(mesh, P("pipe")))
    )
    g_serial = jax.grad(serial_loss)(params)
    g_serial_staged = split_stages(g_serial, n_stages)
    for a, b in zip(
        jax.tree.leaves(g_pipe), jax.tree.leaves(g_serial_staged)
    ):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)


def test_pipeline_composes_with_data_parallel():
    """pipe=2 x data=2: microbatch batch dim sharded over data."""
    n_stages, d = 2, 8
    params = _make_params(jax.random.PRNGKey(4), n_stages, 1, d)
    staged = split_stages(params, n_stages)
    mesh = build_mesh(
        MeshConfig(data=2, pipe=n_stages), devices=jax.devices()[:4]
    )
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 4, d))
    apply_fn = pipeline_apply(
        mesh, _stage_fn, batch_spec=P(("data", "fsdp"))
    )
    staged_sharded = jax.device_put(
        staged, NamedSharding(mesh, P("pipe"))
    )
    x_sharded = jax.device_put(
        x, NamedSharding(mesh, P(None, ("data", "fsdp")))
    )
    out = jax.jit(apply_fn)(staged_sharded, x_sharded)
    np.testing.assert_allclose(
        out, _serial_apply(params, x), atol=1e-4, rtol=1e-4
    )
