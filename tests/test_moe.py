"""MoE gating + expert-parallel layer tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlrover_tpu.models.moe import (
    MoEConfig,
    init_moe_params,
    moe_logical_axes,
    moe_mlp,
    switch_gating,
    top_k_gating,
)
from dlrover_tpu.parallel.mesh import MeshConfig, build_mesh
from dlrover_tpu.parallel.shard_map_compat import use_mesh
from dlrover_tpu.parallel.sharding import tree_shardings


def test_switch_gating_routes_to_argmax():
    logits = jnp.asarray(
        [[2.0, 0.0, 0.0], [0.0, 3.0, 0.0], [0.0, 0.0, 1.0]]
    )
    dispatch, combine, metrics = switch_gating(logits, capacity=2)
    # each token routed to its argmax expert at slot 0
    for tok, exp in [(0, 0), (1, 1), (2, 2)]:
        assert bool(dispatch[tok, exp, 0])
    assert float(metrics["dropped_fraction"]) == 0.0


def test_topk_gating_two_experts_per_token():
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (16, 4))
    dispatch, combine, _ = top_k_gating(logits, top_k=2, capacity=16)
    per_token = jnp.sum(dispatch, axis=(1, 2))
    np.testing.assert_array_equal(per_token, np.full(16, 2))
    # combine weights are the softmax probs of the chosen experts
    probs = jax.nn.softmax(logits, axis=-1)
    tok0_experts = np.argsort(np.asarray(logits[0]))[-2:]
    got = float(jnp.sum(combine[0]))
    want = float(probs[0, tok0_experts[0]] + probs[0, tok0_experts[1]])
    assert abs(got - want) < 1e-5


def test_capacity_drops_overflow():
    # all tokens want expert 0; capacity 2 keeps exactly 2
    logits = jnp.tile(jnp.asarray([[5.0, 0.0]]), (8, 1))
    dispatch, combine, metrics = switch_gating(logits, capacity=2)
    assert int(jnp.sum(dispatch[:, 0, :])) == 2
    assert float(metrics["dropped_fraction"]) == pytest.approx(0.75)


def test_moe_mlp_forward_and_aux_loss():
    cfg = MoEConfig(n_embd=32, n_experts=4, top_k=2, dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
    y, aux = moe_mlp(params, x, cfg)
    assert y.shape == x.shape
    assert jnp.all(jnp.isfinite(y))
    assert float(aux) > 0.0  # aux losses active


def test_moe_grads_flow_to_all_param_groups():
    cfg = MoEConfig(n_embd=16, n_experts=4, top_k=2, dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))

    def loss(p):
        y, aux = moe_mlp(p, x, cfg)
        return jnp.mean(y**2) + aux

    grads = jax.grad(loss)(params)
    for name in ("router", "wi", "wo"):
        assert float(jnp.max(jnp.abs(grads[name]))) > 0.0, name


def test_moe_expert_parallel_on_mesh():
    """Expert-sharded weights + data-sharded tokens: GSPMD compiles the
    dispatch einsums with collectives; results match single-device."""
    mesh = build_mesh(
        MeshConfig(data=2, expert=4), devices=jax.devices()[:8]
    )
    cfg = MoEConfig(n_embd=32, n_experts=4, top_k=2, dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    shardings = tree_shardings(mesh, moe_logical_axes())
    params_sharded = jax.tree.map(
        lambda p, s: jax.device_put(p, s), params, shardings
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 32))
    x_sharded = jax.device_put(
        x, NamedSharding(mesh, P(("data", "fsdp"), None, None))
    )

    y_ref, aux_ref = moe_mlp(params, x, cfg)
    with use_mesh(mesh):
        y, aux = jax.jit(lambda p, x: moe_mlp(p, x, cfg))(
            params_sharded, x_sharded
        )
    np.testing.assert_allclose(y, y_ref, atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(aux, aux_ref, rtol=1e-5)


@pytest.mark.slow
def test_moe_expert_parallel_composes_with_seq_ring():
    """EP x SP co-activation (no prior test ran both at once): a
    Mixtral-shaped Llama-MoE trains one step on a data x seq x expert
    mesh with ring attention over ``seq`` and experts sharded over
    ``expert``; the loss must match the single-device oracle."""
    import functools

    import optax

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel.seq_attention import make_seq_attention
    from dlrover_tpu.trainer.step import (
        make_sharded_init,
        make_train_step,
        shard_batch,
    )

    mesh = build_mesh(
        MeshConfig(data=2, seq=2, expert=2), devices=jax.devices()[:8]
    )
    cfg = llama.LlamaConfig.moe_tiny()
    attn = make_seq_attention(mesh, causal=True, seq_impl="ring")
    loss = functools.partial(llama.loss_fn, cfg=cfg, attn_fn=attn)
    opt = optax.adamw(1e-3)
    init, _ = make_sharded_init(
        mesh,
        functools.partial(llama.init_params, cfg=cfg),
        llama.param_logical_axes(cfg),
        opt,
    )
    params, opt_state = init(jax.random.PRNGKey(0))
    step = make_train_step(mesh, loss, opt)
    tok = jax.random.randint(
        jax.random.PRNGKey(2), (4, cfg.block_size), 0, cfg.vocab_size
    )
    tgt = jnp.roll(tok, -1, axis=1)

    # Single-device oracle from the same init, BEFORE the donating
    # step consumes the buffers.
    dense_params = llama.init_params(jax.random.PRNGKey(0), cfg)
    want = float(llama.loss_fn(dense_params, tok, tgt, cfg=cfg))

    stok, stgt = shard_batch(mesh, tok, tgt)
    params, opt_state, m = step(params, opt_state, stok, stgt)
    got = float(m["loss"])
    assert got == got, "EP x SP loss is NaN"
    np.testing.assert_allclose(got, want, rtol=5e-4)


def test_moe_deterministic_under_jit():
    cfg = MoEConfig(n_embd=16, n_experts=2, top_k=1, dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 16))
    y1, _ = jax.jit(lambda p, x: moe_mlp(p, x, cfg))(params, x)
    y2, _ = moe_mlp(params, x, cfg)
    np.testing.assert_allclose(y1, y2, atol=1e-6)
