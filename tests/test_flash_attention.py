"""Pallas flash attention vs. reference einsum attention (fwd + grads).

Runs the kernel in interpreter mode on the CPU test mesh (conftest sets
JAX_PLATFORMS=cpu), exercising the exact code path that compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.models.gpt import _default_attention
from dlrover_tpu.ops.flash_attention import flash_attention


def _rand_qkv(key, b, t, h, d, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("t", [128, 256])
def test_forward_matches_reference(causal, t):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), 2, t, 2, 64)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = _default_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_unpadded_vs_padded_seq():
    # t=192 pads to 256 internally; padded keys must not leak in.
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), 1, 192, 2, 64)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _default_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("t", [520, 1000, 1024, 1536, 2048])
def test_default_block_sizes_pad_stays_bounded(t):
    """Regression: unequal default blocks once padded to
    lcm(block_q, block_k), which explodes for t=520 (lcm 33280).
    Defaults must never pad a sequence by more than one block."""
    import math

    from dlrover_tpu.ops.flash_attention import default_block_sizes

    bq, bk = default_block_sizes(t)
    pad = (-t) % math.lcm(bq, bk)
    assert pad < max(bq, bk)


def test_distinct_bwd_blocks_grads_match():
    """block_q_bwd/block_k_bwd different from the forward blocks must
    produce identical gradients (only tiling changes)."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), 1, 256, 2, 32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.square(fn(q, k, v)))

    base = loss(
        lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128,
            interpret=True,
        )
    )
    tuned = loss(
        lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128,
            block_q_bwd=64, block_k_bwd=256, interpret=True,
        )
    )
    g1 = jax.grad(base, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(tuned, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5
        )


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), 1, 128, 2, 64)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = _default_attention(q, k, v, causal=causal)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-4)


def test_bf16_inputs():
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), 1, 128, 2, 64, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = _default_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        out.astype(jnp.float32),
        ref.astype(jnp.float32),
        atol=3e-2,
        rtol=3e-2,
    )


def test_jit_and_grad_under_jit():
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), 1, 128, 1, 64)

    @jax.jit
    def step(q, k, v):
        def loss(q):
            return jnp.mean(
                flash_attention(q, k, v, causal=True, interpret=True) ** 2
            )

        return jax.value_and_grad(loss)(q)

    val, grad = step(q, k, v)
    assert jnp.isfinite(val)
    assert bool(jnp.all(jnp.isfinite(grad)))


def test_unequal_blocks_no_dropped_keys():
    # Regression: t=96 with block_q=128 (clamped to 96), block_k=64
    # must pad to lcm and visit every key block.
    q, k, v = _rand_qkv(jax.random.PRNGKey(5), 1, 96, 2, 64)
    out = flash_attention(
        q, k, v, causal=False, block_q=128, block_k=64, interpret=True
    )
    ref = _default_attention(q, k, v, causal=False)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_oversized_explicit_blocks_clamp_to_power_of_two():
    """Regression: explicit block_k=1024 at t=520 used to clamp to 520,
    tripping the divisibility-chain guard for a call that worked before
    the guard existed. Oversized blocks now clamp to the largest power
    of two <= t and the call must succeed and match the reference."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(5), 1, 520, 2, 64)
    out = flash_attention(
        q, k, v, causal=True, block_q=512, block_k=1024, interpret=True
    )
    ref = _default_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_oversized_block_with_tiny_sequence():
    """block_k=1024 at t=20 (default block_q=t): the oversized block
    must clamp to the padded length, not to a power of two that is
    coprime with the non-power-of-two default block_q."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(6), 1, 20, 2, 64)
    out = flash_attention(
        q, k, v, causal=True, block_k=1024, interpret=True
    )
    ref = _default_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# Sliding-window attention (Mistral-style band)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "t,window,bq,bk",
    [
        (256, 64, 128, 128),   # band narrower than a block
        (256, 100, 64, 64),    # band not a block multiple
        (256, 200, 128, 64),   # band wider than a block, unequal tiles
        (192, 64, 128, 64),    # padded sequence (192 -> 256) + window
        (128, 1, 64, 64),      # degenerate: each query sees only itself
    ],
)
def test_window_forward_matches_reference(t, window, bq, bk):
    q, k, v = _rand_qkv(jax.random.PRNGKey(10), 2, t, 2, 64)
    out = flash_attention(
        q, k, v, causal=True, window=window, block_q=bq, block_k=bk,
        interpret=True,
    )
    ref = _default_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_window_band_starts_beyond_first_executed_block():
    """Regression guard for the fully-masked-row hazard: with
    block_q=128 and window=32, the last rows of a q block have bands
    starting several kv blocks after the block-skip's earliest
    admitted block (which is chosen for the FIRST row). Fully-masked
    rows in executed blocks must contribute exp(0)=1 to nothing."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(11), 1, 256, 2, 32)
    out = flash_attention(
        q, k, v, causal=True, window=32, block_q=128, block_k=32,
        interpret=True,
    )
    ref = _default_attention(q, k, v, causal=True, window=32)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [48, 128])
def test_window_gradients_match_reference(window):
    q, k, v = _rand_qkv(jax.random.PRNGKey(12), 1, 192, 2, 32)

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, causal=True, window=window, block_q=64,
            block_k=64, interpret=True,
        )
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = _default_attention(q, k, v, causal=True, window=window)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-4)


def test_window_wider_than_sequence_is_plain_causal():
    q, k, v = _rand_qkv(jax.random.PRNGKey(13), 1, 128, 2, 64)
    wide = flash_attention(
        q, k, v, causal=True, window=4096, interpret=True
    )
    plain = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(wide, plain, atol=0, rtol=0)


def test_window_requires_causal():
    q, k, v = _rand_qkv(jax.random.PRNGKey(14), 1, 64, 2, 32)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(
            q, k, v, causal=False, window=16, interpret=True
        )


def test_window_with_lse_matches_and_grads():
    """return_lse path (ring-attention ingredient) with a window: lse
    must equal the reference band logsumexp and stay differentiable."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(15), 1, 128, 2, 32)

    o, lse = flash_attention(
        q, k, v, causal=True, window=48, block_q=64, block_k=64,
        interpret=True, return_lse=True,
    )
    # Reference lse over the band.
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / np.sqrt(d)
    pos = jnp.arange(q.shape[1])
    mask = (pos[:, None] >= pos[None, :]) & (
        (pos[:, None] - pos[None, :]) < 48
    )
    s = jnp.where(mask[None, None], s, -1e30)
    ref_lse = jax.nn.logsumexp(s, axis=-1)
    np.testing.assert_allclose(lse, ref_lse, atol=2e-5, rtol=2e-5)

    def loss(q, k, v):
        o, lse = flash_attention(
            q, k, v, causal=True, window=48, block_q=64, block_k=64,
            interpret=True, return_lse=True,
        )
        return jnp.sum(jnp.sin(o)) + jnp.sum(jnp.cos(lse))

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert bool(jnp.all(jnp.isfinite(g)))


def test_cfg_attn_blocks_pin_flows_to_kernel():
    """GPTConfig.attn_blocks (the autotune pin) must reach the flash
    kernel call and produce reference-equal output."""
    import dataclasses

    from dlrover_tpu.models import gpt

    cfg = dataclasses.replace(
        gpt.GPTConfig.gpt2(), use_flash_attention=True,
        attn_blocks=(64, 128, 64, 64),
    )
    attn = gpt.default_attention_for(cfg)
    assert attn.keywords["block_q"] == 64
    assert attn.keywords["block_k"] == 128
    assert attn.keywords["block_q_bwd"] == 64
    assert attn.keywords["block_k_bwd"] == 64
    q, k, v = _rand_qkv(jax.random.PRNGKey(16), 1, 128, 2, 32)
    out = attn(q, k, v, interpret=True)
    ref = _default_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
