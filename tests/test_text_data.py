"""Text preparation: tokenizer roundtrip, bin format, packed blocks."""

import numpy as np
import pytest

from dlrover_tpu.data.text import (
    ByteTokenizer,
    PackedDataset,
    prepare_text_file,
    write_token_bin,
)


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    s = "hello, TPU é世界"
    ids = tok.encode(s)
    assert ids.dtype == np.uint16
    assert tok.decode(ids) == s


def test_bin_format_matches_nanogpt(tmp_path):
    """Raw little-endian uint16 — the exact layout nanoGPT memmaps."""
    p = str(tmp_path / "t.bin")
    n = write_token_bin(p, ["abc"])
    assert n == 3
    raw = np.fromfile(p, np.uint16)
    np.testing.assert_array_equal(raw, [97, 98, 99])
    # append mode extends
    write_token_bin(p, ["d"], append=True)
    assert len(np.fromfile(p, np.uint16)) == 4


def test_packed_dataset_blocks(tmp_path):
    p = str(tmp_path / "t.bin")
    text = "".join(chr(65 + (i % 26)) for i in range(1000))
    prepare_text_file(str(_write(tmp_path, text)), p)
    ds = PackedDataset(p, block_size=64)
    assert len(ds) == (1000 - 65) // 64 + 1
    tokens, targets = ds[0]
    assert tokens.shape == targets.shape == (64,)
    np.testing.assert_array_equal(tokens[1:], targets[:-1])
    # disjoint blocks: block 1 starts where block 0 ended
    t1, _ = ds[1]
    assert t1[0] == targets[63]
    with pytest.raises(IndexError):
        ds[len(ds)]


def test_packed_dataset_stride_overlap(tmp_path):
    p = str(tmp_path / "t.bin")
    write_token_bin(p, ["x" * 300])
    ds = PackedDataset(p, block_size=128, stride=32)
    assert len(ds) == (300 - 129) // 32 + 1
    a, _ = ds[0]
    b, _ = ds[1]
    np.testing.assert_array_equal(a[32:], b[:-32])


def test_uint32_vocab_roundtrips_via_sidecar(tmp_path):
    """A >65536-vocab tokenizer writes uint32; PackedDataset reads
    the sidecar and never misinterprets the bin as uint16."""

    class BigVocabTok:
        vocab_size = 150_000

        def encode(self, text):
            return np.array(
                [100_000 + ord(c) for c in text], np.uint32
            )

    p = str(tmp_path / "big.bin")
    write_token_bin(p, ["abcd" * 50], tokenizer=BigVocabTok())
    ds = PackedDataset(p, block_size=16)
    tokens, _ = ds[0]
    assert int(tokens[0]) == 100_000 + ord("a")
    assert tokens.max() < 150_000


def test_trains_through_trainer(tmp_path):
    """PackedDataset plugs into the high-level Trainer unchanged."""
    import functools

    from dlrover_tpu.accelerate import Strategy
    from dlrover_tpu.models import gpt
    from dlrover_tpu.trainer.trainer import Trainer, TrainingArguments

    cfg = gpt.GPTConfig(
        vocab_size=256, block_size=32, n_layer=1, n_head=2, n_embd=32,
        dtype=np.float32, remat=False,
    )
    p = str(tmp_path / "corpus.bin")
    write_token_bin(p, ["the quick brown fox " * 200])
    ds = PackedDataset(p, block_size=cfg.block_size)
    args = TrainingArguments(
        max_steps=2,
        global_batch_size=8,
        micro_batch_size=4,
        checkpoint_dir=str(tmp_path / "ck"),
        save_steps=0,
        strategy=Strategy(
            mesh_shape=(("data", 4),), dtype="float32",
            micro_batch_size=4,
        ),
    )
    out = Trainer(
        functools.partial(gpt.init_params, cfg=cfg),
        functools.partial(gpt.loss_fn, cfg=cfg),
        gpt.param_logical_axes(cfg),
        ds,
        args,
    ).train()
    assert out["final_step"] == 2
    assert np.isfinite(out["final_loss"])


def _write(tmp_path, text):
    f = tmp_path / "in.txt"
    f.write_text(text)
    return f
