"""Flash Checkpoint tests.

Modeled on the reference's test strategy (dlrover/python/tests/
test_ckpt_saver.py + trainer checkpoint tests): real shm + real saver
thread in one process, sharded arrays on the virtual 8-device CPU mesh,
reshard-on-load across different mesh shapes.
"""

import os
import uuid

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
from dlrover_tpu.common import ckpt_shm
from dlrover_tpu.trainer.flash_checkpoint.engine import CheckpointEngine


@pytest.fixture(autouse=True)
def _isolated_job(monkeypatch, tmp_path):
    """Unique job name per test so shm segments/sockets don't collide."""
    monkeypatch.setenv("DLROVER_TPU_JOB_NAME", f"t{uuid.uuid4().hex[:8]}")
    yield


@pytest.fixture()
def saver(tmp_path):
    s = AsyncCheckpointSaver(
        checkpoint_dir=str(tmp_path / "ckpt"),
        local_shard_num=1,
        global_shard_num=1,
        commit_timeout=20.0,
    )
    s.start()
    yield s
    s.close()
    for shm in s._shms:
        shm.unlink()


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def _state(mesh):
    w = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    b = jnp.ones((8,), jnp.bfloat16)
    sharded_w = jax.device_put(
        w, NamedSharding(mesh, P("data", None)))
    return {"w": sharded_w, "inner": {"b": b, "step_scale": jnp.float32(2.0)}}


class TestShmFormat:
    def test_roundtrip(self):
        arrs = [
            ("a/b", np.arange(12, dtype=np.float32).reshape(3, 4)),
            ("c", np.ones((5,), np.int32)),
        ]
        plans = [
            (name, str(a.dtype), a.shape,
             [(0, s) for s in a.shape], a.nbytes)
            for name, a in arrs
        ]
        entries, total = ckpt_shm.plan_entries(plans)
        assert entries[1].offset % 128 == 0
        handler = ckpt_shm.SharedMemoryHandler(0)
        try:
            handler.save(7, list(zip(entries, [a for _, a in arrs])),
                         {"k": "v"})
            step, got_entries, extra, payload = handler.load()
            assert step == 7 and extra["k"] == "v"
            flat = ckpt_shm.assemble_global(got_entries, payload)
            np.testing.assert_array_equal(flat["a/b"], arrs[0][1])
            np.testing.assert_array_equal(flat["c"], arrs[1][1])
        finally:
            handler.unlink()
            handler.close()

    def test_bf16_raw_staging(self):
        import ml_dtypes

        a = np.arange(8, dtype=ml_dtypes.bfloat16)
        raw = a.view(np.uint16)
        plans = [("x", "bfloat16", a.shape, [(0, 8)], raw.nbytes)]
        entries, _ = ckpt_shm.plan_entries(plans)
        handler = ckpt_shm.SharedMemoryHandler(0)
        try:
            handler.save(1, [(entries[0], raw)])
            _, got, _, payload = handler.load()
            flat = ckpt_shm.assemble_global(got, payload)
            assert flat["x"].dtype == ml_dtypes.bfloat16
            np.testing.assert_array_equal(flat["x"], a)
        finally:
            handler.unlink()
            handler.close()


class TestEngineSaverEndToEnd:
    def test_save_and_commit(self, saver, tmp_path):
        mesh = _mesh((4, 2), ("data", "tensor"))
        state = _state(mesh)
        engine = CheckpointEngine(str(tmp_path / "ckpt"), use_agent=True)
        try:
            assert engine.save_to_storage(10, state, {"lr": 0.1})
            assert engine.wait_persisted(10, timeout=20)
            assert engine.latest_step() == 10
            step, flat, extra = engine.load_flat()
            assert step == 10 and extra["lr"] == 0.1
            np.testing.assert_array_equal(
                flat["w"], np.arange(64, dtype=np.float32).reshape(8, 8))
            np.testing.assert_array_equal(
                np.asarray(flat["inner/b"], np.float32), np.ones(8))
        finally:
            engine.close()

    def test_memory_only_then_flush(self, saver, tmp_path):
        """save_to_memory leaves storage untouched; the agent's
        failure-path flush (save_shm_to_storage) persists it."""
        mesh = _mesh((8,), ("data",))
        state = _state(mesh)
        engine = CheckpointEngine(str(tmp_path / "ckpt"), use_agent=True)
        try:
            assert engine.save_to_memory(5, state)
            assert engine.latest_step() == -1
            assert saver.save_shm_to_storage()
            assert engine.latest_step() == 5
        finally:
            engine.close()

    def test_reshard_on_load(self, saver, tmp_path):
        """Save on a (4,2) data×tensor mesh, restore onto (2,4)."""
        mesh_a = _mesh((4, 2), ("data", "tensor"))
        w = jnp.arange(256, dtype=jnp.float32).reshape(16, 16)
        sharded = jax.device_put(
            w, NamedSharding(mesh_a, P("data", "tensor")))
        engine = CheckpointEngine(str(tmp_path / "ckpt"), use_agent=True)
        try:
            assert engine.save_to_storage(3, {"w": sharded})
            assert engine.wait_persisted(3, timeout=20)

            mesh_b = _mesh((2, 4), ("data", "tensor"))
            target = NamedSharding(mesh_b, P("tensor", "data"))
            like = {"w": jax.ShapeDtypeStruct((16, 16), jnp.float32)}
            step, restored, _ = engine.load(
                like, shardings={"w": target})
            assert step == 3
            np.testing.assert_array_equal(np.asarray(restored["w"]), w)
            assert restored["w"].sharding == target
        finally:
            engine.close()

    def test_newer_save_wins(self, saver, tmp_path):
        mesh = _mesh((8,), ("data",))
        engine = CheckpointEngine(str(tmp_path / "ckpt"), use_agent=True)
        try:
            for step in (1, 2):
                state = {"x": jax.device_put(
                    jnp.full((8,), step, jnp.float32),
                    NamedSharding(mesh, P("data")))}
                assert engine.save_to_storage(step, state)
                assert engine.wait_persisted(step, timeout=20)
            assert engine.latest_step() == 2
            _, flat, _ = engine.load_flat()
            np.testing.assert_array_equal(flat["x"], np.full(8, 2.0))
        finally:
            engine.close()


class TestCheckpointerStandalone:
    def test_self_hosted_saver(self, tmp_path):
        from dlrover_tpu.trainer.flash_checkpoint import (
            Checkpointer,
            StorageType,
        )

        mesh = _mesh((8,), ("data",))
        state = _state(mesh)
        ckpt = Checkpointer(str(tmp_path / "ckpt2"))
        saver = ckpt._self_hosted_saver
        try:
            assert ckpt.save_checkpoint(42, state,
                                        storage_type=StorageType.DISK)
            assert ckpt.wait_latest_checkpoint(timeout=20)
            like = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
            restored = ckpt.load_checkpoint(like)
            assert ckpt.latest_step() == 42
            np.testing.assert_array_equal(
                np.asarray(restored["w"]),
                np.arange(64, dtype=np.float32).reshape(8, 8))
        finally:
            ckpt.close()
            if saver is not None:
                for shm in saver._shms:
                    shm.unlink()


class TestAdviceFixes:
    def test_flush_adopts_staged_dir(self, tmp_path):
        """A memory-only staged checkpoint flushed by the agent before a
        restart must land in the TRAINER's checkpoint dir (carried in
        the staged metadata), not the agent's constructor default."""
        mesh = _mesh((8,), ("data",))
        state = _state(mesh)
        agent_default = str(tmp_path / "agent_default")
        trainer_dir = str(tmp_path / "trainer_dir")
        saver = AsyncCheckpointSaver(
            checkpoint_dir=agent_default,
            local_shard_num=1,
            global_shard_num=1,
            commit_timeout=20.0,
        )
        saver.start()
        engine = CheckpointEngine(trainer_dir, use_agent=True)
        try:
            # Fast path only: never a save_to_storage event.
            assert engine.save_to_memory(7, state)
            assert saver.save_shm_to_storage()
            assert engine.latest_step() == 7  # in trainer_dir
            assert not os.path.exists(
                os.path.join(agent_default, "7"))
        finally:
            engine.close()
            saver.close()

    def test_checkpointer_restores_extra(self, tmp_path):
        from dlrover_tpu.trainer.flash_checkpoint import (
            Checkpointer,
            StorageType,
        )

        mesh = _mesh((8,), ("data",))
        state = _state(mesh)
        ckpt = Checkpointer(str(tmp_path / "ckpt3"))
        try:
            assert ckpt.save_checkpoint(
                9, state, storage_type=StorageType.DISK,
                extra={"sampler": {"epoch": 2, "consumed": 640}})
            assert ckpt.wait_latest_checkpoint(timeout=20)
            like = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
            assert ckpt.load_checkpoint(like) is not None
            assert ckpt.last_restored_extra["sampler"] == {
                "epoch": 2, "consumed": 640}
        finally:
            ckpt.close()
