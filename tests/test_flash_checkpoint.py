"""Flash Checkpoint tests.

Modeled on the reference's test strategy (dlrover/python/tests/
test_ckpt_saver.py + trainer checkpoint tests): real shm + real saver
thread in one process, sharded arrays on the virtual 8-device CPU mesh,
reshard-on-load across different mesh shapes.
"""

import os
import uuid

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver
from dlrover_tpu.common import ckpt_shm
from dlrover_tpu.trainer.flash_checkpoint.engine import CheckpointEngine


@pytest.fixture(autouse=True)
def _isolated_job(monkeypatch, tmp_path):
    """Unique job name per test so shm segments/sockets don't collide."""
    monkeypatch.setenv("DLROVER_TPU_JOB_NAME", f"t{uuid.uuid4().hex[:8]}")
    yield


@pytest.fixture()
def saver(tmp_path):
    s = AsyncCheckpointSaver(
        checkpoint_dir=str(tmp_path / "ckpt"),
        local_shard_num=1,
        global_shard_num=1,
        commit_timeout=20.0,
    )
    s.start()
    yield s
    s.close()
    for shm in s._shms:
        shm.unlink()


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def _state(mesh):
    w = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    b = jnp.ones((8,), jnp.bfloat16)
    sharded_w = jax.device_put(
        w, NamedSharding(mesh, P("data", None)))
    return {"w": sharded_w, "inner": {"b": b, "step_scale": jnp.float32(2.0)}}


class TestShmFormat:
    def test_roundtrip(self):
        arrs = [
            ("a/b", np.arange(12, dtype=np.float32).reshape(3, 4)),
            ("c", np.ones((5,), np.int32)),
        ]
        plans = [
            (name, str(a.dtype), a.shape,
             [(0, s) for s in a.shape], a.nbytes)
            for name, a in arrs
        ]
        entries, total = ckpt_shm.plan_entries(plans)
        assert entries[1].offset % 128 == 0
        handler = ckpt_shm.SharedMemoryHandler(0)
        try:
            handler.save(7, list(zip(entries, [a for _, a in arrs])),
                         {"k": "v"})
            step, got_entries, extra, payload = handler.load()
            assert step == 7 and extra["k"] == "v"
            flat = ckpt_shm.assemble_global(got_entries, payload)
            np.testing.assert_array_equal(flat["a/b"], arrs[0][1])
            np.testing.assert_array_equal(flat["c"], arrs[1][1])
        finally:
            handler.unlink()
            handler.close()

    def test_bf16_raw_staging(self):
        import ml_dtypes

        a = np.arange(8, dtype=ml_dtypes.bfloat16)
        raw = a.view(np.uint16)
        plans = [("x", "bfloat16", a.shape, [(0, 8)], raw.nbytes)]
        entries, _ = ckpt_shm.plan_entries(plans)
        handler = ckpt_shm.SharedMemoryHandler(0)
        try:
            handler.save(1, [(entries[0], raw)])
            _, got, _, payload = handler.load()
            flat = ckpt_shm.assemble_global(got, payload)
            assert flat["x"].dtype == ml_dtypes.bfloat16
            np.testing.assert_array_equal(flat["x"], a)
        finally:
            handler.unlink()
            handler.close()


class TestEngineSaverEndToEnd:
    def test_save_and_commit(self, saver, tmp_path):
        mesh = _mesh((4, 2), ("data", "tensor"))
        state = _state(mesh)
        engine = CheckpointEngine(str(tmp_path / "ckpt"), use_agent=True)
        try:
            assert engine.save_to_storage(10, state, {"lr": 0.1})
            assert engine.wait_persisted(10, timeout=20)
            assert engine.latest_step() == 10
            step, flat, extra = engine.load_flat()
            assert step == 10 and extra["lr"] == 0.1
            np.testing.assert_array_equal(
                flat["w"], np.arange(64, dtype=np.float32).reshape(8, 8))
            np.testing.assert_array_equal(
                np.asarray(flat["inner/b"], np.float32), np.ones(8))
        finally:
            engine.close()

    def test_memory_only_then_flush(self, saver, tmp_path):
        """save_to_memory leaves storage untouched; the agent's
        failure-path flush (save_shm_to_storage) persists it."""
        mesh = _mesh((8,), ("data",))
        state = _state(mesh)
        engine = CheckpointEngine(str(tmp_path / "ckpt"), use_agent=True)
        try:
            assert engine.save_to_memory(5, state)
            assert engine.latest_step() == -1
            assert saver.save_shm_to_storage()
            assert engine.latest_step() == 5
        finally:
            engine.close()

    def test_reshard_on_load(self, saver, tmp_path):
        """Save on a (4,2) data×tensor mesh, restore onto (2,4)."""
        mesh_a = _mesh((4, 2), ("data", "tensor"))
        w = jnp.arange(256, dtype=jnp.float32).reshape(16, 16)
        sharded = jax.device_put(
            w, NamedSharding(mesh_a, P("data", "tensor")))
        engine = CheckpointEngine(str(tmp_path / "ckpt"), use_agent=True)
        try:
            assert engine.save_to_storage(3, {"w": sharded})
            assert engine.wait_persisted(3, timeout=20)

            mesh_b = _mesh((2, 4), ("data", "tensor"))
            target = NamedSharding(mesh_b, P("tensor", "data"))
            like = {"w": jax.ShapeDtypeStruct((16, 16), jnp.float32)}
            step, restored, _ = engine.load(
                like, shardings={"w": target})
            assert step == 3
            np.testing.assert_array_equal(np.asarray(restored["w"]), w)
            assert restored["w"].sharding == target
        finally:
            engine.close()

    def test_newer_save_wins(self, saver, tmp_path):
        mesh = _mesh((8,), ("data",))
        engine = CheckpointEngine(str(tmp_path / "ckpt"), use_agent=True)
        try:
            for step in (1, 2):
                state = {"x": jax.device_put(
                    jnp.full((8,), step, jnp.float32),
                    NamedSharding(mesh, P("data")))}
                assert engine.save_to_storage(step, state)
                assert engine.wait_persisted(step, timeout=20)
            assert engine.latest_step() == 2
            _, flat, _ = engine.load_flat()
            np.testing.assert_array_equal(flat["x"], np.full(8, 2.0))
        finally:
            engine.close()


class CountingStorage:
    """PosixStorage wrapper that accounts every byte read."""

    def __init__(self):
        from dlrover_tpu.common.storage import PosixStorage

        self._s = PosixStorage()
        self.full_read_paths = []
        self.range_bytes = 0

    def read_bytes(self, path):
        self.full_read_paths.append(path)
        return self._s.read_bytes(path)

    def read_range(self, path, offset, length):
        self.range_bytes += length
        return self._s.read_range(path, offset, length)

    def __getattr__(self, name):
        return getattr(self._s, name)


def _craft_checkpoint(tmp_path, step=5):
    """Hand-craft a 2-host checkpoint: rank0 holds rows 0:8 of ``w``
    plus a big ``junk`` leaf, rank1 holds rows 8:16 of ``w``. Returns
    (ckpt_dir, w, junk_nbytes, total_payload_bytes)."""
    from dlrover_tpu.trainer.flash_checkpoint.engine import (
        TRACKER_FILE,
        pack_shard_file,
    )

    ckpt_dir = str(tmp_path / "crafted")
    sdir = f"{ckpt_dir}/{step}"
    os.makedirs(sdir, exist_ok=True)
    w = np.arange(256, dtype=np.float32).reshape(16, 16)
    junk = np.ones((64, 64), np.float32)  # 16KB nobody asks for

    total = 0
    for rank, (rows, extras) in enumerate(
        [((0, 8), [("junk", junk)]), ((8, 16), [])]
    ):
        arrays = [("w", w[rows[0]:rows[1]],
                   ((rows[0], rows[1]), (0, 16)), (16, 16))]
        for name, arr in extras:
            arrays.append(
                (name, arr,
                 tuple((0, s) for s in arr.shape), arr.shape)
            )
        plans = [
            (name, str(arr.dtype), gshape, index, arr.nbytes)
            for name, arr, index, gshape in arrays
        ]
        entries, size = ckpt_shm.plan_entries(plans)
        payload = bytearray(size)
        for e, (_, arr, _, _) in zip(entries, arrays):
            payload[e.offset:e.offset + e.nbytes] = arr.tobytes()
        data = pack_shard_file(step, entries, {}, bytes(payload))
        with open(f"{sdir}/rank{rank}.ckpt", "wb") as f:
            f.write(data)
        total += size
    with open(f"{ckpt_dir}/{TRACKER_FILE}", "w") as f:
        f.write(str(step))
    return ckpt_dir, w, junk.nbytes, total


class TestStreamingRestore:
    def test_slice_read_touches_only_owning_shard(self, tmp_path):
        """Fetching rows 0:8 must read rank0's w bytes only — not
        rank1's shard and not the junk leaf."""
        ckpt_dir, w, _, _ = _craft_checkpoint(tmp_path)
        storage = CountingStorage()
        engine = CheckpointEngine(
            ckpt_dir, use_agent=False, storage=storage,
            global_rank=0, world_size=1,
        )
        try:
            step, index, _ = engine.read_shard_metas()
            assert step == 5
            meta_bytes = storage.range_bytes
            sub = engine._read_slice(
                index["w"], (16, 16), "float32",
                (slice(0, 8), slice(0, 16)),
            )
            np.testing.assert_array_equal(sub, w[0:8])
            payload_read = storage.range_bytes - meta_bytes
            assert payload_read == w[0:8].nbytes  # exactly one shard
            # sub-band: rows 2:4 cost 2 rows of bytes, not the entry
            before = storage.range_bytes
            sub2 = engine._read_slice(
                index["w"], (16, 16), "float32",
                (slice(2, 4), slice(0, 16)),
            )
            np.testing.assert_array_equal(sub2, w[2:4])
            assert storage.range_bytes - before == w[2:4].nbytes
            assert not [p for p in storage.full_read_paths
                        if p.endswith('.ckpt')]
        finally:
            engine.close()

    def test_streaming_load_reads_less_than_checkpoint(self, tmp_path):
        """End-to-end load with shardings: bytes read < total
        checkpoint payload (the junk leaf is never fetched), and the
        restored array equals the original across both rank files."""
        ckpt_dir, w, junk_nbytes, total = _craft_checkpoint(tmp_path)
        storage = CountingStorage()
        engine = CheckpointEngine(
            ckpt_dir, use_agent=False, storage=storage,
            global_rank=0, world_size=1,
        )
        mesh = _mesh((8,), ("data",))
        target = NamedSharding(mesh, P("data"))
        try:
            step, state, _ = engine.load(
                {"w": jax.ShapeDtypeStruct((16, 16), jnp.float32)},
                shardings={"w": target},
            )
            assert step == 5
            np.testing.assert_array_equal(np.asarray(state["w"]), w)
            assert state["w"].sharding == target
            assert storage.range_bytes < total  # junk never read
            assert total - storage.range_bytes >= junk_nbytes // 2
            assert not [p for p in storage.full_read_paths
                        if p.endswith('.ckpt')]
        finally:
            engine.close()

    def test_streaming_load_missing_coverage_raises(self, tmp_path):
        """A checkpoint whose shards don't cover the requested slice
        must fail loudly, not return zeros."""
        from dlrover_tpu.trainer.flash_checkpoint.engine import (
            TRACKER_FILE,
            pack_shard_file,
        )

        ckpt_dir = str(tmp_path / "holey")
        os.makedirs(f"{ckpt_dir}/1", exist_ok=True)
        w = np.ones((8, 8), np.float32)
        plans = [("w", "float32", (16, 8), ((0, 8), (0, 8)),
                  w.nbytes)]
        entries, size = ckpt_shm.plan_entries(plans)
        payload = bytearray(size)
        payload[entries[0].offset:entries[0].offset + w.nbytes] = (
            w.tobytes())
        with open(f"{ckpt_dir}/1/rank0.ckpt", "wb") as f:
            f.write(pack_shard_file(1, entries, {}, bytes(payload)))
        with open(f"{ckpt_dir}/{TRACKER_FILE}", "w") as f:
            f.write("1")
        engine = CheckpointEngine(
            ckpt_dir, use_agent=False,
            global_rank=0, world_size=1,
        )
        mesh = _mesh((8,), ("data",))
        try:
            with pytest.raises(Exception, match="cover|missing"):
                engine.load(
                    {"w": jax.ShapeDtypeStruct((16, 8), jnp.float32)},
                    shardings={
                        "w": NamedSharding(mesh, P("data"))},
                )
        finally:
            engine.close()


class TestCheckpointerStandalone:
    def test_self_hosted_saver(self, tmp_path):
        from dlrover_tpu.trainer.flash_checkpoint import (
            Checkpointer,
            StorageType,
        )

        mesh = _mesh((8,), ("data",))
        state = _state(mesh)
        ckpt = Checkpointer(str(tmp_path / "ckpt2"))
        saver = ckpt._self_hosted_saver
        try:
            assert ckpt.save_checkpoint(42, state,
                                        storage_type=StorageType.DISK)
            assert ckpt.wait_latest_checkpoint(timeout=20)
            like = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
            restored = ckpt.load_checkpoint(like)
            assert ckpt.latest_step() == 42
            np.testing.assert_array_equal(
                np.asarray(restored["w"]),
                np.arange(64, dtype=np.float32).reshape(8, 8))
        finally:
            ckpt.close()
            if saver is not None:
                for shm in saver._shms:
                    shm.unlink()


class TestOrbaxCompat:
    def test_export_import_roundtrip(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from dlrover_tpu.trainer.flash_checkpoint import (
            Checkpointer,
            StorageType,
        )
        from dlrover_tpu.trainer.flash_checkpoint.orbax_compat import (
            export_to_orbax,
            import_from_orbax,
        )

        mesh = _mesh((8,), ("data",))
        state = _state(mesh)
        ckpt = Checkpointer(str(tmp_path / "flash"))
        saver = ckpt._self_hosted_saver
        orbax_dir = str(tmp_path / "orbax")
        try:
            assert ckpt.save_checkpoint(
                7, state, storage_type=StorageType.DISK
            )
            assert ckpt.wait_latest_checkpoint(timeout=20)
            like = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                state,
            )
            step = export_to_orbax(ckpt, orbax_dir, like)
            assert step == 7
            got_step, restored = import_from_orbax(orbax_dir)
            assert got_step == 7
            np.testing.assert_array_equal(
                np.asarray(restored["w"]), np.asarray(state["w"])
            )
        finally:
            ckpt.close()
            if saver is not None:
                for shm in saver._shms:
                    shm.unlink()


class TestAdviceFixes:
    def test_flush_adopts_staged_dir(self, tmp_path):
        """A memory-only staged checkpoint flushed by the agent before a
        restart must land in the TRAINER's checkpoint dir (carried in
        the staged metadata), not the agent's constructor default."""
        mesh = _mesh((8,), ("data",))
        state = _state(mesh)
        agent_default = str(tmp_path / "agent_default")
        trainer_dir = str(tmp_path / "trainer_dir")
        saver = AsyncCheckpointSaver(
            checkpoint_dir=agent_default,
            local_shard_num=1,
            global_shard_num=1,
            commit_timeout=20.0,
        )
        saver.start()
        engine = CheckpointEngine(trainer_dir, use_agent=True)
        try:
            # Fast path only: never a save_to_storage event.
            assert engine.save_to_memory(7, state)
            assert saver.save_shm_to_storage()
            assert engine.latest_step() == 7  # in trainer_dir
            assert not os.path.exists(
                os.path.join(agent_default, "7"))
        finally:
            engine.close()
            saver.close()

    def test_checkpointer_restores_extra(self, tmp_path):
        from dlrover_tpu.trainer.flash_checkpoint import (
            Checkpointer,
            StorageType,
        )

        mesh = _mesh((8,), ("data",))
        state = _state(mesh)
        ckpt = Checkpointer(str(tmp_path / "ckpt3"))
        try:
            assert ckpt.save_checkpoint(
                9, state, storage_type=StorageType.DISK,
                extra={"sampler": {"epoch": 2, "consumed": 640}})
            assert ckpt.wait_latest_checkpoint(timeout=20)
            like = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
            assert ckpt.load_checkpoint(like) is not None
            assert ckpt.last_restored_extra["sampler"] == {
                "epoch": 2, "consumed": 640}
        finally:
            ckpt.close()
