"""ElasticJob controller + Brain service."""

import pytest

from dlrover_tpu.brain import (
    BrainResourceOptimizer,
    BrainService,
    JobMetricsRecord,
)
from dlrover_tpu.common.node import Node, NodeResource
from dlrover_tpu.master.job_manager import JobManager, ScalePlan
from dlrover_tpu.master.scaler import ElasticJobScaler, FakeClusterClient
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.operator import (
    ElasticJob,
    ElasticJobController,
    JobPhase,
    ReplicaSpec,
)


# -- operator ---------------------------------------------------------------


def test_controller_creates_master_pod():
    client = FakeClusterClient()
    ctl = ElasticJobController(client)
    job = ElasticJob(name="j1", workers=ReplicaSpec(replicas=4))
    ctl.create_job(job)
    pods = client.list_pods("j1")
    assert [p["name"] for p in pods] == ["j1-master"]
    assert pods[0]["env"]["DLROVER_TPU_NODE_NUM"] == "4"
    assert job.phase == JobPhase.RUNNING


def test_controller_restarts_failed_master_up_to_limit():
    client = FakeClusterClient()
    ctl = ElasticJobController(client)
    job = ElasticJob(name="j1", master_restart_limit=1)
    ctl.create_job(job)
    # master dies once: recreated
    client.fail_pod("j1-master")
    ctl.reconcile("j1")
    assert client.list_pods("j1")  # recreated
    assert job.master_restarts == 1
    # dies again: limit exceeded -> job failed
    client.fail_pod("j1-master")
    ctl.reconcile("j1")
    assert job.phase == JobPhase.FAILED


def test_controller_job_succeeds_with_master():
    client = FakeClusterClient()
    ctl = ElasticJobController(client)
    job = ElasticJob(name="j1")
    ctl.create_job(job)
    client.pods["j1-master"]["phase"] = "Succeeded"
    ctl.reconcile("j1")
    assert job.phase == JobPhase.SUCCEEDED


def test_controller_executes_scaleplan_objects():
    """ElasticJobScaler writes ScalePlan custom objects; the operator
    realizes them (the reference's split of responsibilities)."""
    client = FakeClusterClient()
    ctl = ElasticJobController(client)
    # TPU shape is job-level: the CRD PodMeta only carries cpu/memory
    # (ref scaleplan_types.go:84), the accelerator comes from the
    # job's pod template.
    job = ElasticJob(
        name="j1",
        pod_template={"tpu_accelerator": "v5p", "tpu_chips": 4},
    )
    ctl.create_job(job)

    scaler = ElasticJobScaler("j1", client)
    plan = ScalePlan()
    plan.launch_nodes = [
        Node(
            type="worker", id=0, rank=0,
            config_resource=NodeResource(
                cpu=4, memory_mb=8192, chips=4, tpu_type="v5p"
            ),
        )
    ]
    scaler.scale(plan)
    ctl.reconcile("j1")
    names = {p["name"] for p in client.list_pods("j1")}
    assert names == {"j1-master", "j1-worker-0"}
    worker = client.pods["j1-worker-0"]
    assert worker["tpu_accelerator"] == "v5p"
    # plans execute once, not repeatedly
    client.delete_pod("j1-worker-0")
    ctl.reconcile("j1")
    assert "j1-worker-0" not in client.pods


def test_controller_delete_job_cleans_pods():
    client = FakeClusterClient()
    ctl = ElasticJobController(client)
    ctl.create_job(ElasticJob(name="j1"))
    ctl.delete_job("j1")
    assert client.list_pods("j1") == []


# -- brain ------------------------------------------------------------------


def _seed_brain():
    brain = BrainService()
    runs = [
        (2, 8192, 100.0, 6000, False),
        (4, 8192, 190.0, 6500, False),
        (8, 8192, 360.0, 7000, False),
        (16, 8192, 400.0, 7000, False),  # scaling knee past 8
        (4, 4096, 0.0, 4096, True),  # an OOM run
    ]
    for i, (w, mem, tput, peak, oom) in enumerate(runs):
        brain.persist_metrics(
            JobMetricsRecord(
                job_name=f"job{i}",
                model_signature="gpt-test",
                workers=w,
                memory_mb=mem,
                chips_per_worker=4,
                throughput=tput,
                peak_memory_mb=peak,
                oom=oom,
                completed=not oom,
            )
        )
    return brain


def test_brain_initial_plan_from_history():
    brain = _seed_brain()
    plan = brain.optimize_job_resource("gpt-test")
    assert plan["workers"] in (4, 8)
    assert plan["memory_mb"] == 8192
    assert brain.optimize_job_resource("unknown-model") is None


def test_brain_oom_memory_above_observed_peaks():
    brain = _seed_brain()
    grown = brain.optimize_worker_oom("gpt-test", requested_mb=8192)
    assert grown >= 7000 * 1.5


def test_brain_worker_count_finds_scaling_knee():
    brain = _seed_brain()
    # 2->4: 1.9x for 2x (0.9 marginal), 4->8: ~1.9x (0.89),
    # 8->16: 1.11x for 2x (0.11 marginal) -> knee at 8
    assert brain.optimize_worker_count("gpt-test") == 8


def test_brain_resource_optimizer_plugs_into_scaler_seam():
    brain = _seed_brain()
    opt = BrainResourceOptimizer(
        brain, "gpt-test", hosts_per_slice=4
    )
    assert opt.target_worker_count(2, SpeedMonitor()) == 8
    grown = opt.optimize_oom_node(NodeResource(memory_mb=8192))
    assert grown.memory_mb > 8192


def _seed_ps_brain():
    from dlrover_tpu.brain.service import RuntimeSample

    brain = BrainService()
    for i, (count, cpu, mem, oom, done) in enumerate([
        (2, 8.0, 8192, False, True),
        (4, 12.0, 10240, False, True),
        (4, 16.0, 12288, False, True),
        (2, 8.0, 6144, True, True),  # an OOM'd PS config
    ]):
        brain.persist_ps_job(
            f"psjob{i}", "ctr-test", count, cpu, mem,
            recv_op_count=400, oom=oom, completed=done,
        )
    # runtime: ps 0 runs hot on cpu, ps 1 hot on memory, ps 2 cool
    for t in range(3):
        for node_id, (ucpu, umem) in enumerate(
            [(7.5, 4000), (2.0, 7900), (2.0, 4000)]
        ):
            brain.persist_runtime_sample(RuntimeSample(
                job_name="livejob", node_type="ps", node_id=node_id,
                used_cpu=ucpu, used_memory_mb=umem, config_cpu=8.0,
                config_memory_mb=8192, timestamp=100.0 + t,
            ))
    return brain


def test_brain_ps_create_from_history():
    brain = _seed_ps_brain()
    plan = brain.optimize_ps_create("ctr-test")
    assert plan["ps_count"] == 4  # median of (2, 4, 4, 2)... sorted
    assert plan["ps_cpu"] == 16.0
    assert plan["ps_memory_mb"] == 12288  # max that never OOM'd
    assert brain.optimize_ps_create("unknown") is None


def test_brain_ps_cold_create_defaults():
    brain = BrainService()
    plan = brain.optimize_ps_cold_create()
    assert plan == {
        "ps_count": 2, "ps_cpu": 8.0, "ps_memory_mb": 8192,
    }


def test_brain_ps_init_adjust_scales_cpu_with_recv_ops():
    brain = _seed_ps_brain()
    # 400 recv ops over 4 PS = 100/ps -> ceil(8) + margin 4 = 12
    plan = brain.optimize_ps_init_adjust(
        "livejob", recv_op_count=400, ps_count=4
    )
    assert plan["ps_cpu"] == 12.0
    # heavy fan-in gets the 16-core default
    plan = brain.optimize_ps_init_adjust(
        "otherjob", recv_op_count=4000, ps_count=4
    )
    assert plan["ps_cpu"] == 16.0
    # observed memory peak (7900) grows by the 50% margin
    plan = brain.optimize_ps_init_adjust(
        "livejob", recv_op_count=400, ps_count=4
    )
    assert plan["ps_memory_mb"] == int(7900 * 1.5)


def test_brain_ps_oom_memory_above_oomed_requests():
    brain = _seed_ps_brain()
    grown = brain.optimize_ps_oom("ctr-test", requested_mb=4096)
    assert grown >= int(6144 * 1.5)


def test_brain_hot_ps_grows_hot_nodes():
    brain = _seed_ps_brain()
    plan = brain.optimize_hot_ps(
        "livejob", current_workers=4, target_workers=8,
    )
    # ps 0 (cpu 7.5/8 avg) is cpu-hot: whole group scales by 2x ->
    # ps 0 wants 15 cores; cool nodes (avg 2.0 -> 4) stay under their
    # configured 8 so only the hot node appears with a cpu plan
    assert plan[0]["cpu"] == 15.0
    assert 2 not in plan or "cpu" not in plan[2]
    # ps 1 (mem 7900/8192) is memory-hot: fixed bump
    assert plan[1]["memory_mb"] == 8192 + 4096


def test_brain_worker_create_oom_floor():
    brain = _seed_brain()
    # history has an OOM at 4096 requested and peaks up to 7000
    mb = brain.optimize_worker_create_oom("gpt-test")
    assert mb == int(7000 * 1.5)
    assert BrainService().optimize_worker_create_oom(
        "none", default_mb=2048) == 2048


def test_brain_algorithm_registry_dispatch():
    from dlrover_tpu.brain.service import ALGORITHMS, run_algorithm

    brain = _seed_ps_brain()
    assert len(ALGORITHMS) == 9
    plan = run_algorithm(
        brain, "optimize_job_ps_create_resource", "ctr-test"
    )
    assert plan["ps_count"] == 4
    import pytest as _pytest

    with _pytest.raises(KeyError, match="unknown brain algorithm"):
        run_algorithm(brain, "nope")
