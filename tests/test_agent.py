"""Agent-layer tests: client, rendezvous handler, sharding, supervision.

Real in-process master + real subprocess supervision (no cluster),
mirroring the reference's test technique.
"""

import os
import sys
import threading

import pytest

from dlrover_tpu.agent.agent import (
    AgentConfig,
    ElasticAgent,
    MasterRendezvousHandler,
)
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.sharding_client import IndexShardingClient
from dlrover_tpu.master.master import JobMaster


@pytest.fixture()
def master2():
    m = JobMaster(port=0, node_num=2, rdzv_timeout=1.0)
    m.prepare()
    yield m
    m.stop()


@pytest.fixture()
def master1():
    m = JobMaster(port=0, node_num=1, rdzv_timeout=1.0)
    m.prepare()
    yield m
    m.stop()


def _client(master, node_id):
    return MasterClient(master.addr, node_id=node_id)


class TestRendezvousHandler:
    def test_two_nodes_bootstrap(self, master2):
        specs = {}

        def join(node_id):
            client = _client(master2, node_id)
            client.register_node()
            handler = MasterRendezvousHandler(
                client, local_world_size=4, timeout=30
            )
            specs[node_id] = handler.next_rendezvous()

        threads = [
            threading.Thread(target=join, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert specs[0].node_world_size == 2
        assert specs[0].num_processes == 2
        assert {specs[0].node_rank, specs[1].node_rank} == {0, 1}
        # Both got the same coordinator endpoint from the KV store.
        assert specs[0].coordinator == specs[1].coordinator
        assert specs[0].coordinator.count(":") == 1


class TestIndexShardingClient:
    def test_streams_all_indices(self, master1):
        client = _client(master1, 0)
        shard_client = IndexShardingClient(
            "train", batch_size=4, client=client
        )
        shard_client.create_dataset(
            dataset_size=20, batch_size=4, num_minibatches_per_shard=2
        )
        seen = []
        while True:
            idx = shard_client.fetch_sample_index()
            if idx is None:
                break
            seen.append(idx)
        assert sorted(seen) == list(range(20))


class TestAgentSupervision:
    def test_restart_until_success(self, master1, tmp_path):
        """Entry fails twice (distinct exit codes), then succeeds."""
        counter = tmp_path / "count"
        script = tmp_path / "train.py"
        script.write_text(
            "import pathlib, sys\n"
            f"p = pathlib.Path({str(counter)!r})\n"
            "n = int(p.read_text()) if p.exists() else 0\n"
            "p.write_text(str(n + 1))\n"
            "sys.exit(0 if n >= 2 else 7)\n"
        )
        client = _client(master1, 0)
        config = AgentConfig(
            node_id=0,
            local_world_size=1,
            max_restarts=3,
            monitor_interval=0.2,
            rdzv_timeout=30,
        )
        agent = ElasticAgent(
            config, [sys.executable, str(script)], client=client
        )
        assert agent.run() == 0
        assert counter.read_text() == "3"
        assert agent._restart_count == 2

    def test_gives_up_after_max_restarts(self, master1, tmp_path):
        script = tmp_path / "fail.py"
        script.write_text("import sys; sys.exit(3)\n")
        client = _client(master1, 0)
        config = AgentConfig(
            node_id=0,
            local_world_size=1,
            max_restarts=1,
            monitor_interval=0.2,
            rdzv_timeout=30,
        )
        agent = ElasticAgent(
            config, [sys.executable, str(script)], client=client
        )
        assert agent.run() == 3
        # Both failures were reported to the master.
        node = master1.job_manager.get_node(0)
        assert node.status == "failed"


class TestExcludeStraggler:
    """3 nodes report network-check results; node 2 is 40x slower
    than the median (>2x threshold). The verdict path is driven
    directly over RPC (no live rendezvous threads — that variant is
    scheduling-sensitive on a 1-core CI box); the full check loop
    incl. rendezvous is covered by TestStandaloneCli end-to-end."""

    def _report_times(self, master, times):
        for node_id, elapsed in times.items():
            client = _client(master, node_id)
            client.report_network_check(True, elapsed)

    def _verdict(self, master, node_id, exclude):
        config = AgentConfig(
            node_id=node_id,
            node_rank=node_id,
            local_world_size=1,
            network_check=True,
            exclude_straggler=exclude,
            rdzv_timeout=5.0,
        )
        agent = ElasticAgent(
            config, [sys.executable, "-c", ""],
            client=_client(master, node_id),
        )
        return agent.network_check_verdict()

    def test_straggler_excluded_only_with_flag(self):
        master = JobMaster(port=0, node_num=3, rdzv_timeout=5.0)
        master.prepare()
        try:
            self._report_times(
                master, {0: 0.05, 1: 0.05, 2: 2.0}
            )
            assert self._verdict(master, 0, exclude=False) is True
            assert self._verdict(master, 1, exclude=False) is True
            # straggler + flag -> excluded
            assert self._verdict(master, 2, exclude=True) is False
            stragglers, _ = (
                master.servicer.rdzv_managers["network-check"]
                .get_stragglers()
            )
            assert stragglers == [2]
        finally:
            master.stop()

    def test_straggler_continues_without_flag(self):
        master = JobMaster(port=0, node_num=3, rdzv_timeout=5.0)
        master.prepare()
        try:
            self._report_times(
                master, {0: 0.05, 1: 0.05, 2: 2.0}
            )
            # straggler WITHOUT the flag -> keeps running
            assert self._verdict(master, 2, exclude=False) is True
        finally:
            master.stop()

    def test_failed_node_still_fails_regardless_of_flag(self):
        master = JobMaster(port=0, node_num=3, rdzv_timeout=5.0)
        master.prepare()
        try:
            for node_id, (ok, t) in {
                0: (True, 0.05), 1: (True, 0.05), 2: (False, 0.05),
            }.items():
                _client(master, node_id).report_network_check(ok, t)
            assert self._verdict(master, 2, exclude=False) is False
            assert self._verdict(master, 0, exclude=False) is True
        finally:
            master.stop()

    def test_cli_flag_reaches_agent_config(self):
        from dlrover_tpu.trainer.elastic_run import parse_args

        args = parse_args(
            ["--network-check", "--exclude-straggler", "t.py"]
        )
        assert args.exclude_straggler is True
        args = parse_args(["t.py"])
        assert args.exclude_straggler is False


class TestStandaloneCli:
    def test_end_to_end(self, tmp_path):
        """dlrover-tpu-run --standalone runs a real training script that
        talks to the auto-spawned master for data shards."""
        script = tmp_path / "train.py"
        script.write_text(
            "from dlrover_tpu.agent.master_client import MasterClient\n"
            "from dlrover_tpu.agent.sharding_client import "
            "IndexShardingClient\n"
            "client = MasterClient.singleton()\n"
            "sc = IndexShardingClient('d', batch_size=2, client=client)\n"
            "sc.create_dataset(dataset_size=8, batch_size=2)\n"
            "seen = []\n"
            "while True:\n"
            "    i = sc.fetch_sample_index()\n"
            "    if i is None: break\n"
            "    seen.append(i)\n"
            "assert sorted(seen) == list(range(8)), seen\n"
            "client.report_step(step=4, tokens=64)\n"
            "print('TRAIN_OK')\n"
        )
        from dlrover_tpu.trainer.elastic_run import main

        env_backup = dict(os.environ)
        try:
            # main() runs IN-PROCESS here: keep the agent's flight
            # recorder from rewiring pytest's excepthook/faulthandler
            # (restored with the env below).
            os.environ["DLROVER_TPU_FLIGHT_RECORDER"] = "0"
            MasterClient.reset()
            code = main(
                [
                    "--standalone",
                    "--nproc_per_node",
                    "1",
                    str(script),
                ]
            )
        finally:
            os.environ.clear()
            os.environ.update(env_backup)
            MasterClient.reset()
        assert code == 0
