"""Agent-layer tests: client, rendezvous handler, sharding, supervision.

Real in-process master + real subprocess supervision (no cluster),
mirroring the reference's test technique.
"""

import os
import sys
import threading

import pytest

from dlrover_tpu.agent.agent import (
    AgentConfig,
    ElasticAgent,
    MasterRendezvousHandler,
)
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.sharding_client import IndexShardingClient
from dlrover_tpu.master.master import JobMaster


@pytest.fixture()
def master2():
    m = JobMaster(port=0, node_num=2, rdzv_timeout=1.0)
    m.prepare()
    yield m
    m.stop()


@pytest.fixture()
def master1():
    m = JobMaster(port=0, node_num=1, rdzv_timeout=1.0)
    m.prepare()
    yield m
    m.stop()


def _client(master, node_id):
    return MasterClient(master.addr, node_id=node_id)


class TestRendezvousHandler:
    def test_two_nodes_bootstrap(self, master2):
        specs = {}

        def join(node_id):
            client = _client(master2, node_id)
            client.register_node()
            handler = MasterRendezvousHandler(
                client, local_world_size=4, timeout=30
            )
            specs[node_id] = handler.next_rendezvous()

        threads = [
            threading.Thread(target=join, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert specs[0].node_world_size == 2
        assert specs[0].num_processes == 2
        assert {specs[0].node_rank, specs[1].node_rank} == {0, 1}
        # Both got the same coordinator endpoint from the KV store.
        assert specs[0].coordinator == specs[1].coordinator
        assert specs[0].coordinator.count(":") == 1


class TestIndexShardingClient:
    def test_streams_all_indices(self, master1):
        client = _client(master1, 0)
        shard_client = IndexShardingClient(
            "train", batch_size=4, client=client
        )
        shard_client.create_dataset(
            dataset_size=20, batch_size=4, num_minibatches_per_shard=2
        )
        seen = []
        while True:
            idx = shard_client.fetch_sample_index()
            if idx is None:
                break
            seen.append(idx)
        assert sorted(seen) == list(range(20))


class TestAgentSupervision:
    def test_restart_until_success(self, master1, tmp_path):
        """Entry fails twice (distinct exit codes), then succeeds."""
        counter = tmp_path / "count"
        script = tmp_path / "train.py"
        script.write_text(
            "import pathlib, sys\n"
            f"p = pathlib.Path({str(counter)!r})\n"
            "n = int(p.read_text()) if p.exists() else 0\n"
            "p.write_text(str(n + 1))\n"
            "sys.exit(0 if n >= 2 else 7)\n"
        )
        client = _client(master1, 0)
        config = AgentConfig(
            node_id=0,
            local_world_size=1,
            max_restarts=3,
            monitor_interval=0.2,
            rdzv_timeout=30,
        )
        agent = ElasticAgent(
            config, [sys.executable, str(script)], client=client
        )
        assert agent.run() == 0
        assert counter.read_text() == "3"
        assert agent._restart_count == 2

    def test_gives_up_after_max_restarts(self, master1, tmp_path):
        script = tmp_path / "fail.py"
        script.write_text("import sys; sys.exit(3)\n")
        client = _client(master1, 0)
        config = AgentConfig(
            node_id=0,
            local_world_size=1,
            max_restarts=1,
            monitor_interval=0.2,
            rdzv_timeout=30,
        )
        agent = ElasticAgent(
            config, [sys.executable, str(script)], client=client
        )
        assert agent.run() == 3
        # Both failures were reported to the master.
        node = master1.job_manager.get_node(0)
        assert node.status == "failed"


class TestExcludeStraggler:
    def test_straggler_excluded_only_with_flag(
        self, monkeypatch, tmp_path
    ):
        """3 nodes run the network check; node 2 is 9x slower than the
        median. Without --exclude-straggler it continues (warn only);
        with it, run_network_check returns False so the node exits and
        gets replaced (ref dlrover-run --exclude-straggler)."""
        from dlrover_tpu.common.constants import NodeEnv

        master = JobMaster(port=0, node_num=3, rdzv_timeout=60.0)
        master.prepare()
        try:
            class FakeDone:
                returncode = 0

            def fake_run(cmd, env=None, **kw):
                import time as _t

                pid = int(env.get(NodeEnv.PROCESS_ID, "0"))
                _t.sleep(0.45 if pid == 2 else 0.05)
                return FakeDone()

            from dlrover_tpu.agent import agent as agent_mod

            monkeypatch.setattr(
                agent_mod.subprocess, "run", fake_run
            )

            results = {}

            def run_one(node_id, exclude):
                client = _client(master, node_id)
                config = AgentConfig(
                    node_id=node_id,
                    node_rank=node_id,
                    local_world_size=1,
                    network_check=True,
                    exclude_straggler=exclude,
                    rdzv_timeout=60.0,
                )
                agent = ElasticAgent(
                    config, [sys.executable, "-c", ""], client=client
                )
                results[node_id] = agent.run_network_check()

            threads = [
                threading.Thread(
                    target=run_one, args=(i, i == 2), daemon=True
                )
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            # fast nodes pass; the straggler with the flag exits
            assert results[0] is True
            assert results[1] is True
            assert results[2] is False
            stragglers, _ = (
                master.servicer.rdzv_managers["network-check"]
                .get_stragglers()
            )
            assert stragglers == [2]
        finally:
            master.stop()

    def test_straggler_continues_without_flag(
        self, monkeypatch
    ):
        """Same drill but the slow node does NOT pass the flag: it
        must keep running (True)."""
        from dlrover_tpu.common.constants import NodeEnv

        master = JobMaster(port=0, node_num=3, rdzv_timeout=60.0)
        master.prepare()
        try:
            class FakeDone:
                returncode = 0

            def fake_run(cmd, env=None, **kw):
                import time as _t

                pid = int(env.get(NodeEnv.PROCESS_ID, "0"))
                _t.sleep(0.45 if pid == 2 else 0.05)
                return FakeDone()

            from dlrover_tpu.agent import agent as agent_mod

            monkeypatch.setattr(
                agent_mod.subprocess, "run", fake_run
            )
            results = {}

            def run_one(node_id):
                client = _client(master, node_id)
                config = AgentConfig(
                    node_id=node_id,
                    node_rank=node_id,
                    local_world_size=1,
                    network_check=True,
                    exclude_straggler=False,
                    rdzv_timeout=60.0,
                )
                agent = ElasticAgent(
                    config, [sys.executable, "-c", ""], client=client
                )
                results[node_id] = agent.run_network_check()

            threads = [
                threading.Thread(
                    target=run_one, args=(i,), daemon=True
                )
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert results == {0: True, 1: True, 2: True}
        finally:
            master.stop()

    def test_cli_flag_reaches_agent_config(self):
        from dlrover_tpu.trainer.elastic_run import parse_args

        args = parse_args(
            ["--network-check", "--exclude-straggler", "t.py"]
        )
        assert args.exclude_straggler is True
        args = parse_args(["t.py"])
        assert args.exclude_straggler is False


class TestStandaloneCli:
    def test_end_to_end(self, tmp_path):
        """dlrover-tpu-run --standalone runs a real training script that
        talks to the auto-spawned master for data shards."""
        script = tmp_path / "train.py"
        script.write_text(
            "from dlrover_tpu.agent.master_client import MasterClient\n"
            "from dlrover_tpu.agent.sharding_client import "
            "IndexShardingClient\n"
            "client = MasterClient.singleton()\n"
            "sc = IndexShardingClient('d', batch_size=2, client=client)\n"
            "sc.create_dataset(dataset_size=8, batch_size=2)\n"
            "seen = []\n"
            "while True:\n"
            "    i = sc.fetch_sample_index()\n"
            "    if i is None: break\n"
            "    seen.append(i)\n"
            "assert sorted(seen) == list(range(8)), seen\n"
            "client.report_step(step=4, tokens=64)\n"
            "print('TRAIN_OK')\n"
        )
        from dlrover_tpu.trainer.elastic_run import main

        env_backup = dict(os.environ)
        try:
            MasterClient.reset()
            code = main(
                [
                    "--standalone",
                    "--nproc_per_node",
                    "1",
                    str(script),
                ]
            )
        finally:
            os.environ.clear()
            os.environ.update(env_backup)
            MasterClient.reset()
        assert code == 0
