"""PS-elastic sparse path tests.

Modeled on the reference's test strategy (dlrover/python/tests/
test_ps_manager.py + test_sync_service.py style: real in-process
services, simulated membership events): real PS RPC servers in-process,
a real PsManager orchestrating partition moves, and a kill-one-PS drill
asserting no lost embeddings (restore from the delta flush files).
"""

import threading
import time

import numpy as np
import pytest

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.master.ps_manager import PsManager
from dlrover_tpu.sparse.partition import (
    PartitionMap,
    balanced_assignment,
    key_partition,
)
from dlrover_tpu.sparse.ps_client import DistributedKvClient
from dlrover_tpu.sparse.ps_server import PsServer

DIMS = {"emb": 8}


def _start_ps(node_id, tmp_path, num_partitions=16):
    ps = PsServer(
        node_id=node_id,
        checkpoint_dir=str(tmp_path / "sparse_ckpt"),
        embedding_dims=DIMS,
        num_partitions=num_partitions,
        seed=node_id * 100,
    )
    ps.start()
    return ps


@pytest.fixture()
def cluster(tmp_path):
    """2 PS + manager, partitions assigned."""
    mgr = PsManager(num_partitions=16)
    servers = {}
    for i in (0, 1):
        ps = _start_ps(i, tmp_path, 16)
        servers[i] = ps
        mgr.register_ps(i, ps.addr)
    yield mgr, servers, tmp_path
    for ps in servers.values():
        ps.stop()


def _make_client(mgr):
    return DistributedKvClient(
        lambda: mgr.partition_map, DIMS, retry_interval=0.05
    )


class TestPartitioning:
    def test_key_partition_spread(self):
        parts = key_partition(np.arange(10_000, dtype=np.int64), 16)
        counts = np.bincount(parts, minlength=16)
        assert counts.min() > 300  # roughly uniform

    def test_balanced_assignment_minimal_move(self):
        a1 = balanced_assignment([0, 1], 16)
        pm = PartitionMap(version=1, assignment=a1)
        a2 = balanced_assignment([0, 1, 2], 16, previous=pm)
        moved = sum(1 for x, y in zip(a1, a2) if x != y)
        # adding a third node moves only ~1/3 of partitions
        assert moved <= 6
        assert max(np.bincount(a2, minlength=3)) <= 6

    def test_dead_node_partitions_reassigned(self):
        a1 = balanced_assignment([0, 1, 2], 16)
        pm = PartitionMap(version=1, assignment=a1)
        a2 = balanced_assignment([0, 2], 16, previous=pm)
        assert 1 not in a2
        # survivors keep everything they had
        for p, owner in enumerate(a1):
            if owner in (0, 2):
                assert a2[p] == owner


class TestLookupApply:
    def test_routed_lookup_and_update(self, cluster):
        mgr, servers, _ = cluster
        client = _make_client(mgr)
        keys = np.arange(64, dtype=np.int64)
        vals = client.lookup("emb", keys)
        assert vals.shape == (64, 8)
        # rows landed on both shards
        sizes = [len(ps.table("emb")) for ps in servers.values()]
        assert all(s > 0 for s in sizes) and sum(sizes) == 64

        # sgd-like apply then read-back: lookup must reflect updates
        before = client.lookup("emb", keys)
        grads = np.ones((64, 8), np.float32)
        client.apply_gradients(
            "emb", keys, grads, step=1, optimizer="adagrad", lr=0.1
        )
        after = client.lookup("emb", keys)
        assert not np.allclose(before, after)
        client.close()

    def test_stale_map_rejected_and_retried(self, cluster):
        mgr, servers, _ = cluster
        client = _make_client(mgr)
        keys = np.arange(16, dtype=np.int64)
        client.lookup("emb", keys)  # caches map v_k
        # master publishes a new version (freeze-free no-op rebalance)
        mgr._rebalance(reason="test bump")  # noqa: SLF001
        # client's cached map is stale; fan-out must refetch and succeed
        vals = client.lookup("emb", keys)
        assert vals.shape == (16, 8)
        client.close()


class TestElasticity:
    def test_scale_up_moves_rows(self, cluster):
        """Adding a PS moves whole partitions with their rows AND
        optimizer slots (delta export/import PS-to-PS)."""
        mgr, servers, tmp_path = cluster
        client = _make_client(mgr)
        keys = np.arange(256, dtype=np.int64)
        client.lookup("emb", keys)
        client.apply_gradients(
            "emb", keys, np.ones((256, 8), np.float32), step=1,
            optimizer="adam", lr=0.01,
        )
        vals_before = client.lookup("emb", keys)

        ps2 = _start_ps(2, tmp_path, 16)
        servers[2] = ps2
        mgr.register_ps(2, ps2.addr)

        assert len(ps2.table("emb")) > 0  # data actually moved
        # values identical after the move
        vals_after = client.lookup("emb", keys)
        np.testing.assert_allclose(vals_before, vals_after)
        # optimizer slots moved too: another adam step keeps momentum
        st = ps2._tables["emb"].state_dict()
        assert "m" in st["slots"] and st["slots"]["m"][0].size > 0
        client.close()

    def test_kill_one_ps_no_lost_embeddings(self, cluster):
        """The BASELINE drill: train, flush, kill a PS; survivors
        restore its partitions from the per-partition delta files —
        every key keeps its last-flushed value."""
        mgr, servers, _ = cluster
        client = _make_client(mgr)
        keys = np.arange(512, dtype=np.int64)
        client.lookup("emb", keys)
        for step in (1, 2, 3):
            client.apply_gradients(
                "emb", keys, np.full((512, 8), 0.1, np.float32),
                step=step, optimizer="adagrad", lr=0.1,
            )
        flushed = mgr.flush_all(step=3)
        assert flushed >= 512
        vals_before = client.lookup("emb", keys, train=False)

        # kill PS 1 hard (no graceful export)
        dead = servers.pop(1)
        dead_rows = len(dead.table("emb"))
        assert dead_rows > 0
        dead.stop()
        mgr.remove_ps(1)

        vals_after = client.lookup("emb", keys, train=False)
        np.testing.assert_allclose(vals_before, vals_after, rtol=1e-6)
        # survivor actually absorbed the dead shard's rows
        assert len(servers[0].table("emb")) == 512
        client.close()

    def test_drain_moves_rows_live_without_flush(self, cluster):
        """Hot-PS migration path: drain a still-alive PS — its rows
        must move PS-to-PS (no checkpoint flush ever happened), unlike
        remove_ps which restores from the flush dir."""
        mgr, servers, _ = cluster
        client = _make_client(mgr)
        keys = np.arange(256, dtype=np.int64)
        client.lookup("emb", keys)
        client.apply_gradients(
            "emb", keys, np.full((256, 8), 0.1, np.float32),
            step=1, optimizer="adagrad", lr=0.1,
        )
        vals_before = client.lookup("emb", keys, train=False)
        drained = servers[1]
        assert len(drained.table("emb")) > 0
        mgr.drain_ps(1)  # NOTE: no flush_all before this
        # survivor owns everything; drained node can stop now
        assert set(mgr.partition_map.assignment) == {0}
        drained.stop()
        servers.pop(1)
        vals_after = client.lookup("emb", keys, train=False)
        np.testing.assert_allclose(vals_before, vals_after, rtol=1e-6)
        assert len(servers[0].table("emb")) == 256
        # optimizer slots moved too: another step on the survivor
        # continues adagrad from the accumulated state (values keep
        # moving, no reset-sized jump)
        client.apply_gradients(
            "emb", keys, np.full((256, 8), 0.1, np.float32),
            step=2, optimizer="adagrad", lr=0.1,
        )
        client.close()

    def test_concurrent_traffic_through_reshard(self, cluster):
        """Workers keep training while the master reshards: stale-map
        rejections retry transparently, nothing is lost or wedged."""
        mgr, servers, tmp_path = cluster
        client = _make_client(mgr)
        keys = np.arange(128, dtype=np.int64)
        client.lookup("emb", keys)
        stop = threading.Event()
        errors = []

        def trainer():
            step = 0
            while not stop.is_set():
                step += 1
                try:
                    client.apply_gradients(
                        "emb", keys, np.ones((128, 8), np.float32),
                        step=step, optimizer="adagrad", lr=0.01,
                    )
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return

        t = threading.Thread(target=trainer)
        t.start()
        time.sleep(0.2)
        ps2 = _start_ps(2, tmp_path, 16)
        servers[2] = ps2
        mgr.register_ps(2, ps2.addr)
        time.sleep(0.3)
        stop.set()
        t.join(timeout=10)
        assert not t.is_alive()
        assert not errors
        client.close()


class TestCheckpointFlush:
    def test_delta_flush_is_incremental(self, cluster):
        mgr, servers, _ = cluster
        client = _make_client(mgr)
        keys = np.arange(64, dtype=np.int64)
        client.lookup("emb", keys)
        client.apply_gradients(
            "emb", keys, np.ones((64, 8), np.float32), step=1,
            optimizer="adagrad",
        )
        first = mgr.flush_all(step=1)
        assert first >= 64
        # nothing touched since -> delta flush writes ~nothing
        second = mgr.flush_all(step=2)
        assert second == 0
        # touch 8 keys -> only those flush
        sub = keys[:8]
        client.apply_gradients(
            "emb", sub, np.ones((8, 8), np.float32), step=3,
            optimizer="adagrad",
        )
        third = mgr.flush_all(step=3)
        assert 0 < third <= 8
        client.close()


class TestAuxTensorWire:
    def test_adahessian_over_the_wire(self, cluster):
        """The aux tensor (Hutchinson Hessian diagonals) rides
        PsApplyRequest next to the gradients and is sliced per shard
        exactly like them."""
        mgr, servers, _ = cluster
        client = _make_client(mgr)
        keys = np.arange(64, dtype=np.int64)
        before = client.lookup("emb", keys).copy()
        grads = np.random.default_rng(0).normal(
            size=(64, DIMS["emb"])
        ).astype(np.float32)
        client.apply_gradients(
            "emb", keys, grads, step=1, optimizer="adahessian",
            lr=0.1, hessian=grads, hessian_power=1.0,
        )
        after = client.lookup("emb", keys, train=False)
        assert not np.allclose(before, after)
        assert np.isfinite(after).all()
        client.close()
