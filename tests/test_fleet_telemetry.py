"""Fleet telemetry: snapshot aggregation, goodput accounting,
straggler scoring.

Covers the acceptance criteria of the fleet-telemetry PR:

* the goodput accountant is exhaustive and exclusive — on synthetic
  traces with known phase durations the bucket sums equal total wall
  time (property-tested over randomized streams);
* the master's /metrics endpoint and MetricsRequest RPC serve
  host-labeled aggregated series from >= 2 simulated agent snapshots,
  with departed hosts aged out;
* query_stragglers returns a host that is artificially slowed, and
  ``node.straggler`` appears in the event stream.
"""

import random
import time
from types import SimpleNamespace

import pytest

from dlrover_tpu import obs
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.master.master import JobMaster
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.obs.fleet import FleetAggregator, _percentile
from dlrover_tpu.obs.goodput import (
    CATEGORIES,
    GoodputAccountant,
    attribute_goodput,
    render_goodput,
)
from dlrover_tpu.obs.metrics import MetricsRegistry


def make_snapshot(node_id, host, step_times=(), syncs=0.0,
                  tokens_per_s=None, events=(), registry=None):
    if registry is None:
        registry = {
            "dlrover_train_steps_total": {
                "type": "counter", "help": "steps this process",
                "labelnames": [], "series": [[[], 100 + node_id]],
            },
            "dlrover_train_host_syncs_total": {
                "type": "counter", "help": "host syncs",
                "labelnames": ["reason"],
                "series": [[["log"], syncs]],
            },
            "dlrover_train_step_seconds": {
                "type": "histogram", "help": "step seconds",
                "labelnames": [], "buckets": [0.1, 1.0],
                "series": [[[], [1, 2, 2], 0.6, 2]],
            },
        }
    resource = {"cpu_percent": 10.0 + node_id}
    if tokens_per_s is not None:
        resource["tokens_per_s"] = tokens_per_s
    return SimpleNamespace(
        node_id=node_id,
        host=host,
        timestamp=time.time(),
        registry=registry,
        resource=resource,
        step_times=list(step_times),
        events=list(events),
    )


class TestFleetAggregator:
    def test_host_labeled_series_and_aggregates(self):
        reg = MetricsRegistry()
        fleet = FleetAggregator(registry=reg, ttl=3600.0)
        fleet.ingest(make_snapshot(0, "h0", step_times=[0.1, 0.1],
                                   syncs=3, tokens_per_s=1200.0))
        fleet.ingest(make_snapshot(1, "h1", step_times=[0.4, 0.4],
                                   syncs=5, tokens_per_s=800.0))
        body = reg.render()
        assert 'dlrover_train_steps_total{host="h0"} 100' in body
        assert 'dlrover_train_steps_total{host="h1"} 101' in body
        # histogram series re-rendered with the host label
        assert (
            'dlrover_train_step_seconds_bucket{host="h0",le="0.1"} 1'
            in body
        )
        assert 'dlrover_train_step_seconds_sum{host="h1"} 0.6' in body
        assert "dlrover_fleet_hosts 2" in body
        aggs = fleet.aggregates()
        assert aggs["step_time_s"]["min"] == pytest.approx(0.1)
        assert aggs["step_time_s"]["max"] == pytest.approx(0.4)
        assert aggs["host_syncs_total"]["sum"] == pytest.approx(8.0)
        assert aggs["tokens_per_s"]["sum"] == pytest.approx(2000.0)
        fleet.close()

    def test_reingest_replaces_not_accumulates(self):
        reg = MetricsRegistry()
        fleet = FleetAggregator(registry=reg, ttl=3600.0)
        fleet.ingest(make_snapshot(0, "h0", syncs=3))
        fleet.ingest(make_snapshot(0, "h0", syncs=9))
        body = reg.render()
        assert (
            'dlrover_train_host_syncs_total{reason="log",host="h0"} 9'
            in body
        )
        assert (
            'dlrover_train_host_syncs_total{reason="log",host="h0"} 3'
            not in body
        )
        assert "dlrover_fleet_hosts 1" in body
        fleet.close()

    def test_departed_hosts_age_out_by_ttl(self):
        reg = MetricsRegistry()
        fleet = FleetAggregator(registry=reg, ttl=0.05)
        fleet.ingest(make_snapshot(0, "h0"))
        assert fleet.hosts() == ["h0"]
        time.sleep(0.1)
        assert fleet.hosts() == []
        assert 'host="h0"' not in reg.render()
        fleet.close()

    def test_remove_node_drops_immediately(self):
        reg = MetricsRegistry()
        fleet = FleetAggregator(registry=reg, ttl=3600.0)
        fleet.ingest(make_snapshot(0, "h0"))
        fleet.ingest(make_snapshot(1, "h1"))
        fleet.remove_node(0)
        assert fleet.hosts() == ["h1"]
        assert 'host="h0"' not in reg.render()
        fleet.close()

    def test_step_times_feed_speed_monitor(self):
        sm = SpeedMonitor(min_straggler_hosts=2)
        fleet = FleetAggregator(
            registry=MetricsRegistry(), speed_monitor=sm, ttl=3600.0
        )
        fleet.ingest(make_snapshot(0, "h0", step_times=[0.2, 0.2]))
        assert sm.host_step_ewma()[0] == pytest.approx(0.2)
        fleet.close()

    def test_events_feed_goodput(self):
        reg = MetricsRegistry()
        gp = GoodputAccountant(registry=reg)
        fleet = FleetAggregator(registry=reg, goodput=gp, ttl=3600.0)
        t = 1000.0
        fleet.ingest(make_snapshot(0, "h0", events=[
            {"name": "trainer.step", "ts": t},
            {"name": "trainer.step", "ts": t + 2.0},
        ]))
        body = reg.render()
        assert (
            'dlrover_goodput_seconds_total{category="productive"} 2'
            in body
        )
        assert "dlrover_goodput_ratio 1" in body
        fleet.close()

    def test_percentile_nearest_rank(self):
        assert _percentile([1.0, 2.0, 3.0], 50.0) == 2.0
        assert _percentile([1.0], 90.0) == 1.0
        assert _percentile([], 50.0) == 0.0


class TestGoodput:
    def test_known_trace_buckets(self):
        t = 0.0
        events = [
            {"name": "node.fail", "ts": t + 10.0},
            {"name": "trainer.first_step_done", "ts": t + 30.0},
            # compile: END-stamped span of 5s -> [30, 35]... emitted
            # at 35 with dur 5
            {"name": "trainer.compile_done", "ts": t + 35.0,
             "dur_s": 5.0},
            {"name": "trainer.step", "ts": t + 35.0},
            {"name": "trainer.step", "ts": t + 45.0},
            # data wait inside the step interval: carved out of
            # productive
            {"name": "trainer.prefetch_wait", "ts": t + 40.0,
             "dur_s": 2.0},
            # checkpoint span [45, 49]
            {"name": "ckpt.save_memory", "ts": t + 45.0, "dur_s": 4.0},
            {"name": "trainer.step", "ts": t + 50.0},
        ]
        gp = attribute_goodput(events, t0=0.0, t1=50.0)
        assert gp.seconds["recovery"] == pytest.approx(20.0)
        assert gp.seconds["compile"] == pytest.approx(5.0)
        assert gp.seconds["data_wait"] == pytest.approx(2.0)
        assert gp.seconds["checkpoint"] == pytest.approx(4.0)
        # steps span [35,50] minus wait(2) minus ckpt(4) minus 0
        assert gp.seconds["productive"] == pytest.approx(9.0)
        assert gp.seconds["idle_unknown"] == pytest.approx(10.0)
        assert sum(gp.seconds.values()) == pytest.approx(50.0)
        assert gp.goodput_ratio == pytest.approx(9.0 / 50.0)
        out = render_goodput(gp)
        assert "recovery" in out and "idle_unknown" in out

    def test_unrecovered_failure_is_badput_to_window_end(self):
        events = [
            {"name": "trainer.step", "ts": 0.0},
            {"name": "trainer.step", "ts": 5.0},
            {"name": "node.gone", "ts": 6.0},
        ]
        gp = attribute_goodput(events, t0=0.0, t1=20.0)
        assert gp.seconds["recovery"] == pytest.approx(14.0)
        assert gp.seconds["productive"] == pytest.approx(5.0)
        assert gp.seconds["idle_unknown"] == pytest.approx(1.0)

    def test_recovery_closes_on_first_step_without_phase_mark(self):
        """With tracing off on the hosts, the master only sees its own
        failure events and the steps it synthesizes from StepReports —
        a landed step must close the recovery interval."""
        events = [
            {"name": "node.fail", "ts": 10.0},
            {"name": "trainer.step", "ts": 25.0},
            {"name": "trainer.step", "ts": 30.0},
        ]
        gp = attribute_goodput(events, t0=0.0, t1=30.0)
        assert gp.seconds["recovery"] == pytest.approx(15.0)
        assert gp.seconds["productive"] == pytest.approx(5.0)
        assert gp.seconds["idle_unknown"] == pytest.approx(10.0)

    def test_default_window_covers_trailing_span(self):
        """A start-stamped span at the stream tail extends past its
        ts; the default window must include it, not clip it to zero."""
        events = [
            {"name": "trainer.step", "ts": 0.0},
            {"name": "trainer.step", "ts": 5.0},
            {"name": "ckpt.save_memory", "ts": 5.0, "dur_s": 3.0},
        ]
        gp = attribute_goodput(events)
        assert gp.t1 == pytest.approx(8.0)
        assert gp.seconds["checkpoint"] == pytest.approx(3.0)

    @pytest.mark.parametrize("seed", range(8))
    def test_property_exhaustive_and_exclusive(self, seed):
        """Random event soup: every second of the window lands in
        exactly one bucket — sums match the window length exactly and
        no bucket is negative."""
        rng = random.Random(seed)
        t = 0.0
        events = []
        for _ in range(rng.randint(20, 120)):
            t += rng.uniform(0.0, 3.0)
            kind = rng.random()
            if kind < 0.35:
                events.append({"name": "trainer.step", "ts": t})
            elif kind < 0.5:
                events.append({
                    "name": "trainer.prefetch_wait", "ts": t,
                    "dur_s": rng.uniform(0.0, 2.0),
                })
            elif kind < 0.65:
                events.append({
                    "name": "ckpt.save_memory", "ts": t,
                    "dur_s": rng.uniform(0.0, 4.0),
                })
            elif kind < 0.75:
                events.append({
                    "name": "trainer.compile_done", "ts": t,
                    "dur_s": rng.uniform(0.0, 5.0),
                })
            elif kind < 0.85:
                events.append({
                    "name": rng.choice(
                        ["node.fail", "node.gone",
                         "node.heartbeat_timeout"]
                    ),
                    "ts": t,
                })
            else:
                events.append(
                    {"name": "trainer.first_step_done", "ts": t}
                )
        rng.shuffle(events)  # order of arrival must not matter
        t0, t1 = -5.0, t + 5.0
        gp = attribute_goodput(events, t0=t0, t1=t1)
        assert gp is not None
        assert set(gp.seconds) == set(CATEGORIES)
        for cat, sec in gp.seconds.items():
            assert sec >= 0.0, f"{cat} went negative: {sec}"
        assert sum(gp.seconds.values()) == pytest.approx(
            t1 - t0, abs=1e-6
        )

    def test_empty_stream(self):
        assert attribute_goodput([]) is None
        gp = attribute_goodput([], t0=0.0, t1=10.0)
        assert gp.seconds["idle_unknown"] == pytest.approx(10.0)

    def test_accountant_sets_gauges_and_bounds_events(self):
        reg = MetricsRegistry()
        acct = GoodputAccountant(registry=reg, max_events=10)
        acct.add_events(
            {"name": "trainer.step", "ts": float(i)} for i in range(50)
        )
        report = acct.account()
        assert report.steps == 10  # bounded to max_events (newest)
        body = reg.render()
        assert 'dlrover_goodput_seconds_total{category="productive"}' \
            in body
        assert "dlrover_goodput_ratio 1" in body

    def test_accountant_debounces_reaccounting(self):
        acct = GoodputAccountant(
            registry=MetricsRegistry(), min_account_interval=3600.0
        )
        acct.add_events([{"name": "trainer.step", "ts": float(i)}
                         for i in range(3)])
        first = acct.account()
        acct.add_events([{"name": "trainer.step", "ts": 10.0}])
        assert acct.account() is first      # inside the debounce
        forced = acct.account(force=True)   # bypass recomputes
        assert forced is not first and forced.steps == 4


class TestStragglerScoring:
    def setup_method(self):
        self.tracer = obs.configure_tracer()

    def teardown_method(self):
        obs.disable_tracer()

    def feed(self, sm, times):
        for node_id, step_time in times:
            sm.observe_host_step_time(node_id, step_time)

    def test_slow_host_scored_and_event_emitted(self):
        sm = SpeedMonitor()
        before = obs.get_registry().counter(
            "dlrover_straggler_total",
            labelnames=("node",),
        ).value(node="2")
        for _ in range(5):
            self.feed(sm, [(0, 0.10), (1, 0.11), (2, 0.50)])
        assert sm.stragglers() == [2]
        scores = sm.straggler_scores()
        assert scores[2] > 2.0 > scores[0]
        after = obs.get_registry().counter(
            "dlrover_straggler_total",
            labelnames=("node",),
        ).value(node="2")
        assert after == before + 1  # transition counted once
        names = [e["name"] for e in self.tracer.events()]
        assert "node.straggler" in names
        ev = next(
            e for e in self.tracer.events()
            if e["name"] == "node.straggler"
        )
        assert ev["node_id"] == 2
        assert ev["score"] > 2.0

    def test_needs_minimum_hosts(self):
        sm = SpeedMonitor()
        for _ in range(5):
            self.feed(sm, [(0, 0.1), (1, 0.9)])
        assert sm.stragglers() == []  # 2 hosts cannot out-vote

    def test_needs_minimum_samples(self):
        sm = SpeedMonitor()
        self.feed(sm, [(0, 0.1), (1, 0.1), (2, 0.9)])
        assert sm.stragglers() == []  # 1 sample each

    def test_recovered_straggler_leaves_the_set(self):
        sm = SpeedMonitor()
        for _ in range(5):
            self.feed(sm, [(0, 0.1), (1, 0.1), (2, 0.8)])
        assert sm.stragglers() == [2]
        for _ in range(30):
            self.feed(sm, [(0, 0.1), (1, 0.1), (2, 0.1)])
        assert sm.stragglers() == []
        names = [e["name"] for e in self.tracer.events()]
        assert "node.straggler_recovered" in names

    def test_removed_node_clears_scoring_state(self):
        sm = SpeedMonitor()
        for _ in range(5):
            self.feed(sm, [(0, 0.1), (1, 0.1), (2, 0.9)])
        sm.add_running_node(2)
        sm.remove_running_node(2)
        assert 2 not in sm.host_step_ewma()
        assert sm.stragglers() == []

    def test_step_report_cadence_derives_step_times(self):
        sm = SpeedMonitor(min_straggler_hosts=1)
        t = 1000.0
        sm.collect_node_step(0, 10, timestamp=t)
        sm.collect_node_step(0, 20, timestamp=t + 5.0)
        assert sm.host_step_ewma()[0] == pytest.approx(0.5)


class TestMasterFleetEndToEnd:
    """Acceptance: host-labeled aggregated series over HTTP + RPC from
    two simulated agents, departed-host removal, and a live
    query_stragglers verdict."""

    @pytest.fixture()
    def master(self):
        m = JobMaster(
            port=0, node_num=3, rdzv_timeout=1.0, metrics_port=0,
            collect_interval=999.0,
        )
        m.prepare()
        yield m
        m.stop()

    def snapshot_msg(self, node_id, host, step_times):
        return msg.MetricsSnapshotReport(
            node_id=node_id,
            host=host,
            timestamp=time.time(),
            registry={
                "dlrover_train_steps_total": {
                    "type": "counter", "help": "steps",
                    "labelnames": [],
                    "series": [[[], 40 + node_id]],
                },
            },
            resource={"tokens_per_s": 500.0 + node_id},
            step_times=list(step_times),
            events=[],
        )

    def test_fleet_view_and_stragglers(self, master):
        tracer = obs.configure_tracer()
        try:
            client = RpcClient(master.addr)
            for nid in range(3):
                client.report(msg.NodeAddressRequest(
                    node_id=nid, node_ip=f"h{nid}"
                ))
            # Three agents snapshot; node 2 is artificially slowed.
            for _ in range(4):
                client.report(self.snapshot_msg(0, "h0", [0.1] * 3))
                client.report(self.snapshot_msg(1, "h1", [0.11] * 3))
                client.report(self.snapshot_msg(2, "h2", [0.55] * 3))

            import urllib.request

            url = (
                f"http://127.0.0.1:{master.metrics_server.port}"
                "/metrics"
            )
            body = urllib.request.urlopen(url, timeout=5).read().decode()
            assert 'dlrover_train_steps_total{host="h0"} 40' in body
            assert 'dlrover_train_steps_total{host="h1"} 41' in body
            assert 'dlrover_train_steps_total{host="h2"} 42' in body
            assert "dlrover_fleet_hosts 3" in body
            assert 'dlrover_fleet_series{series="step_time_s"' in body
            assert (
                'dlrover_fleet_series{series="tokens_per_s",'
                'stat="sum"} 1503' in body
            )
            # Same payload over the control-plane RPC.
            rpc_body = client.get(msg.MetricsRequest()).text
            assert 'dlrover_train_steps_total{host="h2"} 42' in rpc_body

            # The slowed host is a straggler, from live step times.
            resp = client.get(
                msg.NetworkCheckQueryRequest(kind="straggler")
            )
            assert 2 in resp.nodes
            names = [e["name"] for e in tracer.events()]
            assert "node.straggler" in names

            # Node 1 departs: its series leave the fleet view now.
            master.job_manager.handle_node_gone(1, "pod deleted")
            body = urllib.request.urlopen(url, timeout=5).read().decode()
            assert 'host="h1"' not in body
            assert "dlrover_fleet_hosts 2" in body
        finally:
            obs.disable_tracer()

    def test_step_reports_close_recovery_in_goodput(self, master):
        """No tracing anywhere: a failure report opens recovery and
        the next StepReport closes it, from master-side signals only."""
        client = RpcClient(master.addr)
        client.report(msg.NodeAddressRequest(node_id=0, node_ip="h0"))
        client.report(msg.NodeAddressRequest(node_id=1, node_ip="h1"))
        t = time.time()
        client.report(msg.StepReport(
            node_id=0, timestamp=t - 30.0, step=10, tokens=100
        ))
        client.report(msg.NodeFailureReport(
            node_id=1, error_data="oom", level="process_error",
        ))
        client.report(msg.StepReport(
            node_id=0, timestamp=t + 20.0, step=11, tokens=100
        ))
        report = master.goodput.account(force=True)
        assert report is not None
        assert report.seconds["recovery"] > 0
        # recovery CLOSED at the post-failure step: it must not run
        # to the window end.
        assert report.seconds["recovery"] < report.total_s
        assert report.seconds["productive"] > 0

    def test_resource_monitor_ships_snapshot_end_to_end(
        self, master, tmp_path
    ):
        """A real ResourceMonitor against a real master: the snapshot
        lands in the fleet aggregator."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.agent.monitor import (
            ResourceMonitor,
            TrainingMonitor,
        )

        client = MasterClient(master.addr, node_id=0)
        rpc = RpcClient(master.addr)
        rpc.report(msg.NodeAddressRequest(node_id=0, node_ip="h0"))
        metrics_file = str(tmp_path / "train_metrics.json")
        TrainingMonitor.write_metrics(
            5, tokens=1000, path=metrics_file, step_time=0.25
        )
        mon = ResourceMonitor(
            client, interval=999.0, metrics_file=metrics_file
        )
        mon.report_once()
        hosts = master.fleet.hosts()
        assert hosts == [mon.host]
        snap = master.fleet.live_snapshots()[0]
        assert snap.step_times == [0.25]
        assert snap.node_id == 0
        client.close()
