"""GPT language model, TPU-first.

Capability parity with the reference's nanoGPT example
(/root/reference/examples/pytorch/nanogpt/train.py — the model DLRover
uses for its elastic-training demos and BASELINE north star), designed
as an idiomatic JAX program rather than a port:

* pure-functional param pytree with *logical sharding axes* per leaf
  (parallel/sharding.py) — GSPMD shards it for DP/FSDP/TP/SP from one
  rule table, replacing torch DDP/FSDP wrappers;
* layers stacked and executed with ``lax.scan`` (one compile of one
  block regardless of depth);
* bf16 activations/weights with f32 layernorm + logits, MXU-friendly
  head dims;
* optional ring attention over the ``seq`` mesh axis for long context;
* ``jax.checkpoint`` rematerialization policy for HBM headroom.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304  # padded to a multiple of 128 for the MXU
    block_size: int = 1024
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    dropout: float = 0.0  # elastic training defaults to 0 (nanoGPT)
    dtype: Any = jnp.bfloat16
    # Policy names from accelerate/remat.py: "none" | "full" |
    # "attention" | "dots" | "offload" (block residuals to pinned
    # host RAM). True = full block remat; False = none; "attention" =
    # checkpoint
    # only the attention inner fn — the [B,H,T,T] softmax is the one
    # activation that doesn't fit, and recomputing it costs ~4% FLOPs
    # vs ~33% for full remat (measured on v5e: 0.29 -> 0.37 MFU).
    remat: Any = True
    # None = auto (flash on TPU at long context); True/False forces.
    use_flash_attention: Optional[bool] = None
    # None = auto (fused Pallas norm kernels on TPU,
    # ops/layer_norm.py); True/False forces.
    use_fused_norm: Optional[bool] = None
    # Declared attention masking. Decoder-only LMs are causal; the
    # auto_accelerate seq-parallel binding reads this so a non-causal
    # model config is never silently given a causal mask.
    causal: bool = True
    # Flash-attention tile override (block_q, block_k, block_q_bwd,
    # block_k_bwd); None = kernel defaults (default_block_sizes + the
    # forward blocks for the backward). The hardware autotune sweep
    # (tools/autotune_bwd_blocks.py) pins its winner here.
    attn_blocks: Optional[tuple] = None
    # lax.scan unroll factor for the layer stack. 1 = rolled (one
    # compiled block, smallest program); k>1 lets XLA fuse across k
    # consecutive layers and amortize the scan-carry
    # dynamic-update-slice traffic the r5 step profile attributes
    # ~16% of step time to. Any k >= 1 works — lax.scan handles a
    # remainder group and clamps k > n_layer (tests assert both). A
    # hardware-autotune axis, not a semantic knob.
    scan_unroll: int = 1

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @staticmethod
    def nano() -> "GPTConfig":
        """The reference nanoGPT 'baby GPT' demo size."""
        return GPTConfig(
            vocab_size=50304, block_size=256, n_layer=6, n_head=6,
            n_embd=384,
        )

    @staticmethod
    def gpt2() -> "GPTConfig":
        return GPTConfig()


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_params(key: jax.Array, cfg: GPTConfig) -> Params:
    """GPT-2-style init (normal 0.02, residual projections scaled by
    1/sqrt(2*n_layer)). Layer params are stacked on a leading 'layers'
    dim for lax.scan."""
    k_wte, k_wpe, k_blocks = jax.random.split(key, 3)
    std = 0.02
    resid_std = 0.02 / np.sqrt(2 * cfg.n_layer)
    E, H, L = cfg.n_embd, cfg.n_head, cfg.n_layer

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(
            cfg.dtype
        )

    ks = jax.random.split(k_blocks, 6)

    def stack(k, shape, s=std):
        return norm(k, (L,) + shape, s)

    params: Params = {
        "wte": norm(k_wte, (cfg.vocab_size, E)),
        "wpe": norm(k_wpe, (cfg.block_size, E)),
        "blocks": {
            "ln1_g": jnp.ones((L, E), jnp.float32),
            "ln1_b": jnp.zeros((L, E), jnp.float32),
            "wqkv": stack(ks[0], (E, 3 * E)),
            "wo": stack(ks[1], (E, E), resid_std),
            "ln2_g": jnp.ones((L, E), jnp.float32),
            "ln2_b": jnp.zeros((L, E), jnp.float32),
            "wi": stack(ks[2], (E, 4 * E)),
            "bi": jnp.zeros((L, 4 * E), cfg.dtype),
            "wo2": stack(ks[3], (4 * E, E), resid_std),
            "bo2": jnp.zeros((L, E), cfg.dtype),
        },
        "lnf_g": jnp.ones((E,), jnp.float32),
        "lnf_b": jnp.zeros((E,), jnp.float32),
    }
    return params


def param_logical_axes(cfg: GPTConfig) -> Params:
    """Logical sharding axes per parameter leaf (same tree shape)."""
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": {
            "ln1_g": ("layers", None),
            "ln1_b": ("layers", None),
            "wqkv": ("layers", "embed", "heads"),
            "wo": ("layers", "heads", "embed"),
            "ln2_g": ("layers", None),
            "ln2_b": ("layers", None),
            "wi": ("layers", "embed", "mlp"),
            "bi": ("layers", "mlp"),
            "wo2": ("layers", "mlp", "embed"),
            "bo2": ("layers", None),
        },
        "lnf_g": (None,),
        "lnf_b": (None,),
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _layer_norm(x, g, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps) * g + b
    return out.astype(x.dtype)


def use_fused_norm(cfg) -> bool:
    """Fused Pallas norms (ops/layer_norm.py) are OPT-IN, default off.

    Measured on v5e (fwd+bwd grad, N=16384 rows, 2026-07-31): XLA's
    own norm fusion wins at every width — 4.5-5.9 ms vs the Pallas
    kernel's 18.8-30.5 ms across E in {768, 1024, 2048, 4096, 8192};
    at the bench config the A/B costs ~1 ms/step (0.891 vs 0.909
    vs_baseline). The dgamma/dbeta accumulator serializes the row
    grid ("arbitrary" semantics, one shared partial block), while
    XLA parallelizes the reduction freely. The kernel stays for
    capability parity (the reference ships a fused LayerNorm,
    atorch/normalization) and for hardware where XLA's fusion is
    weaker — select it per-config with use_fused_norm=True."""
    if cfg.use_fused_norm is not None:
        return cfg.use_fused_norm
    return False


def _default_attention(q, k, v, causal=True, window=None):
    """Plain fused attention (single-shard fallback; the sharded path
    comes from parallel.ring_attention.make_sharded_attention).
    ``window`` applies the same Mistral-style sliding-window band as
    the flash kernel (query i sees keys (i-window, i])."""
    if window is not None and not causal:
        # Same contract as flash_attention: a one-sided band with
        # bidirectional attention would mean different models per
        # backend, not a graceful fallback.
        raise ValueError(
            "window (sliding-window attention) requires causal=True"
        )
    b, lq, h, d = q.shape
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / np.sqrt(d)
    if causal or window is not None:
        pos = jnp.arange(lq)
        mask = jnp.ones((lq, lq), bool)
        if causal:
            mask &= pos[:, None] >= pos[None, :]
        if window is not None:
            mask &= (pos[:, None] - pos[None, :]) < window
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block(x, lp, cfg: GPTConfig, attn_fn):
    """One transformer block. lp = this layer's param slice.

    The ``jax.named_scope`` annotations are load-bearing: the
    module profiler (utils/module_profiler.py) attributes FLOPs /
    bytes per scope from the jaxpr, feeding the strategy engine's
    roofline prior and the TP planner's per-edge costs."""
    B, T, E = x.shape
    H, D = cfg.n_head, cfg.head_dim
    fused = use_fused_norm(cfg)
    if fused:
        from dlrover_tpu.ops.layer_norm import (
            fused_add_layer_norm,
            fused_layer_norm,
        )
    with jax.named_scope("attn"):
        if fused:
            h = fused_layer_norm(x, lp["ln1_g"], lp["ln1_b"])
        else:
            h = _layer_norm(x, lp["ln1_g"], lp["ln1_b"])
        qkv = h @ lp["wqkv"]  # [B,T,3E]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        att = attn_fn(q, k, v).reshape(B, T, E)
        att_out = att @ lp["wo"]
    with jax.named_scope("mlp"):
        if fused:
            # The attention residual add rides inside the norm kernel
            # (one HBM pass for the branch point).
            h, x = fused_add_layer_norm(
                att_out, x, lp["ln2_g"], lp["ln2_b"]
            )
        else:
            x = x + att_out
            h = _layer_norm(x, lp["ln2_g"], lp["ln2_b"])
        h = jax.nn.gelu(h @ lp["wi"] + lp["bi"])
        x = x + h @ lp["wo2"] + lp["bo2"]
    return x


def default_attention_for(cfg: GPTConfig) -> Callable:
    """Pick the attention implementation for this config.

    On TPU the Pallas flash kernel (ops/flash_attention.py) wins from
    ~512 context up (measured v5e, GPT-2 shapes: fwd+bwd 6.3ms/layer
    flash vs 9.4ms XLA at 1024 — XLA materializes [B,H,T,T] f32 scores
    in HBM) and is mandatory beyond ~4k where the scores exceed HBM.
    ``cfg.use_flash_attention`` forces either path; None auto-selects
    (flash on TPU from 512 context up).
    """
    use_flash = cfg.use_flash_attention
    if use_flash is None:
        use_flash = (
            jax.default_backend() == "tpu" and cfg.block_size >= 512
        )
    causal = getattr(cfg, "causal", True)
    window = getattr(cfg, "sliding_window", None)
    if use_flash:
        from dlrover_tpu.ops.flash_attention import flash_attention

        from dlrover_tpu.ops.flash_attention import blocks_kwargs

        block_kwargs = blocks_kwargs(getattr(cfg, "attn_blocks", None))
        return functools.partial(
            flash_attention, causal=causal, window=window,
            **block_kwargs,
        )
    return functools.partial(
        _default_attention, causal=causal, window=window
    )


def backbone(
    params: Params,
    tokens: jax.Array,
    cfg: GPTConfig,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    """Forward WITHOUT the unembedding: [B, T] -> final hidden
    [B, T, E]. Loss paths that fuse the vocab projection (fused
    cross-entropy) start here."""
    if attn_fn is None:
        attn_fn = default_attention_for(cfg)
    B, T = tokens.shape
    with jax.named_scope("embed"):
        x = params["wte"][tokens] + params["wpe"][:T][None]
        x = x.astype(cfg.dtype)
    from dlrover_tpu.accelerate.remat import wire_block

    block = wire_block(
        lambda x, lp, af: _block(x, lp, cfg=cfg, attn_fn=af),
        cfg.remat,
        attn_fn,
    )

    def scan_body(x, lp):
        return block(x, lp), None

    x, _ = jax.lax.scan(
        scan_body, x, params["blocks"], unroll=cfg.scan_unroll
    )
    return _layer_norm(x, params["lnf_g"], params["lnf_b"])


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: GPTConfig,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab] float32."""
    x = backbone(params, tokens, cfg, attn_fn)
    # Tied embeddings (nanoGPT): logits via wte^T, f32 for stable loss.
    with jax.named_scope("head"):
        logits = jnp.einsum(
            "bte,ve->btv",
            x,
            params["wte"],
            preferred_element_type=jnp.float32,
        )
    return logits


def loss_fn(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: GPTConfig,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    logits = forward(params, tokens, cfg, attn_fn)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(ll)


def loss_fn_fused(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: GPTConfig,
    attn_fn: Optional[Callable] = None,
    num_chunks: int = 8,
    save_logits: bool = False,
) -> jax.Array:
    """Same loss via the fused chunked cross-entropy
    (ops/cross_entropy.py): never materializes [B*T, V] log-softmax,
    backward matmuls get bf16 cotangents. Use for big batch*seq.
    ``save_logits`` trades [N,V] bf16 HBM for skipping the backward
    logits recompute — right for GPT-2-size vocab heads with headroom."""
    from dlrover_tpu.ops.cross_entropy import fused_cross_entropy

    x = backbone(params, tokens, cfg, attn_fn)
    n = x.shape[0] * x.shape[1]
    return fused_cross_entropy(
        x.reshape(n, -1), params["wte"], targets.reshape(n), num_chunks,
        save_logits,
    )


def num_params(params: Params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def flops_per_token(cfg: GPTConfig) -> float:
    """Training FLOPs per token via the standard PaLM MFU convention:
    6*N_matmul + 12*L*T*E (attention score+value matmuls, no causal
    discount). Used for MFU/HFU accounting (ref atorch AProfiler role).

    Per-layer matmul params: wqkv 3E^2 + wo E^2 + wi 4E^2 + wo2 4E^2
    = 12E^2; plus the (tied) unembedding V*E.
    """
    E, L = cfg.n_embd, cfg.n_layer
    n_matmul = 12 * L * E * E + cfg.vocab_size * E
    attn = 12 * L * cfg.block_size * E
    return 6.0 * n_matmul + attn
