"""Pipelined Llama training: the second model family through the
full-LM 1F1B assembly (models/pipeline_lm.py; GPT twin
models/gpt_pipeline.py).

Edge placement: token embedding outside the schedule (RoPE needs no
positional embedding table — cos/sin are compile-time constants baked
into every stage), the RMSNorm/GQA/SwiGLU block stack pipelined, and
final RMSNorm + untied lm_head cross-entropy at the last stage.

MoE configs are supported through the schedule's ``stage_aux``
channel: each chunk emits its summed router load-balancing loss,
which the 1F1B body accumulates across stages, means over
microbatches, and differentiates (cotangent 1 per valid backward) —
so the pipelined objective is the microbatched-serial one, never a
silently-dropped balancing term.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from dlrover_tpu.models import gpt, llama
from dlrover_tpu.models.pipeline_lm import (
    LmPipelineBuilder,
    make_pipelined_lm_step,
    shard_params_for_pipeline,  # noqa: F401 — re-export (tests/docs)
)
from dlrover_tpu.parallel.pipeline import split_stages_interleaved


def _stage_fn(chunk, x, cfg: llama.LlamaConfig, attn_fn, cos, sin):
    # Dense path: same scan, aux discarded (the zero-aux carry is
    # DCE'd by XLA) — the llama.py backbone/backbone_with_aux pattern.
    return _stage_fn_aux(
        chunk, x, cfg=cfg, attn_fn=attn_fn, cos=cos, sin=sin
    )[0]


def _stage_fn_aux(chunk, x, cfg: llama.LlamaConfig, attn_fn, cos,
                  sin):
    """MoE variant: also returns this chunk's summed router
    load-balancing loss (the pipeline's stage_aux channel). The RoPE
    table is built once at block_size; the actual sequence may be
    shorter (T is static at trace time, so this slice is free)."""
    T = x.shape[1]
    cos, sin = cos[:T], sin[:T]

    def body(carry, lp):
        h, aux_sum = carry
        h2, aux = llama._block(
            h, lp, cfg=cfg, attn_fn=attn_fn, cos=cos, sin=sin
        )
        return (h2, aux_sum + aux), None

    (out, aux), _ = jax.lax.scan(
        body, (x, jnp.zeros((), jnp.float32)), chunk
    )
    return out, aux


def _head_loss(y, tgt, head, cfg: llama.LlamaConfig):
    h = llama._rms_norm(y, head["rmsf"], cfg.rms_eps)
    logits = jnp.einsum(
        "...te,ve->...tv", h, head["lm_head"],
        preferred_element_type=jnp.float32,
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)
    return -jnp.mean(ll)


def split_params(params, n_stages: int, v_chunks: int):
    staged = split_stages_interleaved(
        params["blocks"], n_stages, v_chunks
    )
    embed = {"wte": params["wte"]}
    head = {"rmsf": params["rmsf"], "lm_head": params["lm_head"]}
    return staged, embed, head


def merge_grads(staged_grads, embed_grads, head_grads):
    def unstage(g):
        q = jnp.swapaxes(g, 0, 1)
        return q.reshape((-1,) + g.shape[3:])

    return {
        "blocks": jax.tree.map(unstage, staged_grads),
        "wte": embed_grads["wte"],
        "rmsf": head_grads["rmsf"],
        "lm_head": head_grads["lm_head"],
    }


def make_llama_pipeline_step(
    mesh: Mesh,
    cfg: llama.LlamaConfig,
    optimizer: optax.GradientTransformation,
    n_micro: Optional[int] = None,
    v_chunks: int = 1,
    attn_fn=None,
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
):
    """Full-Llama 1F1B training step; params/opt_state stay in
    the native checkpoint layout. MoE configs ride the schedule's
    stage_aux channel: each chunk also emits its summed router
    load-balancing loss, added (microbatch-meaned) to the objective
    and differentiated through — the pipelined twin of
    backbone_with_aux's per-batch aux sum."""
    n_stages = mesh.shape.get("pipe", 1)
    if cfg.n_layer % (n_stages * v_chunks):
        raise ValueError(
            f"n_layer={cfg.n_layer} must divide into "
            f"pipe({n_stages}) x v_chunks({v_chunks}) stages"
        )
    if attn_fn is None:
        attn_fn = functools.partial(
            gpt._default_attention,
            causal=getattr(cfg, "causal", True),
            window=getattr(cfg, "sliding_window", None),
        )
    cos, sin = llama.rope_table(cfg, cfg.block_size)
    moe = cfg.n_experts > 0
    stage = _stage_fn_aux if moe else _stage_fn

    def embed(e, toks):
        return e["wte"][toks].astype(cfg.dtype)

    return make_pipelined_lm_step(
        mesh,
        optimizer,
        split_params=lambda p: split_params(p, n_stages, v_chunks),
        merge_grads=merge_grads,
        embed_fn=embed,
        stage_fn=functools.partial(
            stage, cfg=cfg, attn_fn=attn_fn, cos=cos, sin=sin
        ),
        head_loss_fn=functools.partial(_head_loss, cfg=cfg),
        n_stages=n_stages,
        n_micro=n_micro,
        v_chunks=v_chunks,
        batch_axes=batch_axes,
        stage_aux=moe,
    )


def LlamaPipelineBuilder(
    cfg: llama.LlamaConfig, v_chunks: int = 1
) -> LmPipelineBuilder:
    """auto_accelerate pipeline hook for the Llama family (generic
    machinery in pipeline_lm.LmPipelineBuilder; GPT twin in
    gpt_pipeline)."""
    return LmPipelineBuilder(
        init_params=functools.partial(llama.init_params, cfg=cfg),
        make_step=lambda mesh, opt, n_micro, v: (
            make_llama_pipeline_step(
                mesh, cfg, opt, n_micro=n_micro, v_chunks=v
            )
        ),
        v_chunks=v_chunks,
    )
