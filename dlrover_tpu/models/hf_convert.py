"""HuggingFace checkpoint -> native pytree converters.

The reference consumes HF models directly (its Llama example builds
``AutoModelForCausalLM`` and wraps layers,
/root/reference/atorch/examples/llama2/fsdp_llama2.py:8-14); this
framework uses native JAX modules instead, so migration needs a weight
bridge. ``llama_params_from_hf`` maps an HF Llama ``state_dict`` (or
model) onto models/llama.py's stacked-layer pytree:

* torch ``Linear.weight`` is [out, in] — transposed to [in, out];
* per-layer tensors are stacked on a leading ``layers`` dim for the
  ``lax.scan`` backbone;
* rotary convention matches (HF ``rotate_half`` == our split-halves
  apply_rope), so no permutation of q/k rows is needed.

Torch stays host-side only: tensors convert through numpy and the
result is a plain numpy pytree the caller shards via
``jax.device_put`` / ``make_sharded_init``-style shardings.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

from dlrover_tpu.models.llama import LlamaConfig


def _np(t) -> np.ndarray:
    """torch tensor | np array -> float32 numpy on host."""
    if hasattr(t, "detach"):
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def llama_config_from_hf(hf_config) -> LlamaConfig:
    """Map an HF Llama (or Mistral/Mixtral) config to ours — Mixtral
    configs carry num_local_experts/num_experts_per_tok, which switch
    the native family into MoE mode; a Mistral ``sliding_window``
    carries through to the banded flash kernel."""
    return LlamaConfig(
        sliding_window=getattr(hf_config, "sliding_window", None),
        vocab_size=hf_config.vocab_size,
        block_size=hf_config.max_position_embeddings,
        n_layer=hf_config.num_hidden_layers,
        n_head=hf_config.num_attention_heads,
        n_kv_head=getattr(
            hf_config, "num_key_value_heads",
            hf_config.num_attention_heads,
        ),
        n_embd=hf_config.hidden_size,
        intermediate=hf_config.intermediate_size,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rms_eps=hf_config.rms_norm_eps,
        n_experts=getattr(hf_config, "num_local_experts", 0),
        moe_top_k=getattr(hf_config, "num_experts_per_tok", 2),
        # No-drop capacity (capacity == all tokens): HF Mixtral has no
        # capacity concept, so a converted model must never drop or it
        # diverges from the source. Lower it explicitly to fine-tune
        # with GShard-style dropping.
        moe_capacity_factor=(
            float(getattr(hf_config, "num_local_experts", 0))
            / max(getattr(hf_config, "num_experts_per_tok", 2), 1)
            if getattr(hf_config, "num_local_experts", 0)
            else 1.25
        ),
    )


def llama_params_from_hf(
    state_dict: Mapping[str, Any],
    cfg: LlamaConfig,
    dtype: Any = np.float32,
) -> Dict[str, Any]:
    """HF Llama(ForCausalLM) state_dict -> our param pytree.

    Accepts either the ``model.``-prefixed CausalLM dict or a bare
    LlamaModel dict. Tied-embedding checkpoints (no lm_head.weight)
    fall back to wte for the head, matching HF's tie_word_embeddings
    at conversion time — but the returned pytree carries ``wte`` and
    ``lm_head`` as two *independent* leaves, so the tie does not
    survive training: gradients flow to each copy separately and they
    diverge from the first optimizer step. That is fine for inference
    and full-finetune-with-untied-head, but differs from HF's tied
    fine-tune semantics; callers who need the tie preserved should
    check ``"lm_head.weight" not in state_dict`` and alias the leaves
    in their own step function (e.g. overwrite lm_head from wte after
    each update, or compute logits against wte directly).
    """
    if hasattr(state_dict, "state_dict"):
        raise TypeError("pass model.state_dict(), not the model")
    sd = dict(state_dict)
    used = set()

    def get(name):
        for key in (name, f"model.{name}"):
            if key in sd:
                used.add(key)
                return _np(sd[key])
        raise KeyError(
            f"HF state_dict is missing {name!r} "
            f"(have e.g. {list(sd)[:4]})"
        )

    L = cfg.n_layer

    def stack(fmt, transpose=True):
        mats = []
        for i in range(L):
            w = get(fmt.format(i=i))
            mats.append(w.T if transpose else w)
        return np.stack(mats).astype(dtype)

    wte = get("embed_tokens.weight").astype(dtype)
    try:
        head = _np(sd["lm_head.weight"]).astype(dtype)
    except KeyError:
        head = wte  # tie_word_embeddings
    blocks = {
        "rms1": stack(
            "layers.{i}.input_layernorm.weight", transpose=False
        ).astype(np.float32),
        "wq": stack("layers.{i}.self_attn.q_proj.weight"),
        "wk": stack("layers.{i}.self_attn.k_proj.weight"),
        "wv": stack("layers.{i}.self_attn.v_proj.weight"),
        "wo": stack("layers.{i}.self_attn.o_proj.weight"),
        "rms2": stack(
            "layers.{i}.post_attention_layernorm.weight",
            transpose=False,
        ).astype(np.float32),
    }
    if cfg.n_experts > 0:
        # Mixtral block_sparse_moe: gate -> router, experts j:
        # w1 = SwiGLU gate, w3 = up, w2 = down.
        def stack_experts(fmt):
            mats = []
            for i in range(L):
                mats.append(
                    np.stack(
                        [
                            get(fmt.format(i=i, j=j)).T
                            for j in range(cfg.n_experts)
                        ]
                    )
                )
            return np.stack(mats).astype(dtype)  # [L, E, in, out]

        blocks["moe"] = {
            "router": stack(
                "layers.{i}.block_sparse_moe.gate.weight"
            ).astype(np.float32),
            "wg": stack_experts(
                "layers.{i}.block_sparse_moe.experts.{j}.w1.weight"
            ),
            "wi": stack_experts(
                "layers.{i}.block_sparse_moe.experts.{j}.w3.weight"
            ),
            "wo": stack_experts(
                "layers.{i}.block_sparse_moe.experts.{j}.w2.weight"
            ),
        }
    else:
        blocks.update(
            w_gate=stack("layers.{i}.mlp.gate_proj.weight"),
            w_up=stack("layers.{i}.mlp.up_proj.weight"),
            w_down=stack("layers.{i}.mlp.down_proj.weight"),
        )
    params = {
        "wte": wte,
        "blocks": blocks,
        "rmsf": get("norm.weight").astype(np.float32),
        "lm_head": head,
    }
    used.add("lm_head.weight")
    # Models with weights we don't map (e.g. attention_bias=True
    # checkpoints carry q_proj.bias) would silently convert into a
    # different function — refuse instead of degrading.
    leftover = {
        k for k in sd
        if k not in used
        and not k.endswith("rotary_emb.inv_freq")  # recomputed
    }
    if leftover:
        raise ValueError(
            "HF state_dict contains tensors this converter does not "
            f"map (unsupported architecture variant?): "
            f"{sorted(leftover)[:6]}"
        )
    return params
