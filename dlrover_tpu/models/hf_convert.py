"""HuggingFace checkpoint -> native pytree converters.

The reference consumes HF models directly (its Llama example builds
``AutoModelForCausalLM`` and wraps layers,
/root/reference/atorch/examples/llama2/fsdp_llama2.py:8-14); this
framework uses native JAX modules instead, so migration needs a weight
bridge. ``llama_params_from_hf`` maps an HF Llama ``state_dict`` (or
model) onto models/llama.py's stacked-layer pytree:

* torch ``Linear.weight`` is [out, in] — transposed to [in, out];
* per-layer tensors are stacked on a leading ``layers`` dim for the
  ``lax.scan`` backbone;
* rotary convention matches (HF ``rotate_half`` == our split-halves
  apply_rope), so no permutation of q/k rows is needed.

Torch stays host-side only: tensors convert through numpy and the
result is a plain numpy pytree the caller shards via
``jax.device_put`` / ``make_sharded_init``-style shardings.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import numpy as np

from dlrover_tpu.models.llama import LlamaConfig


def _np(t) -> np.ndarray:
    """torch tensor | np array -> float32 numpy on host."""
    if hasattr(t, "detach"):
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def llama_config_from_hf(hf_config) -> LlamaConfig:
    """Map an HF Llama (or Mistral/Mixtral) config to ours — Mixtral
    configs carry num_local_experts/num_experts_per_tok, which switch
    the native family into MoE mode; a Mistral ``sliding_window``
    carries through to the banded flash kernel."""
    return LlamaConfig(
        sliding_window=getattr(hf_config, "sliding_window", None),
        vocab_size=hf_config.vocab_size,
        block_size=hf_config.max_position_embeddings,
        n_layer=hf_config.num_hidden_layers,
        n_head=hf_config.num_attention_heads,
        n_kv_head=getattr(
            hf_config, "num_key_value_heads",
            hf_config.num_attention_heads,
        ),
        n_embd=hf_config.hidden_size,
        intermediate=hf_config.intermediate_size,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rms_eps=hf_config.rms_norm_eps,
        n_experts=getattr(hf_config, "num_local_experts", 0),
        moe_top_k=getattr(hf_config, "num_experts_per_tok", 2),
        # No-drop capacity (capacity == all tokens): HF Mixtral has no
        # capacity concept, so a converted model must never drop or it
        # diverges from the source. Lower it explicitly to fine-tune
        # with GShard-style dropping.
        moe_capacity_factor=(
            float(getattr(hf_config, "num_local_experts", 0))
            / max(getattr(hf_config, "num_experts_per_tok", 2), 1)
            if getattr(hf_config, "num_local_experts", 0)
            else 1.25
        ),
    )


def llama_params_from_hf(
    state_dict: Mapping[str, Any],
    cfg: LlamaConfig,
    dtype: Any = np.float32,
) -> Dict[str, Any]:
    """HF Llama(ForCausalLM) state_dict -> our param pytree.

    Accepts either the ``model.``-prefixed CausalLM dict or a bare
    LlamaModel dict. Tied-embedding checkpoints (no lm_head.weight)
    fall back to wte for the head, matching HF's tie_word_embeddings
    at conversion time — but the returned pytree carries ``wte`` and
    ``lm_head`` as two *independent* leaves, so the tie does not
    survive training: gradients flow to each copy separately and they
    diverge from the first optimizer step. That is fine for inference
    and full-finetune-with-untied-head, but differs from HF's tied
    fine-tune semantics; callers who need the tie preserved should
    check ``"lm_head.weight" not in state_dict`` and alias the leaves
    in their own step function (e.g. overwrite lm_head from wte after
    each update, or compute logits against wte directly).
    """
    if hasattr(state_dict, "state_dict"):
        raise TypeError("pass model.state_dict(), not the model")
    sd = dict(state_dict)
    used = set()

    def get(name):
        for key in (name, f"model.{name}"):
            if key in sd:
                used.add(key)
                return _np(sd[key])
        raise KeyError(
            f"HF state_dict is missing {name!r} "
            f"(have e.g. {list(sd)[:4]})"
        )

    L = cfg.n_layer

    def stack(fmt, transpose=True):
        mats = []
        for i in range(L):
            w = get(fmt.format(i=i))
            mats.append(w.T if transpose else w)
        return np.stack(mats).astype(dtype)

    wte = get("embed_tokens.weight").astype(dtype)
    try:
        head = _np(sd["lm_head.weight"]).astype(dtype)
    except KeyError:
        head = wte  # tie_word_embeddings
    blocks = {
        "rms1": stack(
            "layers.{i}.input_layernorm.weight", transpose=False
        ).astype(np.float32),
        "wq": stack("layers.{i}.self_attn.q_proj.weight"),
        "wk": stack("layers.{i}.self_attn.k_proj.weight"),
        "wv": stack("layers.{i}.self_attn.v_proj.weight"),
        "wo": stack("layers.{i}.self_attn.o_proj.weight"),
        "rms2": stack(
            "layers.{i}.post_attention_layernorm.weight",
            transpose=False,
        ).astype(np.float32),
    }
    if cfg.n_experts > 0:
        # Mixtral block_sparse_moe: gate -> router, experts j:
        # w1 = SwiGLU gate, w3 = up, w2 = down.
        def stack_experts(fmt):
            mats = []
            for i in range(L):
                mats.append(
                    np.stack(
                        [
                            get(fmt.format(i=i, j=j)).T
                            for j in range(cfg.n_experts)
                        ]
                    )
                )
            return np.stack(mats).astype(dtype)  # [L, E, in, out]

        blocks["moe"] = {
            "router": stack(
                "layers.{i}.block_sparse_moe.gate.weight"
            ).astype(np.float32),
            "wg": stack_experts(
                "layers.{i}.block_sparse_moe.experts.{j}.w1.weight"
            ),
            "wi": stack_experts(
                "layers.{i}.block_sparse_moe.experts.{j}.w3.weight"
            ),
            "wo": stack_experts(
                "layers.{i}.block_sparse_moe.experts.{j}.w2.weight"
            ),
        }
    else:
        blocks.update(
            w_gate=stack("layers.{i}.mlp.gate_proj.weight"),
            w_up=stack("layers.{i}.mlp.up_proj.weight"),
            w_down=stack("layers.{i}.mlp.down_proj.weight"),
        )
    params = {
        "wte": wte,
        "blocks": blocks,
        "rmsf": get("norm.weight").astype(np.float32),
        "lm_head": head,
    }
    used.add("lm_head.weight")
    # Models with weights we don't map (e.g. attention_bias=True
    # checkpoints carry q_proj.bias) would silently convert into a
    # different function — refuse instead of degrading.
    leftover = {
        k for k in sd
        if k not in used
        and not k.endswith("rotary_emb.inv_freq")  # recomputed
    }
    if leftover:
        raise ValueError(
            "HF state_dict contains tensors this converter does not "
            f"map (unsupported architecture variant?): "
            f"{sorted(leftover)[:6]}"
        )
    return params


# ---------------------------------------------------------------------------
# ChatGLM2/3 (GLM family, models/glm.py)
# ---------------------------------------------------------------------------


def _interleaved_to_halves_perm(rot: int) -> np.ndarray:
    """Index permutation mapping ChatGLM's interleaved rotary layout
    (pairs (x_{2j}, x_{2j+1}) rotated together) onto our split-halves
    apply_rope layout (x_j with x_{j+rot/2}). perm[j] = source index
    in the interleaved layout for target position j."""
    half = rot // 2
    perm = np.empty(rot, np.int64)
    perm[:half] = 2 * np.arange(half)
    perm[half:] = 2 * np.arange(half) + 1
    return perm


def glm_config_from_hf(hf_config) -> LlamaConfig:
    """Map a ChatGLM2/3 HF config onto the native GLM shape
    (models/glm.py: Llama backbone + qkv bias + half-dim rotary).

    Long-context ChatGLM checkpoints (e.g. the 32k variants) scale the
    rotary base by ``rope_ratio`` — HF's modeling_chatglm computes
    ``base = 10000 * rope_ratio`` — so it is read into rope_theta here
    rather than silently defaulted.  ``original_rope`` flips the
    interleaved rotary convention; the permutation mapping assumes the
    standard (True) layout, so a False value is rejected rather than
    converted wrong."""
    if not getattr(hf_config, "original_rope", True):
        raise ValueError(
            "ChatGLM config has original_rope=False (non-standard "
            "rotary layout); the interleaved->split-halves rotary "
            "permutation in glm_params_from_hf assumes the standard "
            "layout and would convert this checkpoint incorrectly"
        )
    return LlamaConfig(
        vocab_size=hf_config.padded_vocab_size,
        block_size=hf_config.seq_length,
        n_layer=hf_config.num_layers,
        n_head=hf_config.num_attention_heads,
        n_kv_head=(
            hf_config.multi_query_group_num
            if getattr(hf_config, "multi_query_attention", False)
            else hf_config.num_attention_heads
        ),
        n_embd=hf_config.hidden_size,
        intermediate=hf_config.ffn_hidden_size,
        rms_eps=hf_config.layernorm_epsilon,
        qkv_bias=getattr(hf_config, "add_qkv_bias", True),
        rotary_pct=0.5,
        rope_theta=10000.0 * getattr(hf_config, "rope_ratio", 1.0),
        # Same generation semantics as the native presets: prompts
        # prefill bidirectionally (models/glm.py).
        prefix_lm=True,
    )


def glm_params_from_hf(
    state_dict, cfg: LlamaConfig, dtype: Any = np.float32
) -> Dict[str, Any]:
    """ChatGLM2/3 state_dict -> our param pytree.

    Three layout conversions on top of the Llama mapping:

    * the fused ``query_key_value`` weight/bias splits into wq/wk/wv
      rows ([E + 2*kv, E] row-major: q then k then v);
    * the fused SwiGLU ``dense_h_to_4h`` ([2I, E], silu(first half) *
      second half) splits into w_gate/w_up;
    * ChatGLM rotates interleaved pairs over the first half of each
      head; our apply_rope rotates split halves — the q/k columns of
      each head's rotary slice are permuted so the two conventions
      compute the same function (validated by
      tests/test_glm.py::test_rotary_permutation_equivalence).
    """
    if hasattr(state_dict, "state_dict"):
        raise TypeError("pass model.state_dict(), not the model")
    sd = dict(state_dict)
    used = set()

    def get(name):
        for key in (name, f"transformer.{name}"):
            if key in sd:
                used.add(key)
                return _np(sd[key])
        raise KeyError(f"ChatGLM state_dict is missing {name!r}")

    L, E, D = cfg.n_layer, cfg.n_embd, cfg.head_dim
    kv = cfg.n_kv_head * D
    rot = int(D * cfg.rotary_pct)
    perm = _interleaved_to_halves_perm(rot)

    def permute_heads(w, n_heads):
        """Permute each head's rotary slice of the OUTPUT dim.
        w: [..., n_heads*D] column-major heads."""
        shaped = w.reshape(w.shape[:-1] + (n_heads, D))
        fixed = np.concatenate(
            [shaped[..., perm], shaped[..., rot:]], axis=-1
        )
        return fixed.reshape(w.shape)

    wq_l, wk_l, wv_l, bq_l, bk_l, bv_l = [], [], [], [], [], []
    gate_l, up_l, down_l, wo_l, r1_l, r2_l = [], [], [], [], [], []
    for i in range(L):
        pre = f"encoder.layers.{i}"
        qkv_w = get(f"{pre}.self_attention.query_key_value.weight")
        wq_l.append(permute_heads(qkv_w[:E].T, cfg.n_head))
        wk_l.append(permute_heads(qkv_w[E:E + kv].T, cfg.n_kv_head))
        wv_l.append(qkv_w[E + kv:].T)
        if cfg.qkv_bias:
            qkv_b = get(f"{pre}.self_attention.query_key_value.bias")
            bq_l.append(permute_heads(qkv_b[:E], cfg.n_head))
            bk_l.append(
                permute_heads(qkv_b[E:E + kv], cfg.n_kv_head)
            )
            bv_l.append(qkv_b[E + kv:])
        wo_l.append(get(f"{pre}.self_attention.dense.weight").T)
        h4 = get(f"{pre}.mlp.dense_h_to_4h.weight")
        gate_l.append(h4[: cfg.intermediate].T)
        up_l.append(h4[cfg.intermediate:].T)
        down_l.append(get(f"{pre}.mlp.dense_4h_to_h.weight").T)
        r1_l.append(get(f"{pre}.input_layernorm.weight"))
        r2_l.append(get(f"{pre}.post_attention_layernorm.weight"))

    blocks = {
        "rms1": np.stack(r1_l).astype(np.float32),
        "wq": np.stack(wq_l).astype(dtype),
        "wk": np.stack(wk_l).astype(dtype),
        "wv": np.stack(wv_l).astype(dtype),
        "wo": np.stack(wo_l).astype(dtype),
        "rms2": np.stack(r2_l).astype(np.float32),
        "w_gate": np.stack(gate_l).astype(dtype),
        "w_up": np.stack(up_l).astype(dtype),
        "w_down": np.stack(down_l).astype(dtype),
    }
    if cfg.qkv_bias:
        blocks.update(
            bq=np.stack(bq_l).astype(dtype),
            bk=np.stack(bk_l).astype(dtype),
            bv=np.stack(bv_l).astype(dtype),
        )
    params = {
        "wte": get("embedding.word_embeddings.weight").astype(dtype),
        "blocks": blocks,
        "rmsf": get("encoder.final_layernorm.weight").astype(
            np.float32
        ),
        "lm_head": get("output_layer.weight").astype(dtype),
    }
    leftover = {
        k for k in sd
        if k not in used and "rotary_pos_emb" not in k
    }
    if leftover:
        raise ValueError(
            "ChatGLM state_dict contains tensors this converter "
            f"does not map: {sorted(leftover)[:6]}"
        )
    return params
