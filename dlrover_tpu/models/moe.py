"""Mixture-of-Experts layer with expert parallelism, GSPMD-native.

Capability parity with the reference's MoE stack
(atorch/modules/moe/moe_layer.py:611LoC — MOELayer + expert process
groups, topk_gating.py, switch_gating.py, all-to-all dispatch) built
the TPU way: no process groups, no explicit all-to-all calls. The
GShard dispatch/combine formulation — one-hot dispatch tensors and
einsums — with expert weights sharded over the ``expert`` mesh axis
and tokens over ``data``/``fsdp``; GSPMD inserts the all-to-alls over
ICI where the reference hand-writes NCCL a2a.

Gating:
* ``top_k_gating`` — top-k router (k=2 default; GShard/Mixtral style)
  with capacity dropping, load-balance auxiliary loss and router
  z-loss.
* ``switch_gating`` — top-1 Switch-Transformer routing (the
  reference's switch_gating.py) = top_k_gating(k=1).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_embd: int
    n_experts: int = 8
    expert_hidden: int = 0  # 0 -> 4 * n_embd
    top_k: int = 2
    capacity_factor: float = 1.25
    # loss weights (GShard defaults)
    aux_loss_weight: float = 1e-2
    z_loss_weight: float = 1e-3
    dtype: Any = jnp.bfloat16
    # gated=True: experts are SwiGLU (w_gate/w_in/w_out) — the
    # Mixtral expert shape — instead of the 2-matmul GELU FFN.
    gated: bool = False
    # renorm_top_k=True: combine weights are renormalized over the
    # token's kept choices (Mixtral's softmax-over-top-k) instead of
    # the raw full-softmax probabilities (GShard).
    renorm_top_k: bool = False

    @property
    def hidden(self) -> int:
        return self.expert_hidden or 4 * self.n_embd


def init_moe_params(key: jax.Array, cfg: MoEConfig) -> Dict[str, Any]:
    k_r, k_i, k_o, k_g = jax.random.split(key, 4)
    E, D, H = cfg.n_experts, cfg.n_embd, cfg.hidden
    std = 0.02

    def norm(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(
            cfg.dtype
        )

    params = {
        # Router stays float32: tiny, and routing decisions are
        # precision-sensitive.
        "router": jax.random.normal(k_r, (D, E), jnp.float32) * std,
        "wi": norm(k_i, (E, D, H)),
        "wo": norm(k_o, (E, H, D)),
    }
    if cfg.gated:
        params["wg"] = norm(k_g, (E, D, H))
    return params


def moe_logical_axes(
    gated: bool = False,
) -> Dict[str, Tuple[Optional[str], ...]]:
    axes = {
        "router": (None, None),
        "wi": ("expert", "embed", "mlp"),
        "wo": ("expert", "mlp", "embed"),
    }
    if gated:
        axes["wg"] = ("expert", "embed", "mlp")
    return axes


def _gating(
    logits: jax.Array,  # [n, E] float32
    top_k: int,
    capacity: int,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Returns (dispatch [n,E,C] bool, combine [n,E,C] f32, metrics).

    GShard-style: for each of the k choices in order, tokens claim
    expert capacity slots by cumulative position; overflowing tokens
    are dropped for that choice (residual path carries them).
    """
    n, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    dispatch = jnp.zeros((n, E, capacity), jnp.bool_)
    combine = jnp.zeros((n, E, capacity), jnp.float32)
    # slots already taken per expert by earlier choices
    fill = jnp.zeros((E,), jnp.int32)
    masked_logits = logits
    # fraction of tokens routed per expert (for aux loss): first choice
    top1_mask = None

    for choice in range(top_k):
        idx = jnp.argmax(masked_logits, axis=-1)  # [n]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)  # [n, E]
        if top1_mask is None:
            top1_mask = onehot
        # position of each token within its chosen expert's queue
        pos_in_expert = (
            jnp.cumsum(onehot, axis=0) - onehot
        ) * onehot  # [n, E]
        pos = jnp.sum(pos_in_expert, axis=-1) + fill[idx]  # [n]
        keep = pos < capacity
        gate = jnp.sum(probs * onehot, axis=-1) * keep  # [n]
        slot = jax.nn.one_hot(
            jnp.where(keep, pos, capacity), capacity + 1, dtype=jnp.float32
        )[:, :capacity]  # [n, C] (dropped tokens -> all-zero row)
        d = onehot[:, :, None].astype(jnp.float32) * slot[:, None, :]
        dispatch = jnp.logical_or(dispatch, d > 0)
        combine = combine + gate[:, None, None] * d
        fill = fill + jnp.sum(
            onehot * keep[:, None].astype(jnp.int32), axis=0
        )
        # mask this choice out for the next round
        masked_logits = jnp.where(onehot > 0, -1e30, masked_logits)

    # GShard load-balance loss: E * sum_e mean_prob_e * frac_tokens_e
    frac_tokens = jnp.mean(top1_mask.astype(jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(frac_tokens * mean_prob)
    # router z-loss (stabilizes logits scale)
    z_loss = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    metrics = {
        "aux_loss": aux_loss,
        "z_loss": z_loss,
        "dropped_fraction": 1.0
        - jnp.sum(combine > 0) / (n * top_k),
    }
    return dispatch, combine, metrics


def top_k_gating(logits, top_k, capacity):
    return _gating(logits, top_k, capacity)


def switch_gating(logits, capacity):
    """Top-1 Switch-Transformer routing (ref switch_gating.py)."""
    return _gating(logits, 1, capacity)


def moe_mlp(
    params: Dict[str, Any],
    x: jax.Array,  # [B, T, D]
    cfg: MoEConfig,
) -> Tuple[jax.Array, jax.Array]:
    """MoE feed-forward. Returns (y [B,T,D], aux_loss scalar).

    Drop-in for the dense MLP of a transformer block: add aux_loss
    (already weighted) to the training loss.
    """
    B, T, D = x.shape
    n = B * T
    E = cfg.n_experts
    capacity = int(
        np.ceil(cfg.capacity_factor * cfg.top_k * n / E)
    )
    flat = x.reshape(n, D)
    logits = flat.astype(jnp.float32) @ params["router"]  # [n, E]
    dispatch, combine, metrics = _gating(logits, cfg.top_k, capacity)
    if cfg.renorm_top_k:
        # Mixtral semantics: weights renormalized over the token's
        # kept choices (== softmax over the top-k logits when no
        # capacity drop occurs).
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)

    # dispatch tokens to expert buffers: [E, C, D]
    buf = jnp.einsum(
        "nec,nd->ecd",
        dispatch.astype(cfg.dtype),
        flat.astype(cfg.dtype),
    )
    # expert FFN, batched over the (sharded) expert dim
    h = jnp.einsum(
        "ecd,edh->ech", buf, params["wi"],
        preferred_element_type=jnp.float32,
    )
    if cfg.gated:
        g = jnp.einsum(
            "ecd,edh->ech", buf, params["wg"],
            preferred_element_type=jnp.float32,
        )
        h = (jax.nn.silu(g) * h).astype(cfg.dtype)
    else:
        h = jax.nn.gelu(h).astype(cfg.dtype)
    out = jnp.einsum(
        "ech,ehd->ecd", h, params["wo"],
        preferred_element_type=jnp.float32,
    )
    # combine back, weighted by gates
    y = jnp.einsum(
        "nec,ecd->nd", combine, out.astype(jnp.float32)
    )
    aux = (
        cfg.aux_loss_weight * metrics["aux_loss"]
        + cfg.z_loss_weight * metrics["z_loss"]
    )
    return y.reshape(B, T, D).astype(x.dtype), aux
