"""GLM family (ChatGLM2/3) on the Llama backbone.

The last of the reference's four module-replacement families
(BERT/GPT2/LLaMA/GLM — /root/reference/atorch/atorch/auto/opt_lib/
module_replace_optimization.py; parallel GLM blocks
/root/reference/atorch/atorch/modules/distributed_modules/
transformer.py). Architecturally ChatGLM2/3 is the Llama backbone
with three deltas, all expressed as config switches on
models/llama.py rather than a parallel module forest:

* bias on the q/k/v projections (``qkv_bias=True``);
* rotary embedding over half the head dims (``rotary_pct=0.5``),
  the rest passing through unrotated;
* grouped-query attention with 2 kv groups (``n_kv_head=2``).

The GLM-distinctive *training* surface is blank-infilling: a prefix
of context tokens attends bidirectionally, the generation suffix
causally (ops/prefix_lm.py — a bidirectional prefix-square flash
call plus a rectangular causal call of the suffix queries at their
global offset, exact cost). :func:`prefix_attention_for` binds a static prefix length
into an attention fn the backbone scan consumes unchanged, and
:func:`prefix_lm_loss_fn` scores only suffix positions — the
blank-infilling objective.

Everything the strategy engine knows about Llama (sharding axes,
module profiles, TP plans, pipeline splits, remat/offload policies)
transfers: the parameters and jaxpr shapes are the backbone's own
(trajectory parity through the 1F1B pipeline:
tests/test_glm.py::test_glm_pipelines_like_llama).

Sequence sharding: single-shard prefix-LM uses the exact-cost
composition (ops/prefix_lm.py); under ``seq`` sharding the two-pass
prefix ring applies (parallel/ring_attention.py
ring_prefix_lm_attention via make_sharded_prefix_attention — causal
ring + prefix-masked bidirectional ring + positional select, ~2x a
causal step). Causal-mode GLM (the common ChatGLM2/3 SFT setup)
shards everywhere Llama does at no extra cost.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from dlrover_tpu.models import llama

Params = llama.Params


def chatglm2_6b(**overrides) -> llama.LlamaConfig:
    """ChatGLM2-6B shape: L28 H32 E4096, 2 kv groups, ffn 13696,
    65024-token vocab, half-dim RoPE, qkv bias."""
    cfg = llama.LlamaConfig(
        vocab_size=65024,
        block_size=32768,
        n_layer=28,
        n_head=32,
        n_kv_head=2,
        n_embd=4096,
        intermediate=13696,
        rope_theta=10000.0,
        qkv_bias=True,
        rotary_pct=0.5,
        prefix_lm=True,
    )
    return dataclasses.replace(cfg, **overrides)


def chatglm3_6b(**overrides) -> llama.LlamaConfig:
    """ChatGLM3-6B: same architecture as ChatGLM2, 8k context."""
    return chatglm2_6b(block_size=8192, **overrides)


def tiny(**overrides) -> llama.LlamaConfig:
    """Test-size GLM: exercises qkv bias + partial rotary + GQA."""
    cfg = llama.LlamaConfig(
        vocab_size=256,
        block_size=64,
        n_layer=2,
        n_head=4,
        n_kv_head=2,
        n_embd=64,
        intermediate=128,
        qkv_bias=True,
        rotary_pct=0.5,
        prefix_lm=True,
        dtype=jnp.float32,
        remat=False,
    )
    return dataclasses.replace(cfg, **overrides)


# Parameter init/axes/forward are the backbone's own.
init_params = llama.init_params
param_logical_axes = llama.param_logical_axes
forward = llama.forward
loss_fn = llama.loss_fn


def prefix_attention_for(
    cfg: llama.LlamaConfig, prefix_len: int, mesh=None
) -> Callable:
    """Attention fn with GLM's prefix-LM mask bound in.

    ``prefix_len`` is static — the backbone jit compiles one program
    per distinct length, so batch construction should bucket prompts
    to a few lengths (the standard XLA static-shape contract).
    Flash-kernel composition when the config would use flash;
    the dense masked reference otherwise.

    Pass ``mesh`` to sequence-shard: a mesh with seq > 1 routes to
    the fused prefix ring (parallel/ring_attention.py
    make_sharded_prefix_attention); the default is single-shard.
    """
    from dlrover_tpu.ops.prefix_lm import (
        prefix_lm_attention,
        prefix_lm_attention_reference,
    )

    if mesh is not None and mesh.shape.get("seq", 1) > 1:
        from dlrover_tpu.parallel.ring_attention import (
            make_sharded_prefix_attention,
        )

        return make_sharded_prefix_attention(
            mesh, prefix_len, attn_blocks=cfg.attn_blocks
        )

    use_flash = cfg.use_flash_attention
    if use_flash is None:
        # Same auto rule as gpt.default_attention_for: the Pallas
        # composition on TPU from 512 context up; the dense masked
        # reference elsewhere (interpreted Pallas on CPU would be
        # orders of magnitude slower than the XLA softmax).
        use_flash = (
            jax.default_backend() == "tpu" and cfg.block_size >= 512
        )
    if use_flash:
        blocks = cfg.attn_blocks
        return lambda q, k, v: prefix_lm_attention(
            q, k, v, prefix_len, attn_blocks=blocks
        )
    return lambda q, k, v: prefix_lm_attention_reference(
        q, k, v, prefix_len
    )


def prefix_lm_forward(
    params: Params,
    tokens: jax.Array,
    cfg: llama.LlamaConfig,
    prefix_len: int,
) -> jax.Array:
    return llama.forward(
        params, tokens, cfg, prefix_attention_for(cfg, prefix_len)
    )


def prefix_lm_loss_fn(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: llama.LlamaConfig,
    prefix_len: int,
) -> jax.Array:
    """Blank-infilling objective: next-token CE over the positions
    that PREDICT suffix tokens — the band [prefix_len - 1, T - 1).
    Position prefix_len - 1 (the last prefix token) is included
    because its logit head generates the FIRST suffix token at
    sampling time; position T - 1 is excluded because its next-token
    target lies outside the sequence (callers following the
    ``jnp.roll(tokens, -1)`` convention would otherwise supervise
    wrap-around garbage)."""
    t_static = tokens.shape[1]
    if max(prefix_len - 1, 0) >= t_static - 1:
        raise ValueError(
            f"prefix_len={prefix_len} leaves no supervised positions in a "
            f"length-{t_static} sequence (band [{max(prefix_len - 1, 0)}, "
            f"{t_static - 1}) is empty) — a mis-bucketed batch would train "
            "on nothing"
        )
    x, aux = llama.backbone_with_aux(
        params, tokens, cfg, prefix_attention_for(cfg, prefix_len)
    )
    logits = llama.head_logits(params, x)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    t = tokens.shape[1]
    pos = jnp.arange(t)
    band = (
        (pos >= max(prefix_len - 1, 0)) & (pos < t - 1)
    ).astype(ll.dtype)
    denom = jnp.maximum(band.sum(), 1.0)
    return -(ll * band[None, :]).sum() / (
        denom * tokens.shape[0]
    ) + aux
