"""Autoregressive decoding with a KV cache (GPT + Llama).

Counterpart of the reference's sampling paths — nanoGPT's
``model.generate`` loop in the example the framework demos train
(/root/reference/examples/pytorch/nanogpt/train.py builds the same
GPT this repo's models/gpt.py implements) and the HF ``generate`` its
Llama examples inherit — built the XLA way:

* static shapes end to end: the cache is a preallocated
  [layers, batch, max_len, heads, head_dim] pytree, positions write
  via ``lax.dynamic_update_slice``; one compile regardless of prompt
  or output length;
* the whole decode loop is a single ``lax.scan`` (no per-token Python
  dispatch), layers run under the same stacked-params scan as
  training;
* sampling: greedy, temperature, and top-k via ``jax.random``.

The per-token block math intentionally reuses each model's weights
layout but re-derives the single-position forward (rope at one
position, attention against the cache) — training forwards stay
scan-over-sequence and never pay cache plumbing.
"""

from __future__ import annotations


import functools
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.models import gpt as gpt_mod
from dlrover_tpu.models import llama as llama_mod


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, T_max, H_kv, D]
    v: jax.Array


def _cache_for(cfg, batch: int, max_len: int, n_kv: int) -> KVCache:
    shape = (cfg.n_layer, batch, max_len, n_kv, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype), v=jnp.zeros(shape, cfg.dtype)
    )


def _cached_attention(q, k_cache, v_cache, pos, window=None):
    """q [B,1,H,D] against cache [B,T,H_kv,D]; positions > pos
    masked. H may be a q_per_kv multiple of H_kv (grouped-query):
    query heads fold into a group dim and attend the UN-expanded
    cache — no repeated K/V copies in the decode hot path.
    ``window`` applies the Mistral sliding band — the decode step
    sees keys (pos-window, pos], matching the training mask."""
    b, t, hkv, d = k_cache.shape
    h = q.shape[2]
    g = h // hkv
    qg = q.reshape(b, 1, hkv, g, d)
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k_cache,
        preferred_element_type=jnp.float32,
    ) / np.sqrt(d)
    idx = jnp.arange(t)[None, None, None, None, :]
    mask = idx <= pos
    if window is not None:
        mask &= (pos - idx) < window
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", p, v_cache)
    return o.reshape(b, 1, h, d)


# ---------------------------------------------------------------------------
# Per-model single-token steps
# ---------------------------------------------------------------------------


def gpt_decode_step(params, cache: KVCache, token, pos, cfg):
    """One token through GPT with cache. token [B] int32, pos scalar.
    Returns (logits [B, vocab] f32, new cache)."""
    B = token.shape[0]
    H, D, E = cfg.n_head, cfg.head_dim, cfg.n_embd
    wpe = jax.lax.dynamic_slice_in_dim(params["wpe"], pos, 1, 0)
    x = params["wte"][token][:, None, :] + wpe[None]
    x = x.astype(cfg.dtype)  # [B,1,E]

    def body(x, layer):
        lp, k_c, v_c = layer
        h = gpt_mod._layer_norm(x, lp["ln1_g"], lp["ln1_b"])
        qkv = h @ lp["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, 1, H, D)
        k_c = jax.lax.dynamic_update_slice(
            k_c, k.reshape(B, 1, H, D), (0, pos, 0, 0)
        )
        v_c = jax.lax.dynamic_update_slice(
            v_c, v.reshape(B, 1, H, D), (0, pos, 0, 0)
        )
        att = _cached_attention(q, k_c, v_c, pos).reshape(B, 1, E)
        x = x + att @ lp["wo"]
        h = gpt_mod._layer_norm(x, lp["ln2_g"], lp["ln2_b"])
        h = jax.nn.gelu(h @ lp["wi"] + lp["bi"])
        x = x + h @ lp["wo2"] + lp["bo2"]
        return x, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    x = gpt_mod._layer_norm(x, params["lnf_g"], params["lnf_b"])
    logits = jnp.einsum(
        "boe,ve->bov", x, params["wte"],
        preferred_element_type=jnp.float32,
    )[:, 0]
    return logits, KVCache(k=k_new, v=v_new)


def _llama_mlp(x, h, lp, cfg):
    """Decode-path wrapper over the training block's MLP tail
    (llama.mlp_tail — single definition); the aux loss is irrelevant
    at inference and dropped."""
    y, _ = llama_mod.mlp_tail(x, h, lp, cfg)
    return y


def gpt_prefill(params, cache: KVCache, tokens, cfg):
    """Batched prompt pass: one forward over [B, T0] fills cache
    positions 0..T0 and returns the last position's logits — the
    time-to-first-token path (vs T0 sequential decode steps)."""
    B, T0 = tokens.shape
    H, D, E = cfg.n_head, cfg.head_dim, cfg.n_embd
    x = params["wte"][tokens] + params["wpe"][:T0][None]
    x = x.astype(cfg.dtype)

    def body(x, layer):
        lp, k_c, v_c = layer
        h = gpt_mod._layer_norm(x, lp["ln1_g"], lp["ln1_b"])
        qkv = h @ lp["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T0, H, D)
        k = k.reshape(B, T0, H, D)
        v = v.reshape(B, T0, H, D)
        k_c = jax.lax.dynamic_update_slice(k_c, k, (0, 0, 0, 0))
        v_c = jax.lax.dynamic_update_slice(v_c, v, (0, 0, 0, 0))
        att = gpt_mod._default_attention(
            q, k, v, causal=True
        ).reshape(B, T0, E)
        x = x + att @ lp["wo"]
        h = gpt_mod._layer_norm(x, lp["ln2_g"], lp["ln2_b"])
        h = jax.nn.gelu(h @ lp["wi"] + lp["bi"])
        x = x + h @ lp["wo2"] + lp["bo2"]
        return x, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    x = gpt_mod._layer_norm(
        x[:, -1:], params["lnf_g"], params["lnf_b"]
    )
    logits = jnp.einsum(
        "boe,ve->bov", x, params["wte"],
        preferred_element_type=jnp.float32,
    )[:, 0]
    return logits, KVCache(k=k_new, v=v_new)


def _llama_qkv(h, lp, cfg, B, T):
    """q/k/v projections incl. the optional GLM-style bias, reshaped
    to [B, T, heads, D]."""
    q, k, v = h @ lp["wq"], h @ lp["wk"], h @ lp["wv"]
    if getattr(cfg, "qkv_bias", False):
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    D = cfg.head_dim
    return (
        q.reshape(B, T, cfg.n_head, D),
        k.reshape(B, T, cfg.n_kv_head, D),
        v.reshape(B, T, cfg.n_kv_head, D),
    )


def llama_prefill(params, cache: KVCache, tokens, cfg, rope=None,
                  causal=True):
    """``causal=False`` runs the prompt bidirectionally — GLM
    prefix-LM generation (models/glm.py): the prompt is the prefix,
    so its k/v (at EVERY layer — deeper layers' k/v depend on the
    mask through the hiddens) must be contextualized with the full
    bidirectional mask before causal decode steps extend it."""
    B, T0 = tokens.shape
    H, Hkv, D, E = cfg.n_head, cfg.n_kv_head, cfg.head_dim, cfg.n_embd
    cos_t, sin_t = rope if rope is not None else llama_mod.rope_table(
        cfg, cfg.block_size
    )
    cos, sin = cos_t[:T0], sin_t[:T0]
    x = params["wte"][tokens].astype(cfg.dtype)

    def body(x, layer):
        lp, k_c, v_c = layer
        h = llama_mod._rms_norm(x, lp["rms1"], cfg.rms_eps)
        q, k, v = _llama_qkv(h, lp, cfg, B, T0)
        q = llama_mod.apply_rope(q, cos, sin)
        k = llama_mod.apply_rope(k, cos, sin)
        k_c = jax.lax.dynamic_update_slice(k_c, k, (0, 0, 0, 0))
        v_c = jax.lax.dynamic_update_slice(v_c, v, (0, 0, 0, 0))
        if Hkv != H:
            k = jnp.repeat(k, cfg.q_per_kv, axis=2)
            v = jnp.repeat(v, cfg.q_per_kv, axis=2)
        att = gpt_mod._default_attention(
            q, k, v, causal=causal,
            window=getattr(cfg, "sliding_window", None),
        ).reshape(B, T0, E)
        x = x + att @ lp["wo"]
        h = llama_mod._rms_norm(x, lp["rms2"], cfg.rms_eps)
        return _llama_mlp(x, h, lp, cfg), (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    x = llama_mod._rms_norm(x[:, -1:], params["rmsf"], cfg.rms_eps)
    logits = llama_mod.head_logits(params, x)[:, 0]
    return logits, KVCache(k=k_new, v=v_new)


def llama_decode_step(params, cache: KVCache, token, pos, cfg,
                      rope=None):
    B = token.shape[0]
    H, Hkv, D, E = cfg.n_head, cfg.n_kv_head, cfg.head_dim, cfg.n_embd
    x = params["wte"][token][:, None, :].astype(cfg.dtype)  # [B,1,E]
    cos_t, sin_t = rope if rope is not None else llama_mod.rope_table(
        cfg, cfg.block_size
    )
    cos = jax.lax.dynamic_slice_in_dim(cos_t, pos, 1, 0)
    sin = jax.lax.dynamic_slice_in_dim(sin_t, pos, 1, 0)

    def body(x, layer):
        lp, k_c, v_c = layer
        h = llama_mod._rms_norm(x, lp["rms1"], cfg.rms_eps)
        q, k, v = _llama_qkv(h, lp, cfg, B, 1)
        q = llama_mod.apply_rope(q, cos, sin)
        k = llama_mod.apply_rope(k, cos, sin)
        k_c = jax.lax.dynamic_update_slice(k_c, k, (0, pos, 0, 0))
        v_c = jax.lax.dynamic_update_slice(v_c, v, (0, pos, 0, 0))
        # GQA handled inside _cached_attention (grouped einsum) —
        # never materialize a q_per_kv-expanded cache copy per step.
        att = _cached_attention(
            q, k_c, v_c, pos,
            window=getattr(cfg, "sliding_window", None),
        ).reshape(B, 1, E)
        x = x + att @ lp["wo"]
        h = llama_mod._rms_norm(x, lp["rms2"], cfg.rms_eps)
        return _llama_mlp(x, h, lp, cfg), (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    x = llama_mod._rms_norm(x, params["rmsf"], cfg.rms_eps)
    logits = llama_mod.head_logits(params, x)[:, 0]
    return logits, KVCache(k=k_new, v=v_new)


# ---------------------------------------------------------------------------
# Serving path: ragged (per-lane-position) decode + lane-granular
# prefill over one shared multi-lane cache. This is the model half of
# the continuous-batching scheduler (dlrover_tpu/serving/scheduler.py):
# every batch lane hosts a DIFFERENT sequence at a DIFFERENT position,
# so positions are vectors, cache writes are per-lane scatters, and
# prompt prefill lands chunk-by-chunk into one lane without touching
# the others. Llama-family configs only (the serving fleet's family);
# GPT's absolute position table would slot in the same way.
# ---------------------------------------------------------------------------


def _apply_rope_gathered(x, cos_t, sin_t, pos):
    """Rotate x [B, 1, H, D] with each lane at its OWN position:
    ``pos`` [B] int32 gathers per-lane rows from the precomputed
    tables. Same split-halves convention as llama.apply_rope."""
    cos = cos_t[pos][:, None, None, :]  # [B, 1, 1, d2]
    sin = sin_t[pos][:, None, None, :]
    d2 = cos.shape[-1]
    x1, x2 = x[..., :d2], x[..., d2:2 * d2]
    c = cos.astype(x.dtype)
    s = sin.astype(x.dtype)
    parts = [x1 * c - x2 * s, x2 * c + x1 * s]
    if 2 * d2 < x.shape[-1]:
        parts.append(x[..., 2 * d2:])
    return jnp.concatenate(parts, axis=-1)


def _cached_attention_ragged(q, k_cache, v_cache, pos, window=None):
    """q [B,1,H,D] against cache [B,T,H_kv,D] with PER-LANE positions
    ``pos`` [B]: lane b sees keys idx <= pos[b] (band-clamped under a
    sliding window). Grouped-query handled exactly like
    :func:`_cached_attention` — no expanded cache copies."""
    b, t, hkv, d = k_cache.shape
    h = q.shape[2]
    g = h // hkv
    qg = q.reshape(b, 1, hkv, g, d)
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k_cache,
        preferred_element_type=jnp.float32,
    ) / np.sqrt(d)
    idx = jnp.arange(t)[None, None, None, None, :]
    p = pos[:, None, None, None, None]
    mask = idx <= p
    if window is not None:
        mask &= (p - idx) < window
    s = jnp.where(mask, s, -1e30)
    att = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", att, v_cache)
    return o.reshape(b, 1, h, d)


def llama_decode_step_ragged(params, cache: KVCache, token, pos, cfg,
                             rope=None, active=None):
    """One continuous-batching decode step: token [B] int32, pos [B]
    int32 — every lane advances at its own position. Cache updates are
    one vectorized scatter per layer (``.at[lane, pos[lane]].set``);
    rope rows gather per lane; attention masks per lane. Returns
    (logits [B, vocab] f32, new cache).

    ``active`` [B] bool masks the CACHE WRITES: an inactive lane (no
    sequence, or one still mid-prefill) must not have its own cache
    touched — without the mask, every decode step would scatter a
    garbage key at ``pos[b]`` of lane b (the scheduler passes 0 for
    idle lanes), clobbering position 0 of a lane whose chunked
    prefill is still in flight. Inactive lanes still COMPUTE garbage
    logits the scheduler never reads — the price of one static-shape
    program for any active set; only their writes are suppressed.
    ``active=None`` means all lanes write (the all-decoding batch)."""
    B = token.shape[0]
    x = params["wte"][token][:, None, :].astype(cfg.dtype)  # [B,1,E]
    cos_t, sin_t = rope if rope is not None else llama_mod.rope_table(
        cfg, cfg.block_size
    )
    lanes = jnp.arange(B)
    write_mask = (
        None if active is None else active[:, None, None]
    )

    def body(x, layer):
        lp, k_c, v_c = layer
        h = llama_mod._rms_norm(x, lp["rms1"], cfg.rms_eps)
        q, k, v = _llama_qkv(h, lp, cfg, B, 1)
        q = _apply_rope_gathered(q, cos_t, sin_t, pos)
        k = _apply_rope_gathered(k, cos_t, sin_t, pos)
        k_w, v_w = k[:, 0], v[:, 0]
        if write_mask is not None:
            k_w = jnp.where(write_mask, k_w, k_c[lanes, pos])
            v_w = jnp.where(write_mask, v_w, v_c[lanes, pos])
        k_c = k_c.at[lanes, pos].set(k_w)
        v_c = v_c.at[lanes, pos].set(v_w)
        att = _cached_attention_ragged(
            q, k_c, v_c, pos,
            window=getattr(cfg, "sliding_window", None),
        ).reshape(B, 1, cfg.n_embd)
        x = x + att @ lp["wo"]
        h = llama_mod._rms_norm(x, lp["rms2"], cfg.rms_eps)
        return _llama_mlp(x, h, lp, cfg), (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    x = llama_mod._rms_norm(x, params["rmsf"], cfg.rms_eps)
    logits = llama_mod.head_logits(params, x)[:, 0]
    return logits, KVCache(k=k_new, v=v_new)


def _rect_attention_dense(q, k, v, start, window=None):
    """Rectangular causal attention for a lane prefill chunk: q
    [1,C,H,D] at absolute positions start..start+C against the lane's
    full key range [1,T,H_kv,D]; key j visible to chunk query i iff
    j <= start + i (band-clamped under a window). Dense masked einsum
    — the serving chunk is small, so the [C,T] score tile is cheap;
    the long-context path keeps ops/flash_attention_rect."""
    b, c, hq, d = q.shape
    t, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    qg = q.reshape(b, c, hkv, g, d)
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, k,
        preferred_element_type=jnp.float32,
    ) / np.sqrt(d)
    qi = start + jnp.arange(c)[None, None, None, :, None]
    ki = jnp.arange(t)[None, None, None, None, :]
    mask = ki <= qi
    if window is not None:
        mask &= (qi - ki) < window
    s = jnp.where(mask, s, -1e30)
    att = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", att, v)
    return o.reshape(b, c, hq, d)


def llama_lane_prefill_chunk(params, cache: KVCache, tokens, lane,
                             start, cfg, rope=None):
    """Prefill ``tokens`` [1, C] of ONE sequence into lane ``lane`` of
    the shared multi-lane cache at positions [start, start+C), leaving
    every other lane untouched — the bounded prefill admission step of
    the continuous-batching scheduler (decode latency is protected by
    capping C, not by pausing the whole batch for a monolithic
    prompt pass).

    ``lane`` and ``start`` are traced scalars, so one compiled program
    serves every lane/offset for a given chunk length C; the scheduler
    pads ragged final chunks up to C (padded positions write garbage
    that the next chunk or decode step overwrites BEFORE any mask can
    expose it, and padded queries' outputs are discarded host-side).

    Returns (chunk logits [1, C, vocab] f32, cache) — all chunk
    positions, so the caller samples the first token from the last
    REAL position of a padded final chunk."""
    B, C = tokens.shape
    if B != 1:
        raise ValueError(
            f"lane prefill takes one sequence, got batch {B}"
        )
    cos_t, sin_t = rope if rope is not None else llama_mod.rope_table(
        cfg, cfg.block_size
    )
    cos = jax.lax.dynamic_slice_in_dim(cos_t, start, C, 0)
    sin = jax.lax.dynamic_slice_in_dim(sin_t, start, C, 0)
    x = params["wte"][tokens].astype(cfg.dtype)  # [1,C,E]

    def body(x, layer):
        lp, k_c, v_c = layer
        h = llama_mod._rms_norm(x, lp["rms1"], cfg.rms_eps)
        q, k, v = _llama_qkv(h, lp, cfg, B, C)
        q = llama_mod.apply_rope(q, cos, sin)
        k = llama_mod.apply_rope(k, cos, sin)
        k_c = jax.lax.dynamic_update_slice(k_c, k, (lane, start, 0, 0))
        v_c = jax.lax.dynamic_update_slice(v_c, v, (lane, start, 0, 0))
        k_lane = jax.lax.dynamic_slice_in_dim(k_c, lane, 1, 0)
        v_lane = jax.lax.dynamic_slice_in_dim(v_c, lane, 1, 0)
        att = _rect_attention_dense(
            q, k_lane, v_lane, start,
            window=getattr(cfg, "sliding_window", None),
        ).reshape(B, C, cfg.n_embd)
        x = x + att @ lp["wo"]
        h = llama_mod._rms_norm(x, lp["rms2"], cfg.rms_eps)
        return _llama_mlp(x, h, lp, cfg), (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    x = llama_mod._rms_norm(x, params["rmsf"], cfg.rms_eps)
    logits = llama_mod.head_logits(params, x)
    return logits, KVCache(k=k_new, v=v_new)


def _fns_for(cfg) -> tuple:
    """(prefill_fn, step_fn) with model-specific constants (rope
    tables) precomputed once, outside any scan."""
    if isinstance(cfg, llama_mod.LlamaConfig):
        rope = llama_mod.rope_table(cfg, cfg.block_size)
        return (
            functools.partial(
                llama_prefill, rope=rope,
                causal=not getattr(cfg, "prefix_lm", False),
            ),
            functools.partial(llama_decode_step, rope=rope),
        )
    if isinstance(cfg, gpt_mod.GPTConfig):
        return gpt_prefill, gpt_decode_step
    raise TypeError(f"unsupported config type {type(cfg).__name__}")


def _kv_heads(cfg) -> int:
    return getattr(cfg, "n_kv_head", cfg.n_head)


# ---------------------------------------------------------------------------
# Generation loop
# ---------------------------------------------------------------------------


def generate(
    params: Dict[str, Any],
    cfg,
    prompt: jax.Array,  # [B, T_prompt] int32
    max_new_tokens: int,
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample ``max_new_tokens`` continuations. Greedy when
    ``temperature == 0``. Returns [B, T_prompt + max_new_tokens].

    The prompt fills the cache in ONE batched forward (prefill); the
    decode loop is one ``lax.scan`` over positions; jit-compatible
    (wrap in jax.jit with static max_new_tokens for repeated use).
    """
    prefill_fn, step_fn = _fns_for(cfg)
    b, t_prompt = prompt.shape
    total = t_prompt + max_new_tokens
    if total > cfg.block_size:
        raise ValueError(
            f"prompt+new = {total} exceeds block_size {cfg.block_size}"
        )
    if key is None:
        key = jax.random.PRNGKey(0)
    cache = _cache_for(cfg, b, total, _kv_heads(cfg))
    logits, cache = prefill_fn(params, cache, prompt, cfg)

    def sample(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k is not None:
            kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
            logits = jnp.where(logits < kth, -1e30, logits)
        return jax.random.categorical(k, logits).astype(jnp.int32)

    def decode_body(carry, i):
        cache, logits, key = carry
        key, sub = jax.random.split(key)
        tok = sample(logits, sub)
        new_logits, cache = step_fn(
            params, cache, tok, t_prompt + i, cfg
        )
        return (cache, new_logits, key), tok

    (_, _, _), toks = jax.lax.scan(
        decode_body, (cache, logits, key), jnp.arange(max_new_tokens)
    )
    return jnp.concatenate([prompt, toks.T], axis=1)


def decode_logits_sequential(params, cfg, tokens: jax.Array):
    """Teacher-forcing consistency helper (used by tests): run the
    cached decode step over ``tokens`` [B, T] and return the logits at
    every position [B, T, vocab] — must match the training forward."""
    _, step_fn = _fns_for(cfg)
    b, t = tokens.shape
    cache = _cache_for(cfg, b, t, _kv_heads(cfg))

    def body(cache, i):
        logits, cache = step_fn(params, cache, tokens[:, i], i, cfg)
        return cache, logits

    _, logits = jax.lax.scan(body, cache, jnp.arange(t))
    return jnp.swapaxes(logits, 0, 1)


def llama_prefill_chunked(params, cache: KVCache, tokens, cfg,
                          chunk_size: int = 1024, rope=None):
    """Bounded-memory prefill for LONG prompts: query chunks of
    ``chunk_size`` run through all layers against the growing cache
    via the rectangular flash kernel (flash_attention_rect, q_offset
    = chunk start) — peak attention memory is O(chunk * T) with no
    [T, T] score tile, versus the one-shot prefill's full-prompt
    pass. Causal only (a bidirectional GLM prefix cannot be chunked:
    early chunks would need future prefix context — use
    ``llama_prefill(causal=False)``).

    Returns the same (last-position logits, filled cache) contract as
    :func:`llama_prefill`; parity is regression-tested chunk-by-chunk
    (tests/test_flash_rect.py).

    Compilation note: the Python chunk loop traces one program per
    distinct (chunk start, chunk length) pair per call — ceil(T0 /
    chunk_size) compiles on first use for a given prompt length.
    Amortized over a long prompt this is cheap (the final ragged chunk
    is the only shape that varies between prompt lengths), but latency-
    sensitive servers should bucket prompt lengths to multiples of
    ``chunk_size``.
    """
    from dlrover_tpu.ops.flash_attention import flash_attention_rect

    if getattr(cfg, "prefix_lm", False):
        raise ValueError(
            "prefix-LM prompts prefill bidirectionally and cannot "
            "be chunked (early chunks would need future prefix "
            "context); use llama_prefill(causal=False)"
        )
    B, T0 = tokens.shape
    if T0 < 1:
        raise ValueError(
            "llama_prefill_chunked needs at least one prompt token "
            f"(got tokens of shape {tokens.shape})"
        )
    Hkv, E = cfg.n_kv_head, cfg.n_embd
    cos_t, sin_t = rope if rope is not None else llama_mod.rope_table(
        cfg, cfg.block_size
    )
    k_cache, v_cache = cache.k, cache.v
    x_last = None
    for start in range(0, T0, chunk_size):
        end = min(start + chunk_size, T0)
        c = end - start
        cos, sin = cos_t[start:end], sin_t[start:end]
        x = params["wte"][tokens[:, start:end]].astype(cfg.dtype)

        def body(x, layer, start=start, end=end, c=c, cos=cos,
                 sin=sin):
            lp, k_c, v_c = layer
            h = llama_mod._rms_norm(x, lp["rms1"], cfg.rms_eps)
            q, k, v = _llama_qkv(h, lp, cfg, B, c)
            q = llama_mod.apply_rope(q, cos, sin)
            k = llama_mod.apply_rope(k, cos, sin)
            k_c = jax.lax.dynamic_update_slice(
                k_c, k, (0, start, 0, 0)
            )
            v_c = jax.lax.dynamic_update_slice(
                v_c, v, (0, start, 0, 0)
            )
            win = getattr(cfg, "sliding_window", None)
            # Under a band, clamp visible keys to it: per-chunk key
            # traffic is O(chunk * window), not O(chunk * T) — the
            # kernel's dead-block skip saves the MXU work but not
            # the K/V block fetches.
            lo = 0 if win is None else max(0, start - win + 1)
            k_vis, v_vis = k_c[:, lo:end], v_c[:, lo:end]
            off = start - lo
            g = cfg.q_per_kv
            if g == 1:
                att = flash_attention_rect(
                    q, k_vis, v_vis, causal=True, q_offset=off,
                    window=win,
                )
            else:
                # GQA without expanding the cache: q heads i*g+j use
                # kv head i, so group j's strided head slice attends
                # the raw cache — g kernel calls over a small q chunk
                # instead of a q_per_kv-times K/V copy (which would
                # peak at the one-shot prefill's footprint, defeating
                # the point of chunking).
                outs = [
                    flash_attention_rect(
                        q[:, :, j::g], k_vis, v_vis, causal=True,
                        q_offset=off, window=win,
                    )
                    for j in range(g)
                ]
                att = jnp.stack(outs, axis=3).reshape(
                    B, c, cfg.n_head, cfg.head_dim
                )
            att = att.reshape(B, c, E)
            x = x + att @ lp["wo"]
            h = llama_mod._rms_norm(x, lp["rms2"], cfg.rms_eps)
            return _llama_mlp(x, h, lp, cfg), (k_c, v_c)

        x, (k_cache, v_cache) = jax.lax.scan(
            body, x, (params["blocks"], k_cache, v_cache)
        )
        x_last = x[:, -1:]
    x = llama_mod._rms_norm(x_last, params["rmsf"], cfg.rms_eps)
    logits = llama_mod.head_logits(params, x)[:, 0]
    return logits, KVCache(k=k_cache, v=v_cache)
