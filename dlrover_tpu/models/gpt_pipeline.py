"""Pipelined GPT training: the full model over the ``pipe`` mesh axis.

The missing piece between parallel/pipeline.py (generic 1F1B over
uniform-activation stages) and the GPT family: a real transformer has
an embedding before the uniform block stack and a norm+unembedding
after it. This module assembles the complete differentiable step the
way pipelines do it in practice (ref: the reference's PiPPy stage
split puts embed/head on the edge stages,
atorch/compilers/pipe_compiler/distributed_pippy_compiler.py):

* the EMBEDDING runs outside the pipeline (data-parallel, replicated
  over pipe — it is a gather, negligible next to a block); its
  backward uses the per-microbatch input cotangents the 1F1B schedule
  collects at logical stage 0 (``collect_input_grads``);
* the BLOCK STACK — the model's entire FLOPs body — pipelines with
  the interleaved 1F1B schedule, stage params stacked
  [n_stages, v_chunks, L/(n*V), ...];
* the HEAD (final norm + tied unembedding cross-entropy) evaluates at
  the last logical stage inside the schedule (``with_head``), its
  gradients psum'd out; the tied ``wte`` gradient is the sum of its
  embedding-side and head-side contributions.

Losses match the dense ``gpt.loss_fn`` exactly (same math, different
schedule) — the parity test trains both steps from one init and
compares trajectories.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from dlrover_tpu.models import gpt
from dlrover_tpu.models.pipeline_lm import (
    LmPipelineBuilder,
    make_pipelined_lm_step,
    shard_params_for_pipeline,  # noqa: F401 — re-export (tests/docs)
)
from dlrover_tpu.parallel.pipeline import split_stages_interleaved


def _stage_fn(chunk, x, cfg: gpt.GPTConfig, attn_fn):
    """One pipeline chunk = a scan over its share of the blocks."""

    def body(h, lp):
        return gpt._block(h, lp, cfg=cfg, attn_fn=attn_fn), None

    out, _ = jax.lax.scan(body, x, chunk)
    return out


def _head_loss(y, tgt, head, cfg: gpt.GPTConfig):
    """Final norm + tied unembedding + mean token cross-entropy for
    ONE microbatch (y [mb, T, E], tgt [mb, T])."""
    h = gpt._layer_norm(y, head["lnf_g"], head["lnf_b"])
    logits = (h @ head["wte"].T).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)
    return -jnp.mean(ll)


def split_params(params, n_stages: int, v_chunks: int):
    """GPT param tree -> (staged_blocks, embed, head)."""
    staged = split_stages_interleaved(
        params["blocks"], n_stages, v_chunks
    )
    embed = {"wte": params["wte"], "wpe": params["wpe"]}
    head = {
        "lnf_g": params["lnf_g"],
        "lnf_b": params["lnf_b"],
        "wte": params["wte"],  # tied unembedding
    }
    return staged, embed, head


def merge_grads(
    staged_grads, embed_grads, head_grads, n_stages: int,
    v_chunks: int,
):
    """Inverse of :func:`split_params` for gradients: re-stack block
    grads to the scanned [L, ...] layout and sum the tied wte
    contributions."""
    nV = n_stages * v_chunks

    def unstage(g):
        # [n, V, L/nV, ...] -> [V, n, L/nV, ...] -> [L, ...]
        q = jnp.swapaxes(g, 0, 1)
        return q.reshape((-1,) + g.shape[3:])

    blocks = jax.tree.map(unstage, staged_grads)
    del nV
    return {
        "blocks": blocks,
        "wte": embed_grads["wte"] + head_grads["wte"],
        "wpe": embed_grads["wpe"],
        "lnf_g": head_grads["lnf_g"],
        "lnf_b": head_grads["lnf_b"],
    }


def make_gpt_pipeline_step(
    mesh: Mesh,
    cfg: gpt.GPTConfig,
    optimizer: optax.GradientTransformation,
    n_micro: Optional[int] = None,
    v_chunks: int = 1,
    attn_fn=None,
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    seq_axis: Optional[str] = None,
):
    """Build ``step(params, opt_state, tokens, targets) -> (params,
    opt_state, metrics)`` training the FULL GPT with its block stack
    1F1B-pipelined over the mesh's ``pipe`` axis (the generic
    assembly lives in models/pipeline_lm.py). ``tokens`` [B, T] is
    cut into ``n_micro`` microbatches (default 2 * pipe size, the
    bubble-amortizing 1F1B convention).

    ``seq_axis`` shards the token dimension over that mesh axis
    inside the schedule (see make_pipelined_lm_step); the caller must
    then supply an ``attn_fn`` that is collective over the axis
    (e.g. ring attention called directly — the stage body is already
    inside shard_map). GPT is seq-shard-friendly at the edges: the
    positional embedding is added at embed time on the full sequence,
    and the head loss is a shard-local token mean the schedule
    pmean-corrects.
    """
    if seq_axis is not None and mesh.shape.get(seq_axis, 1) > 1 \
            and attn_fn is None:
        raise ValueError(
            "seq_axis sharding needs an explicitly collective attn_fn "
            "(the default dense attention would silently attend "
            "within each sequence shard only)"
        )
    n_stages = mesh.shape.get("pipe", 1)
    if cfg.n_layer % (n_stages * v_chunks):
        raise ValueError(
            f"n_layer={cfg.n_layer} must divide into "
            f"pipe({n_stages}) x v_chunks({v_chunks}) stages"
        )
    if attn_fn is None:
        attn_fn = functools.partial(
            gpt._default_attention,
            causal=getattr(cfg, "causal", True),
            window=getattr(cfg, "sliding_window", None),
        )

    def embed(e, toks):
        T = toks.shape[-1]
        return (e["wte"][toks] + e["wpe"][:T][None]).astype(cfg.dtype)

    return make_pipelined_lm_step(
        mesh,
        optimizer,
        split_params=lambda p: split_params(p, n_stages, v_chunks),
        merge_grads=lambda s, e, h: merge_grads(
            s, e, h, n_stages, v_chunks
        ),
        embed_fn=embed,
        stage_fn=functools.partial(_stage_fn, cfg=cfg, attn_fn=attn_fn),
        head_loss_fn=functools.partial(_head_loss, cfg=cfg),
        n_stages=n_stages,
        n_micro=n_micro,
        v_chunks=v_chunks,
        batch_axes=batch_axes,
        seq_axis=seq_axis,
    )


def GptPipelineBuilder(
    cfg: gpt.GPTConfig, v_chunks: int = 1
) -> LmPipelineBuilder:
    """auto_accelerate pipeline hook for the GPT family (the generic
    machinery — strategy-derived microbatch count, stage-sharded
    init — lives in pipeline_lm.LmPipelineBuilder)."""
    return LmPipelineBuilder(
        init_params=functools.partial(gpt.init_params, cfg=cfg),
        make_step=lambda mesh, opt, n_micro, v: (
            make_gpt_pipeline_step(
                mesh, cfg, opt, n_micro=n_micro, v_chunks=v
            )
        ),
        v_chunks=v_chunks,
    )
