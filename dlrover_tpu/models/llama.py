"""Llama-family language model, TPU-first.

Capability parity with the reference's Llama-2 pretraining/finetune
examples (/root/reference/atorch/examples/llama2/fsdp_llama2.py — HF
LlamaDecoderLayer + atorch auto_accelerate FSDP; ds_3d_llama2.py for
the 3D-parallel variant), built as an idiomatic JAX program rather
than an HF wrapper:

* pure-functional param pytree with logical sharding axes per leaf —
  the same (mesh, rules) pair that shards GPT drives Llama through
  DP/FSDP/TP/SP (parallel/sharding.py), replacing the reference's
  FSDP-wrapper + device-mesh plumbing;
* layers stacked and executed with ``lax.scan`` (one compiled block);
* RMSNorm in f32, rotary embeddings precomputed once outside the
  scan, SwiGLU MLP, optional grouped-query attention (n_kv_head <
  n_head, Llama-3 style);
* the same Pallas flash-attention kernel and named remat policies as
  GPT (ops/flash_attention.py, accelerate/remat.py);
* fused chunked cross-entropy against the (untied) lm_head for the
  loss (ops/cross_entropy.py).

``make_sharded_init`` (trainer/step.py) plays the role of the
reference's ``init_empty_weights_with_disk_offload``
(atorch/utils/meta_model_utils.py): params are materialized directly
into their shards on device, never gathered on one host.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    block_size: int = 4096
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 32  # < n_head enables grouped-query attention
    n_embd: int = 4096
    intermediate: int = 11008
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: Any = True  # same named policies as GPTConfig.remat
    use_flash_attention: Optional[bool] = None
    # None = auto (fused Pallas RMSNorm on TPU, ops/layer_norm.py).
    use_fused_norm: Optional[bool] = None
    # Declared attention masking (read by the auto_accelerate
    # seq-parallel binding, like GPTConfig.causal).
    causal: bool = True
    # > 0 switches every block's MLP to a mixture-of-experts routed
    # over the ``expert`` mesh axis (models/moe.py — Mixtral-shaped
    # family; experts use the GShard FFN formulation). ``intermediate``
    # then sets the per-expert hidden width.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # Mistral-style sliding-window attention: query i sees keys
    # (i-sliding_window, i]. None = full causal attention. The flash
    # kernel skips kv blocks entirely below the band (O(T*window)
    # work); the plain fallback applies the same band mask.
    sliding_window: Optional[int] = None
    # Flash tile override (block_q, block_k, block_q_bwd, block_k_bwd)
    # — same contract as GPTConfig.attn_blocks.
    attn_blocks: Optional[tuple] = None
    # Learned bias on the q/k/v projections (the ChatGLM2/3 shape —
    # models/glm.py; Llama/Mistral keep the default False).
    qkv_bias: bool = False
    # Prefix-LM generation semantics (GLM): prompts prefill with the
    # full bidirectional mask — every layer's prompt k/v depends on
    # the mask through the hiddens — then decode steps run causally.
    prefix_lm: bool = False
    # Fraction of head_dim that receives rotary embedding; the rest
    # passes through unrotated (ChatGLM applies RoPE to half the
    # dims). 1.0 = full-dim RoPE (Llama).
    rotary_pct: float = 1.0

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def q_per_kv(self) -> int:
        return self.n_head // self.n_kv_head

    def __post_init__(self):
        if self.n_head % self.n_kv_head:
            raise ValueError(
                f"n_head={self.n_head} not divisible by "
                f"n_kv_head={self.n_kv_head}"
            )
        rot = int(self.head_dim * self.rotary_pct)
        if not 0 < rot <= self.head_dim or rot % 2:
            raise ValueError(
                f"rotary_pct={self.rotary_pct} gives {rot} rotary "
                f"dims of head_dim={self.head_dim}; need an even "
                "count in (0, head_dim]"
            )
        if self.prefix_lm and self.sliding_window is not None:
            # A one-sided band over a bidirectional prefix is not a
            # defined mask; reject at config time rather than deep
            # inside the prefill scan (flash and the XLA fallback
            # both refuse window with causal=False).
            raise ValueError(
                "prefix_lm and sliding_window are mutually "
                "exclusive: the bidirectional prefix has no causal "
                "band to window"
            )

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256,
            block_size=8192,
            n_layer=32,
            n_head=32,
            n_kv_head=8,
            n_embd=4096,
            intermediate=14336,
            rope_theta=500000.0,
        )

    @staticmethod
    def tiny() -> "LlamaConfig":
        """Test-size config (GQA on, so tests cover the kv-repeat path)."""
        return LlamaConfig(
            vocab_size=256,
            block_size=64,
            n_layer=2,
            n_head=4,
            n_kv_head=2,
            n_embd=64,
            intermediate=128,
            dtype=jnp.float32,
            remat=False,
        )

    @staticmethod
    def mistral_7b() -> "LlamaConfig":
        """Mistral-7B-v0.1: Llama backbone + GQA + 4k sliding window
        over an 8k context."""
        return LlamaConfig(
            vocab_size=32000,
            block_size=8192,
            n_layer=32,
            n_head=32,
            n_kv_head=8,
            n_embd=4096,
            intermediate=14336,
            rope_theta=10000.0,
            sliding_window=4096,
        )

    @staticmethod
    def moe_8x7b() -> "LlamaConfig":
        """Mixtral-8x7B-shaped: Llama-2 backbone, 8 experts, top-2."""
        return LlamaConfig(
            vocab_size=32000,
            block_size=4096,
            n_layer=32,
            n_head=32,
            n_kv_head=8,
            n_embd=4096,
            intermediate=14336,
            rope_theta=1e6,
            n_experts=8,
            moe_top_k=2,
        )

    @staticmethod
    def moe_tiny() -> "LlamaConfig":
        return dataclasses.replace(
            LlamaConfig.tiny(), n_experts=4, moe_top_k=2
        )

    def _moe_cfg(self):
        from dlrover_tpu.models.moe import MoEConfig

        return MoEConfig(
            n_embd=self.n_embd,
            n_experts=self.n_experts,
            expert_hidden=self.intermediate,
            top_k=self.moe_top_k,
            capacity_factor=self.moe_capacity_factor,
            dtype=self.dtype,
            gated=True,  # SwiGLU experts + renormalized top-k:
            renorm_top_k=True,  # the Mixtral block shape
        )


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    """Llama init: normal(0, 0.02) everywhere, residual-output
    projections scaled down by 1/sqrt(2*n_layer) (GPT-2 convention the
    reference inherits through HF init overrides)."""
    E, L, I = cfg.n_embd, cfg.n_layer, cfg.intermediate
    D, Hkv = cfg.head_dim, cfg.n_kv_head
    std = 0.02
    resid_std = std / np.sqrt(2 * L)
    keys = jax.random.split(key, 9)

    def norm(k, shape, s=std):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(
            cfg.dtype
        )

    def stack(k, shape, s=std):
        return norm(k, (L,) + shape, s)

    blocks = {
        "rms1": jnp.ones((L, E), jnp.float32),
        "wq": stack(keys[1], (E, E)),
        "wk": stack(keys[2], (E, Hkv * D)),
        "wv": stack(keys[3], (E, Hkv * D)),
        "wo": stack(keys[4], (E, E), resid_std),
        "rms2": jnp.ones((L, E), jnp.float32),
    }
    if cfg.qkv_bias:
        blocks.update(
            bq=jnp.zeros((L, E), cfg.dtype),
            bk=jnp.zeros((L, Hkv * D), cfg.dtype),
            bv=jnp.zeros((L, Hkv * D), cfg.dtype),
        )
    if cfg.n_experts > 0:
        from dlrover_tpu.models.moe import init_moe_params

        per_layer = [
            init_moe_params(k, cfg._moe_cfg())
            for k in jax.random.split(keys[5], L)
        ]
        blocks["moe"] = jax.tree.map(
            lambda *xs: jnp.stack(xs), *per_layer
        )
    else:
        blocks.update(
            w_gate=stack(keys[5], (E, I)),
            w_up=stack(keys[6], (E, I)),
            w_down=stack(keys[7], (I, E), resid_std),
        )
    return {
        "wte": norm(keys[0], (cfg.vocab_size, E)),
        "blocks": blocks,
        "rmsf": jnp.ones((E,), jnp.float32),
        "lm_head": norm(keys[8], (cfg.vocab_size, E)),
    }


def param_logical_axes(cfg: LlamaConfig) -> Params:
    """Logical sharding axes per leaf (tensor axis on heads/mlp, fsdp
    on embed — the same rule table as GPT, parallel/sharding.py)."""
    blocks = {
        "rms1": ("layers", None),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "heads"),
        "wv": ("layers", "embed", "heads"),
        "wo": ("layers", "heads", "embed"),
        "rms2": ("layers", None),
    }
    if cfg.qkv_bias:
        blocks.update(
            bq=("layers", "heads"),
            bk=("layers", "heads"),
            bv=("layers", "heads"),
        )
    if cfg.n_experts > 0:
        from dlrover_tpu.models.moe import moe_logical_axes

        blocks["moe"] = {
            name: ("layers",) + axes
            for name, axes in moe_logical_axes(
                gated=cfg._moe_cfg().gated
            ).items()
        }
    else:
        blocks.update(
            w_gate=("layers", "embed", "mlp"),
            w_up=("layers", "embed", "mlp"),
            w_down=("layers", "mlp", "embed"),
        )
    return {
        "wte": ("vocab", "embed"),
        "blocks": blocks,
        "rmsf": (None,),
        "lm_head": ("vocab", "embed"),
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _rms_norm(x, g, eps):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(
        jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + eps
    )
    return (x32 * scale * g).astype(x.dtype)


def rope_table(cfg: LlamaConfig, t: int) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables [T, rot/2] in f32, computed once outside the
    layer scan (the reference recomputes them per forward inside the
    HF rotary module). ``rot = head_dim * rotary_pct`` — partial
    rotary (GLM) just shrinks the table; apply_rope reads the rotated
    width off the table shape."""
    d2 = int(cfg.head_dim * cfg.rotary_pct) // 2
    inv_freq = 1.0 / (
        cfg.rope_theta ** (np.arange(0, d2, dtype=np.float32) / d2)
    )
    pos = jnp.arange(t, dtype=jnp.float32)
    ang = pos[:, None] * inv_freq[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, T, H, D] -> rotated, split-halves convention (HF
    Llama). When the table covers fewer than D dims (rotary_pct < 1),
    the trailing D - 2*table dims pass through unrotated."""
    d2 = cos.shape[-1]
    x1, x2 = x[..., :d2], x[..., d2:2 * d2]
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    parts = [x1 * c - x2 * s, x2 * c + x1 * s]
    if 2 * d2 < x.shape[-1]:
        parts.append(x[..., 2 * d2:])
    return jnp.concatenate(parts, axis=-1)


def _block(x, lp, cfg: LlamaConfig, attn_fn, cos, sin):
    """One block. Returns (x, aux_loss) — aux is 0 for dense MLPs,
    the router load-balancing loss for MoE blocks."""
    B, T, E = x.shape
    H, Hkv, D = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    from dlrover_tpu.models.gpt import use_fused_norm

    fused = use_fused_norm(cfg)
    if fused:
        from dlrover_tpu.ops.layer_norm import (
            fused_add_rms_norm,
            fused_rms_norm,
        )

        h = fused_rms_norm(x, lp["rms1"], eps=cfg.rms_eps)
    else:
        h = _rms_norm(x, lp["rms1"], cfg.rms_eps)
    q, k, v = h @ lp["wq"], h @ lp["wk"], h @ lp["wv"]
    if cfg.qkv_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(B, T, H, D)
    k = k.reshape(B, T, Hkv, D)
    v = v.reshape(B, T, Hkv, D)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if Hkv != H and not getattr(attn_fn, "supports_gqa", False):
        # grouped-query: broadcast each kv head over its query group.
        # GQA-aware attention (the seq-parallel constructors) takes
        # the COMPACT k/v instead — the ring/a2a then move 1/q_per_kv
        # the bytes and broadcast per block on-device.
        k = jnp.repeat(k, cfg.q_per_kv, axis=2)
        v = jnp.repeat(v, cfg.q_per_kv, axis=2)
    att = attn_fn(q, k, v).reshape(B, T, E)
    if fused:
        # Attention residual add fused into the second norm's kernel.
        h, x = fused_add_rms_norm(
            att @ lp["wo"], x, lp["rms2"], eps=cfg.rms_eps
        )
    else:
        x = x + att @ lp["wo"]
        h = _rms_norm(x, lp["rms2"], cfg.rms_eps)
    return mlp_tail(x, h, lp, cfg)


def mlp_tail(x, h, lp, cfg: LlamaConfig):
    """Dense-SwiGLU or expert-routed MLP tail of a block. Shared by
    the training block and the decode paths (models/generate.py).
    Returns (x + mlp(h), aux_loss)."""
    if cfg.n_experts > 0:
        from dlrover_tpu.models.moe import moe_mlp

        y, aux = moe_mlp(lp["moe"], h, cfg._moe_cfg())
        return x + y.astype(x.dtype), aux
    gated = jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])
    return x + gated @ lp["w_down"], jnp.zeros((), jnp.float32)


def head_logits(params: Params, x: jax.Array) -> jax.Array:
    """lm_head projection in f32 — the single definition shared by
    forward() and the loss paths."""
    return jnp.einsum(
        "...te,ve->...tv", x, params["lm_head"],
        preferred_element_type=jnp.float32,
    )


def default_attention_for(cfg: LlamaConfig) -> Callable:
    """Same auto-selection as GPT (gpt.default_attention_for reads
    only block_size/use_flash_attention, which both configs carry)."""
    from dlrover_tpu.models import gpt

    return gpt.default_attention_for(cfg)


def backbone_with_aux(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    attn_fn: Optional[Callable] = None,
) -> tuple:
    """Forward without the head: ([B,T,E] hidden, summed MoE aux
    loss — 0 for dense configs)."""
    if attn_fn is None:
        attn_fn = default_attention_for(cfg)
    B, T = tokens.shape
    cos, sin = rope_table(cfg, T)
    x = params["wte"][tokens].astype(cfg.dtype)

    from dlrover_tpu.accelerate.remat import wire_block

    block = wire_block(
        lambda x, lp, af: _block(
            x, lp, cfg=cfg, attn_fn=af, cos=cos, sin=sin
        ),
        cfg.remat,
        attn_fn,
    )

    def scan_body(carry, lp):
        x, aux_sum = carry
        x, aux = block(x, lp)
        return (x, aux_sum + aux), None

    (x, aux), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), params["blocks"]
    )
    return _rms_norm(x, params["rmsf"], cfg.rms_eps), aux


def backbone(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    return backbone_with_aux(params, tokens, cfg, attn_fn)[0]


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    return head_logits(params, backbone(params, tokens, cfg, attn_fn))


def loss_fn(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: LlamaConfig,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    x, aux = backbone_with_aux(params, tokens, cfg, attn_fn)
    logits = head_logits(params, x)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(ll) + aux


def loss_fn_fused(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: LlamaConfig,
    attn_fn: Optional[Callable] = None,
    num_chunks: int = 8,
    save_logits: bool = False,
) -> jax.Array:
    from dlrover_tpu.ops.cross_entropy import fused_cross_entropy

    x, aux = backbone_with_aux(params, tokens, cfg, attn_fn)
    n = x.shape[0] * x.shape[1]
    return fused_cross_entropy(
        x.reshape(n, -1),
        params["lm_head"],
        targets.reshape(n),
        num_chunks,
        save_logits,
    ) + aux


def flops_per_token(cfg: LlamaConfig) -> float:
    """PaLM-convention training FLOPs/token (matches the reference's
    compute_llama2_training_flops in examples/llama2/example_utils.py:
    6 * matmul params + attention score/value matmuls). MoE counts
    only the *active* experts' matmuls (top_k) plus the router."""
    E, L, I = cfg.n_embd, cfg.n_layer, cfg.intermediate
    kv = cfg.n_kv_head * cfg.head_dim
    if cfg.n_experts > 0:
        # SwiGLU experts: gate+in+out matmuls per active expert
        mlp = 3 * cfg.moe_top_k * E * I + E * cfg.n_experts
    else:
        mlp = 3 * E * I  # gate + up + down
    per_layer = E * E + 2 * E * kv + E * E + mlp
    n_matmul = L * per_layer + cfg.vocab_size * E
    attn = 12 * L * cfg.block_size * E
    return 6.0 * n_matmul + attn
