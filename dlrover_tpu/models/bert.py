"""Bidirectional encoder (BERT family) on the GPT backbone.

The reference accelerates HF BERT by swapping its attention for fused
kernels (module_replace: /root/reference/atorch/atorch/auto/opt_lib/
module_replace_optimization.py; FlashMHA mappings
atorch/modules/transformer/layers.py) and training it through
auto_accelerate. Here the encoder IS models/gpt.py's backbone with
``causal=False`` — identical learned positions, pre-LN blocks, GELU
MLP, fused-norm and flash kernels, sharding rules and remat policies
all apply unchanged — plus the two training surfaces BERT adds:

* the masked-language-model objective (:func:`mask_tokens` +
  :func:`mlm_loss_fn`), 80/10/10 corruption;
* a sequence-classification head over mean-pooled hiddens
  (:func:`init_classifier_params` + :func:`classifier_loss_fn`), the
  fine-tune path.

Everything the strategy engine knows about GPT (module profiles, TP
plans, pipe splits) transfers, since the parameters and jaxpr are the
same shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from dlrover_tpu.models import gpt

Params = Any


def bert_base(**overrides) -> gpt.GPTConfig:
    """BERT-base shape (L12 H12 E768, 30522 WordPiece vocab) as a
    non-causal GPTConfig."""
    cfg = gpt.GPTConfig(
        vocab_size=30522,
        block_size=512,
        n_layer=12,
        n_head=12,
        n_embd=768,
        causal=False,
    )
    return dataclasses.replace(cfg, **overrides)


def bert_large(**overrides) -> gpt.GPTConfig:
    cfg = gpt.GPTConfig(
        vocab_size=30522,
        block_size=512,
        n_layer=24,
        n_head=16,
        n_embd=1024,
        causal=False,
    )
    return dataclasses.replace(cfg, **overrides)


def tiny(**overrides) -> gpt.GPTConfig:
    """Test-size encoder."""
    cfg = gpt.GPTConfig(
        vocab_size=256,
        block_size=64,
        n_layer=2,
        n_head=4,
        n_embd=64,
        causal=False,
        dtype=jnp.float32,
        remat=False,
    )
    return dataclasses.replace(cfg, **overrides)


# Parameter init/axes are the backbone's own.
init_params = gpt.init_params
param_logical_axes = gpt.param_logical_axes


def mask_tokens(
    key: jax.Array,
    tokens: jax.Array,
    vocab_size: int,
    mask_id: int,
    mask_rate: float = 0.15,
) -> tuple:
    """BERT corruption: select ``mask_rate`` of positions; replace 80%
    with [MASK], 10% with a random token, keep 10%. Returns
    (corrupted [B,T], labels [B,T] = original tokens, weights [B,T]
    f32 1.0 at selected positions). Fully traceable — usable inside
    jit / the input pipeline."""
    k_sel, k_op, k_rand = jax.random.split(key, 3)
    sel = jax.random.uniform(k_sel, tokens.shape) < mask_rate
    op = jax.random.uniform(k_op, tokens.shape)
    rand_tok = jax.random.randint(k_rand, tokens.shape, 0, vocab_size)
    corrupted = jnp.where(
        sel & (op < 0.8),
        mask_id,
        jnp.where(sel & (op >= 0.9), rand_tok, tokens),
    )
    return corrupted, tokens, sel.astype(jnp.float32)


def mlm_loss_fn(
    params: Params,
    tokens: jax.Array,
    labels: jax.Array,
    weights: jax.Array,
    cfg: gpt.GPTConfig,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    """Mean cross-entropy over the selected (weight>0) positions,
    logits via the tied embedding head."""
    logits = gpt.forward(params, tokens, cfg, attn_fn)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return -jnp.sum(ll * weights) / denom


def init_classifier_params(
    key: jax.Array, cfg: gpt.GPTConfig, n_classes: int
) -> Params:
    """Backbone params plus a mean-pool classification head."""
    k_body, k_head = jax.random.split(key)
    params = gpt.init_params(k_body, cfg)
    params["cls_w"] = (
        jax.random.normal(k_head, (cfg.n_embd, n_classes)) * 0.02
    )
    params["cls_b"] = jnp.zeros((n_classes,))
    return params


def classifier_logits(
    params: Params,
    tokens: jax.Array,
    cfg: gpt.GPTConfig,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    """[B, T] -> [B, n_classes] via mean-pooled final hiddens (the
    pooler; mean beats CLS-token pooling without a dedicated token)."""
    x = gpt.backbone(params, tokens, cfg, attn_fn)  # [B, T, E]
    pooled = jnp.mean(x.astype(jnp.float32), axis=1)
    return pooled @ params["cls_w"] + params["cls_b"]


def classifier_loss_fn(
    params: Params,
    tokens: jax.Array,
    labels: jax.Array,
    cfg: gpt.GPTConfig,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    logits = classifier_logits(params, tokens, cfg, attn_fn)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[:, None], axis=-1)
    return -jnp.mean(ll)


def classifier_logical_axes(cfg: gpt.GPTConfig, n_classes: int):
    axes = gpt.param_logical_axes(cfg)
    axes["cls_w"] = ("embed", None)
    axes["cls_b"] = (None,)
    return axes
