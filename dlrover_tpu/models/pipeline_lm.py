"""Generic full-LM pipeline assembly over parallel/pipeline.py.

Factors what every pipelined language model shares (the shape PiPPy's
stage split produces in the reference,
atorch/compilers/pipe_compiler/distributed_pippy_compiler.py): the
embedding runs outside the 1F1B schedule (replicated over pipe, its
backward driven by the collected stage-0 input cotangents), the
uniform block stack pipelines, and the head loss evaluates at the
last logical stage with its own gradients. Model families instantiate
it with their split/embed/stage/head callables —
models/gpt_pipeline.py and models/llama_pipeline.py are the two
in-tree users.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import optax
from jax.sharding import Mesh, PartitionSpec as P

from dlrover_tpu.parallel.pipeline import pipeline_train


def make_pipelined_lm_step(
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    split_params: Callable,   # params -> (staged, embed_p, head_p)
    merge_grads: Callable,    # (staged_g, embed_g, head_g) -> grads
    embed_fn: Callable,       # (embed_p, tokens[mb,T]) -> x[mb,T,E]
    stage_fn: Callable,       # (chunk, x[mb,T,E]) -> y[mb,T,E]
    head_loss_fn: Callable,   # (y[mb,T,E], tgt[mb,T], head_p) -> loss
    n_stages: int,
    n_micro: Optional[int] = None,
    v_chunks: int = 1,
    batch_axes: Tuple[str, ...] = ("data", "fsdp"),
    stage_aux: bool = False,
    seq_axis: Optional[str] = None,
):
    """Build ``step(params, opt_state, tokens, targets)`` training the
    full LM with its block stack 1F1B-pipelined. ``params`` and
    ``opt_state`` stay in the model's native layout (checkpoints and
    elastic restarts are pipeline-agnostic); the stage split/merge
    happens inside the jitted step.

    ``seq_axis`` additionally shards the TOKEN dimension of every
    microbatch (and target) over that mesh axis — sequence parallelism
    inside the pipeline. The caller's ``stage_fn`` then sees
    [mb, T/shards, E] activations inside an already-manual region and
    must use collective attention directly (e.g.
    ring_attention(axis_name=seq_axis), NOT a shard_map-wrapped
    constructor), with any position-dependent terms (rope tables)
    offset by the shard's axis_index. The 1F1B body's loss/grad pmean
    over the combined batch+seq axes turns shard-local token means
    into the exact global mean (equal shard sizes).
    """
    if n_micro is None:
        n_micro = max(2 * n_stages, 1)
    batch_axes = tuple(
        a for a in batch_axes if mesh.shape.get(a, 1) > 1
    )
    if seq_axis is not None and mesh.shape.get(seq_axis, 1) > 1:
        batch_spec = P(batch_axes if batch_axes else None, seq_axis)
    else:
        batch_spec = P(batch_axes) if batch_axes else P()

    pipe_step = pipeline_train(
        mesh,
        stage_fn,
        head_loss_fn,
        v_chunks=v_chunks,
        batch_spec=batch_spec,
        with_head=True,
        collect_input_grads=True,
        stage_aux=stage_aux,
    )

    def loss_and_grads(params, tokens, targets):
        staged, embed_p, head_p = split_params(params)
        B, T = tokens.shape
        if B % n_micro:
            raise ValueError(
                f"batch {B} must divide into {n_micro} microbatches"
            )
        mb = B // n_micro
        toks_mb = tokens.reshape(n_micro, mb, T)
        tgts_mb = targets.reshape(n_micro, mb, T)

        x0, embed_vjp = jax.vjp(
            lambda e: jax.vmap(lambda t: embed_fn(e, t))(toks_mb),
            embed_p,
        )
        loss, staged_grads, head_grads, dx0 = pipe_step(
            staged, x0, tgts_mb, head_p
        )
        # dx0 carries per-microbatch cotangents of the UN-meaned
        # per-microbatch losses; 1/M here restores d(mean)/d(x0).
        (embed_grads,) = embed_vjp(
            (dx0 / n_micro).astype(x0.dtype)
        )
        return loss, merge_grads(staged_grads, embed_grads, head_grads)

    def step(params, opt_state, tokens, targets):
        loss, grads = loss_and_grads(params, tokens, targets)
        grads = jax.tree.map(
            lambda g, p: g.astype(p.dtype), grads, params
        )
        updates, opt_state = optimizer.update(
            grads, opt_state, params
        )
        params = optax.apply_updates(params, updates)
        return params, opt_state, {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
        }

    return jax.jit(step, donate_argnums=(0, 1))


def shard_params_for_pipeline(
    mesh: Mesh, params, stacked_key: str = "blocks"
):
    """Device-put a native LM param tree so the stacked block subtree
    lives layer-per-stage (leading axis over ``pipe``) and everything
    else replicates — the layout the staged step reads without
    resharding."""
    from jax.sharding import NamedSharding

    blocks = jax.tree.map(
        lambda p: jax.device_put(p, NamedSharding(mesh, P("pipe"))),
        params[stacked_key],
    )
    rep = NamedSharding(mesh, P())
    out = {
        k: jax.device_put(v, rep)
        for k, v in params.items()
        if k != stacked_key
    }
    out[stacked_key] = blocks
    return out


class LmPipelineBuilder:
    """Generic auto_accelerate pipeline hook: derives a feasible
    microbatch count from each strategy and assembles (init_fn,
    step_fn). Model families provide ``init_params(key)`` and
    ``make_step(mesh, optimizer, n_micro, v_chunks)`` — see
    gpt_pipeline.GptPipelineBuilder / llama_pipeline.
    LlamaPipelineBuilder for the two in-tree instantiations."""

    def __init__(self, init_params, make_step, v_chunks: int = 1):
        self.init_params = init_params
        self.make_step = make_step
        self.v_chunks = v_chunks

    def __call__(self, mesh, strategy, optimizer):
        def init_fn(key):
            params = shard_params_for_pipeline(
                mesh, self.init_params(key)
            )
            return params, optimizer.init(params)

        pipe = mesh.shape.get("pipe", 1)
        batch_shards = mesh.shape.get("data", 1) * mesh.shape.get(
            "fsdp", 1
        )
        n_micro = feasible_n_micro(
            strategy.micro_batch_size, pipe, batch_shards
        )
        if n_micro is None:
            raise ValueError(
                f"no feasible microbatch count: batch "
                f"{strategy.micro_batch_size} over pipe={pipe}, "
                f"batch shards={batch_shards}"
            )
        step = self.make_step(mesh, optimizer, n_micro, self.v_chunks)
        return init_fn, step


def feasible_n_micro(
    batch: int, pipe: int, batch_shards: int
) -> Optional[int]:
    """Largest microbatch count satisfying the 1F1B constraints for a
    global ``batch``: a multiple of ``pipe`` dividing the batch, with
    each microbatch's rows divisible across the batch-sharding axes.
    Prefers 2*pipe (the bubble-amortizing convention), then the
    largest feasible; None when nothing fits."""
    feasible = [
        m
        for m in range(pipe, batch + 1, pipe)
        if batch % m == 0 and (batch // m) % batch_shards == 0
    ]
    if not feasible:
        return None
    return 2 * pipe if 2 * pipe in feasible else max(feasible)
