"""LoRA fine-tuning as a pytree transform.

Capability parity with the reference's peft integration
(/root/reference/atorch/examples/llama2/fsdp_llama2.py:116-129 wraps
HF Llama in peft LoraConfig; atorch/utils/peft_utils.py patches
save/load around it), done the functional-JAX way: LoRA factors are a
*separate* pytree mirroring the selected weight leaves, and
``apply`` materializes ``W + (alpha/r) * A @ B`` per step — the
rank-r matmul is a few MFLOPs, XLA fuses the add into the consumer,
and the model code (models/gpt.py, models/llama.py) is unchanged.

Training recipe::

    lcfg = LoraConfig(rank=8)
    lora_p = init_lora(params, lcfg, key)
    def loss(lora_p, tokens, targets):
        eff = apply(params, lora_p, lcfg)
        return llama.loss_fn(eff, tokens, targets, cfg)
    # optimizer state covers only the LoRA tree -> frozen base params

Because base params stay a plain (sharded) pytree, FSDP/TP sharding,
flash checkpoint, and the elastic trainer all work unchanged on LoRA
runs; only the optimizer tree shrinks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# weight leaves LoRA attaches to by default (attention + MLP
# projections in both model families; biases/norms never)
DEFAULT_TARGETS = (
    "wqkv", "wo", "wi", "wo2",            # gpt
    "wq", "wk", "wv", "w_gate", "w_up", "w_down",  # llama (wo shared)
)


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Sequence[str] = DEFAULT_TARGETS

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def _is_target(name: str, leaf, cfg: LoraConfig) -> bool:
    return (
        name in cfg.targets
        and hasattr(leaf, "ndim")
        and leaf.ndim >= 2
    )


def init_lora(
    params: Params,
    cfg: LoraConfig,
    key: jax.Array,
) -> Params:
    """Build the LoRA tree: for each targeted leaf [..., in, out]
    (leading dims = stacked layers), A ~ normal with std
    1/sqrt(fan_in) shaped [..., in, r], and B = 0 [..., r, out].
    B=0 makes step 0 a no-op, the standard LoRA init; the fan-in
    scaling keeps A@x at unit variance regardless of rank — same
    spirit as peft's Kaiming-uniform init (which uses a uniform
    distribution and a slightly different constant)."""
    flat = _flatten_named(params)
    out: Dict[Tuple[str, ...], Any] = {}
    keys = jax.random.split(key, max(len(flat), 1))
    for (path, leaf), k in zip(flat.items(), keys):
        if not _is_target(path[-1], leaf, cfg):
            continue
        *lead, n_in, n_out = leaf.shape
        a = (
            jax.random.normal(k, (*lead, n_in, cfg.rank), jnp.float32)
            / (n_in**0.5)
        ).astype(leaf.dtype)
        b = jnp.zeros((*lead, cfg.rank, n_out), leaf.dtype)
        out[path] = {"a": a, "b": b}
    return _unflatten_named(out)


def apply(params: Params, lora_params: Params, cfg: LoraConfig) -> Params:
    """Effective params: W + scaling * A@B on targeted leaves. Cheap
    enough to run inside the jitted step every iteration."""
    lora_flat = _flatten_named(lora_params, leaf_keys=("a", "b"))
    flat = _flatten_named(params)
    merged = dict(flat)
    for path, ab in lora_flat.items():
        w = flat[path]
        delta = jnp.einsum(
            "...ir,...ro->...io", ab["a"], ab["b"]
        ) * cfg.scaling
        merged[path] = (w + delta.astype(w.dtype)).astype(w.dtype)
    return _unflatten_named(merged)


def merge(params: Params, lora_params: Params, cfg: LoraConfig) -> Params:
    """Bake LoRA into the base weights for export/serving (the
    reference's peft merge_and_unload)."""
    return apply(params, lora_params, cfg)


def num_trainable(lora_params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(lora_params))


# ---------------------------------------------------------------------------
# named flatten/unflatten helpers (dict pytrees only — both model
# families use plain dicts)
# ---------------------------------------------------------------------------


def _flatten_named(tree: Params, leaf_keys=None, prefix=()) -> dict:
    out = {}
    if isinstance(tree, dict):
        if leaf_keys is not None and set(tree) == set(leaf_keys):
            out[prefix] = tree
            return out
        for k, v in tree.items():
            out.update(_flatten_named(v, leaf_keys, prefix + (k,)))
    else:
        out[prefix] = tree
    return out


def _unflatten_named(flat: dict) -> Params:
    root: Params = {}
    for path, leaf in flat.items():
        node = root
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = leaf
    return root
