"""Brain service entrypoint: ``python -m dlrover_tpu.brain.main``.

The standalone deployment of the historical resource optimizer (ref:
the Go brain's processor service + MySQL store,
go/brain/pkg/datastore/...): one long-lived process, a durable sqlite
file, masters connect with brain.server.RemoteBrain.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from dlrover_tpu.brain.server import BrainRpcServer, BrainService
from dlrover_tpu.common.log import get_logger

logger = get_logger("brain.main")


def parse_args(argv=None):
    p = argparse.ArgumentParser("dlrover-tpu-brain")
    p.add_argument(
        "--db", default="brain.db",
        help="sqlite datastore path (the durable cross-job history)",
    )
    p.add_argument("--port", type=int, default=0)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    server = BrainRpcServer(BrainService(args.db), port=args.port)
    server.start()
    print(f"DLROVER_TPU_BRAIN_PORT={server.port}", flush=True)
    stop = threading.Event()

    def _term(signum, frame):
        logger.info("signal %s; shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
