"""Brain: offline resource-optimization service.

Functional parity with the reference's Go brain
(dlrover/go/brain/: gRPC optimize API, ~10 pluggable optimization
algorithms, MySQL-backed job-metrics datastore): a Python service with
a sqlite datastore (this environment has no MySQL) exposing the same
shape — persist job runtime facts, answer resource-plan queries from
historical evidence. The master plugs it in through the
ResourceOptimizer seam of master/auto_scaler.py, exactly where the
reference's BrainResourceOptimizer sits
(python/master/resource/brain_optimizer.py).
"""

from dlrover_tpu.brain.service import (  # noqa: F401
    BrainService,
    BrainResourceOptimizer,
    JobMetricsRecord,
)
