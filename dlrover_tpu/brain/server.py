"""Standalone brain service + client.

The reference runs the Brain as its own deployment (gRPC service +
MySQL datastore, go/brain/pkg/...): jobs come and go but the brain
accumulates cross-job history. The in-process BrainService already
carries the full algorithm suite over sqlite; this module makes it a
SERVICE: an RPC server any master can call, a client that mirrors the
BrainService method surface, and a CLI entrypoint
(``python -m dlrover_tpu.brain.main --db /data/brain.db``) whose
sqlite file is the durable datastore (the MySQL analogue for a
single-writer service).
"""

from __future__ import annotations

import dataclasses

from dlrover_tpu.brain.service import (
    ALGORITHMS,
    BrainService,
    JobMetricsRecord,
    RuntimeSample,
    run_algorithm,
)
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient, RpcDispatcher, RpcServer
from dlrover_tpu.common.log import get_logger

logger = get_logger("brain.server")


class BrainRpcServer:
    """Hosts a BrainService behind the typed-msgpack RPC envelope."""

    def __init__(self, brain: BrainService, port: int = 0):
        self.brain = brain
        dispatcher = RpcDispatcher()
        dispatcher.register_report(
            msg.BrainPersistRequest, self._persist
        )
        dispatcher.register_get(
            msg.BrainOptimizeRequest, self._optimize
        )
        self._server = RpcServer(dispatcher, port=port)

    @property
    def port(self) -> int:
        return self._server.port

    @property
    def addr(self) -> str:
        return self._server.addr

    def start(self) -> None:
        self._server.start()
        logger.info("brain serving on %s", self.addr)

    def stop(self, grace: float = 5.0) -> None:
        # Drain in-flight persists on shutdown: a hard cancel would
        # leave masters unable to tell whether their history record
        # committed.
        self._server.stop(grace)

    # -- handlers --------------------------------------------------------

    @staticmethod
    def _known_fields(cls, payload: dict) -> dict:
        """Drop unknown payload keys, matching the wire schema's
        forward-compat guarantee (messages.py drops unknown fields on
        decode; the opaque payload dict must behave the same so a
        newer client's extra fields don't crash an older brain)."""
        names = {f.name for f in dataclasses.fields(cls)}
        return {k: v for k, v in payload.items() if k in names}

    def _persist(self, req: msg.BrainPersistRequest):
        if req.kind == "metrics":
            self.brain.persist_metrics(
                JobMetricsRecord(
                    **self._known_fields(JobMetricsRecord, req.payload)
                )
            )
        elif req.kind == "sample":
            self.brain.persist_runtime_sample(
                RuntimeSample(
                    **self._known_fields(RuntimeSample, req.payload)
                )
            )
        elif req.kind in ("ps_job", "fleet", "health", "remediation"):
            import inspect

            method = {
                "ps_job": self.brain.persist_ps_job,
                "fleet": self.brain.persist_fleet_sample,
                "health": self.brain.persist_health_verdict,
                "remediation":
                    self.brain.persist_remediation_decision,
            }[req.kind]
            params = set(inspect.signature(method).parameters)
            method(
                **{
                    k: v
                    for k, v in req.payload.items()
                    if k in params
                }
            )
        else:
            raise ValueError(f"unknown persist kind {req.kind!r}")
        return None

    def _optimize(self, req: msg.BrainOptimizeRequest):
        try:
            result = run_algorithm(
                self.brain, req.algorithm, *req.args, **req.kwargs
            )
        except Exception as exc:  # noqa: BLE001 — report, don't kill
            logger.warning(
                "algorithm %s failed", req.algorithm, exc_info=True
            )
            return msg.BrainOptimizeResponse(
                ok=False, error=f"{type(exc).__name__}: {exc}"
            )
        return msg.BrainOptimizeResponse(ok=True, result=result)


class RemoteBrain:
    """Client mirroring the BrainService surface over RPC — drop-in
    for BrainResourceOptimizer and the master's persistence hooks, so
    'in-process sqlite brain' and 'standalone brain deployment' are
    the same code path with a different constructor."""

    def __init__(self, addr: str, timeout: float = 10.0):
        self._client = RpcClient(addr, timeout=timeout)

    def close(self) -> None:
        self._client.close()

    # -- persistence -----------------------------------------------------

    def persist_metrics(self, rec: JobMetricsRecord) -> None:
        self._client.report(
            msg.BrainPersistRequest(
                kind="metrics", payload=dataclasses.asdict(rec)
            )
        )

    def persist_runtime_sample(self, s: RuntimeSample) -> None:
        self._client.report(
            msg.BrainPersistRequest(
                kind="sample", payload=dataclasses.asdict(s)
            )
        )

    def persist_ps_job(self, **kw) -> None:
        self._client.report(
            msg.BrainPersistRequest(kind="ps_job", payload=dict(kw))
        )

    def persist_fleet_sample(self, **kw) -> None:
        self._client.report(
            msg.BrainPersistRequest(kind="fleet", payload=dict(kw))
        )

    def persist_health_verdict(self, **kw) -> None:
        self._client.report(
            msg.BrainPersistRequest(kind="health", payload=dict(kw))
        )

    def persist_remediation_decision(self, **kw) -> None:
        self._client.report(
            msg.BrainPersistRequest(
                kind="remediation", payload=dict(kw)
            )
        )

    # -- algorithms ------------------------------------------------------

    def _call(self, algorithm: str, *args, **kwargs):
        resp = self._client.get(
            msg.BrainOptimizeRequest(
                algorithm=algorithm, args=list(args),
                kwargs=dict(kwargs),
            )
        )
        if not resp.ok:
            raise RuntimeError(
                f"brain algorithm {algorithm} failed: {resp.error}"
            )
        return resp.result


def _add_algorithm_proxies() -> None:
    """Generate one RemoteBrain method per BrainService algorithm
    method, so the client tracks the service surface automatically.
    Aliases (two algorithm names, one method) simply overwrite: any
    registered name reaches the same remote method."""
    for algo, method in ALGORITHMS.items():

        def proxy(self, *args, _algo=algo, **kw):
            return self._call(_algo, *args, **kw)

        proxy.__name__ = method
        proxy.__doc__ = (
            f"Remote call of BrainService.{method} (algorithm "
            f"{algo!r})."
        )
        setattr(RemoteBrain, method, proxy)


_add_algorithm_proxies()
