"""Brain service: historical job metrics -> resource plans.

Algorithms re-derived from the reference's optalgorithm set
(go/brain/pkg/optimizer/implementation/optalgorithm/):

* ``optimize_job_resource`` — initial plan from similar completed jobs
  (optimize_job_worker_create_resource.go): median of what worked.
* ``optimize_worker_oom`` — grow memory after OOM
  (optimize_job_worker_resource.go): max(seen peak * 1.5, request * 2).
* ``optimize_worker_count`` — throughput-knee detection
  (optimize_job_worker_count.go): stop adding workers when marginal
  speedup per worker drops below a threshold.

The datastore is sqlite (stdlib) instead of MySQL — same schema shape
(job facts + runtime samples), zero deployment burden.
"""

from __future__ import annotations

import dataclasses
import json
import sqlite3
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import NodeResource
from dlrover_tpu.master.auto_scaler import ResourceOptimizer
from dlrover_tpu.master.speed_monitor import SpeedMonitor

logger = get_logger("brain")


@dataclasses.dataclass
class JobMetricsRecord:
    job_name: str
    model_signature: str  # e.g. "gpt2-124m" — similarity key
    workers: int
    memory_mb: int
    chips_per_worker: int
    throughput: float  # samples or tokens / s
    peak_memory_mb: int = 0
    oom: bool = False
    completed: bool = True
    timestamp: float = 0.0


class BrainService:
    def __init__(self, db_path: str = ":memory:"):
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._lock = threading.Lock()
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS job_metrics (
                job_name TEXT, model_signature TEXT, workers INT,
                memory_mb INT, chips_per_worker INT, throughput REAL,
                peak_memory_mb INT, oom INT, completed INT,
                timestamp REAL
            )"""
        )

    def persist_metrics(self, rec: JobMetricsRecord) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO job_metrics VALUES (?,?,?,?,?,?,?,?,?,?)",
                (
                    rec.job_name,
                    rec.model_signature,
                    rec.workers,
                    rec.memory_mb,
                    rec.chips_per_worker,
                    rec.throughput,
                    rec.peak_memory_mb,
                    int(rec.oom),
                    int(rec.completed),
                    rec.timestamp or time.time(),
                ),
            )
            self._db.commit()

    def _rows(self, signature: str) -> List[tuple]:
        with self._lock:
            cur = self._db.execute(
                "SELECT workers, memory_mb, chips_per_worker, "
                "throughput, peak_memory_mb, oom, completed "
                "FROM job_metrics WHERE model_signature = ?",
                (signature,),
            )
            return cur.fetchall()

    # -- algorithms ---------------------------------------------------------

    def optimize_job_resource(
        self, signature: str
    ) -> Optional[Dict]:
        """Initial plan from successful history: median worker count
        and the max memory that never OOM'd."""
        rows = [r for r in self._rows(signature) if r[6]]  # completed
        if not rows:
            return None
        workers = sorted(r[0] for r in rows)
        memory = [r[1] for r in rows if not r[5]]
        plan = {
            "workers": workers[len(workers) // 2],
            "memory_mb": max(memory) if memory else max(
                r[1] for r in rows
            ),
            "chips_per_worker": rows[-1][2],
        }
        return plan

    def optimize_worker_oom(
        self, signature: str, requested_mb: int
    ) -> int:
        """Memory for an OOM retry: above every observed peak."""
        rows = self._rows(signature)
        peaks = [r[4] for r in rows if r[4] > 0]
        candidate = int(max(peaks) * 1.5) if peaks else requested_mb * 2
        return max(candidate, int(requested_mb * 1.5))

    def optimize_worker_count(
        self, signature: str, min_marginal_gain: float = 0.6
    ) -> Optional[int]:
        """Largest worker count whose marginal throughput per added
        worker stays above ``min_marginal_gain`` x linear scaling."""
        rows = [r for r in self._rows(signature) if r[3] > 0]
        if len(rows) < 2:
            return None
        by_workers: Dict[int, float] = {}
        for r in rows:
            by_workers[r[0]] = max(by_workers.get(r[0], 0.0), r[3])
        counts = sorted(by_workers)
        best = counts[0]
        for prev, cur in zip(counts, counts[1:]):
            gain = by_workers[cur] - by_workers[prev]
            linear = by_workers[prev] / prev * (cur - prev)
            if linear > 0 and gain / linear >= min_marginal_gain:
                best = cur
            else:
                break
        return best


class BrainResourceOptimizer(ResourceOptimizer):
    """Plugs the Brain into the master's auto-scaler (ref
    brain_optimizer.py BrainResoureOptimizer)."""

    def __init__(
        self,
        brain: BrainService,
        signature: str,
        min_workers: int = 1,
        max_workers: int = 64,
        hosts_per_slice: int = 1,
    ):
        self.brain = brain
        self.signature = signature
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.hosts_per_slice = max(hosts_per_slice, 1)

    def optimize_oom_node(self, resource: NodeResource) -> NodeResource:
        grown = NodeResource.from_dict(resource.to_dict())
        grown.memory_mb = self.brain.optimize_worker_oom(
            self.signature, max(resource.memory_mb, 1024)
        )
        return grown

    def target_worker_count(
        self, current: int, speed_monitor: SpeedMonitor
    ) -> int:
        suggested = self.brain.optimize_worker_count(self.signature)
        target = suggested if suggested is not None else current
        target = max(self.min_workers, min(target, self.max_workers))
        target -= target % self.hosts_per_slice
        return max(target, self.hosts_per_slice)
