"""Brain service: historical job metrics -> resource plans.

Algorithms re-derived from the reference's optalgorithm set
(go/brain/pkg/optimizer/implementation/optalgorithm/ — one function per
file, registered by name; same registry shape here in ALGORITHMS):

* ``optimize_job_resource`` — initial worker plan from similar
  completed jobs (optimize_job_worker_create_resource.go).
* ``optimize_worker_oom`` — grow memory after a worker OOM
  (optimize_job_worker_resource.go runtime path).
* ``optimize_worker_create_oom`` — initial memory for a job family
  with OOM history (optimize_job_worker_create_oom_resource.go).
* ``optimize_worker_count`` — throughput-knee detection
  (optimize_job_worker_resource.go count path).
* ``optimize_ps_create`` — PS count/resource from similar historic
  jobs (optimize_job_ps_create_resource.go).
* ``optimize_ps_cold_create`` — cold-start defaults with no history
  (optimize_job_ps_cold_create_resource.go).
* ``optimize_ps_init_adjust`` — PS cpu from the model's recv-op
  count + margin once the first steps ran
  (optimize_job_ps_init_adjust_resource.go).
* ``optimize_ps_oom`` — PS OOM memory growth
  (optimize_job_ps_oom_resource.go).
* ``optimize_hot_ps`` — per-node cpu/memory hotness over the last N
  runtime samples -> grow hot PS nodes
  (optimize_job_hot_ps_resource.go).

The datastore is sqlite (stdlib) instead of MySQL — same schema shape
(job facts + runtime samples), zero deployment burden.
"""

from __future__ import annotations

import dataclasses
import json
import sqlite3
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import NodeResource
from dlrover_tpu.master.auto_scaler import ResourceOptimizer
from dlrover_tpu.master.speed_monitor import SpeedMonitor

logger = get_logger("brain")


@dataclasses.dataclass
class JobMetricsRecord:
    job_name: str
    model_signature: str  # e.g. "gpt2-124m" — similarity key
    workers: int
    memory_mb: int
    chips_per_worker: int
    throughput: float  # samples or tokens / s
    peak_memory_mb: int = 0
    oom: bool = False
    completed: bool = True
    timestamp: float = 0.0


@dataclasses.dataclass
class RuntimeSample:
    """One telemetry snapshot of one node — the analogue of the
    reference's JobRuntimeInfo rows (PSCPU/PSMemory/WorkerCPU maps)."""

    job_name: str
    node_type: str  # "worker" | "ps"
    node_id: int
    used_cpu: float
    used_memory_mb: int
    config_cpu: float
    config_memory_mb: int
    speed: float = 0.0  # global steps/s at sample time
    timestamp: float = 0.0


class BrainService:
    # samples averaged for hotness decisions (ref
    # optimplcomm.NRecordToAvgResource)
    HOT_WINDOW = 3
    MAX_PS_CPU = 32.0  # ref maxCPUThreshold

    def __init__(self, db_path: str = ":memory:"):
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._lock = threading.Lock()
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS job_metrics (
                job_name TEXT, model_signature TEXT, workers INT,
                memory_mb INT, chips_per_worker INT, throughput REAL,
                peak_memory_mb INT, oom INT, completed INT,
                timestamp REAL
            )"""
        )
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS runtime_samples (
                job_name TEXT, node_type TEXT, node_id INT,
                used_cpu REAL, used_memory_mb INT, config_cpu REAL,
                config_memory_mb INT, speed REAL, timestamp REAL
            )"""
        )
        self._db.execute(
            """CREATE INDEX IF NOT EXISTS idx_runtime_samples
               ON runtime_samples (job_name, node_type, timestamp)"""
        )
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS ps_job_facts (
                job_name TEXT, model_signature TEXT, ps_count INT,
                ps_cpu REAL, ps_memory_mb INT, recv_op_count INT,
                oom INT, completed INT, timestamp REAL
            )"""
        )
        # Health plane (obs/health.py): fleet aggregate snapshots and
        # detector verdicts on the evaluation cadence — the telemetry
        # HISTORY the scaling policy engine plans over.
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS fleet_samples (
                job_name TEXT, aggregates TEXT, goodput_ratio REAL,
                health_score REAL, timestamp REAL
            )"""
        )
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS health_verdicts (
                job_name TEXT, detector TEXT, severity TEXT,
                node_id INT, message TEXT, action TEXT,
                evidence TEXT, timestamp REAL
            )"""
        )
        # Remediation engine (master/remediation.py): every decision
        # (acted, blocked, dry-run) and outcome transition, with the
        # governor audit trail as JSON — the record of what the
        # self-healing loop DID, next to what the detectors SAW.
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS remediation_decisions (
                job_name TEXT, decision_id INT, detector TEXT,
                node_id INT, host TEXT, action TEXT, outcome TEXT,
                dry_run INT, governors TEXT, message TEXT,
                timestamp REAL
            )"""
        )
        # Capacity plane (obs/capacity.py): closed slice state
        # intervals and per-tenant goodput rollups — the offline
        # history the capacity brain (ROADMAP item 5) warm-starts
        # goodput-per-chip planning from.
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS capacity_intervals (
                job_name TEXT, slice_id INT, state TEXT,
                tenant TEXT, job_id TEXT, start_ts REAL,
                end_ts REAL, chip_seconds REAL
            )"""
        )
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS tenant_goodput (
                job_name TEXT, tenant TEXT, chips INT,
                held_chip_seconds REAL, productive_chip_seconds REAL,
                goodput_per_chip REAL, timestamp REAL
            )"""
        )

    def persist_metrics(self, rec: JobMetricsRecord) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO job_metrics VALUES (?,?,?,?,?,?,?,?,?,?)",
                (
                    rec.job_name,
                    rec.model_signature,
                    rec.workers,
                    rec.memory_mb,
                    rec.chips_per_worker,
                    rec.throughput,
                    rec.peak_memory_mb,
                    int(rec.oom),
                    int(rec.completed),
                    rec.timestamp or time.time(),
                ),
            )
            self._db.commit()

    def _rows(self, signature: str) -> List[tuple]:
        with self._lock:
            cur = self._db.execute(
                "SELECT workers, memory_mb, chips_per_worker, "
                "throughput, peak_memory_mb, oom, completed "
                "FROM job_metrics WHERE model_signature = ?",
                (signature,),
            )
            return cur.fetchall()

    # -- algorithms ---------------------------------------------------------

    def optimize_job_resource(
        self, signature: str
    ) -> Optional[Dict]:
        """Initial plan from successful history: median worker count
        and the max memory that never OOM'd."""
        rows = [r for r in self._rows(signature) if r[6]]  # completed
        if not rows:
            return None
        workers = sorted(r[0] for r in rows)
        memory = [r[1] for r in rows if not r[5]]
        plan = {
            "workers": workers[len(workers) // 2],
            "memory_mb": max(memory) if memory else max(
                r[1] for r in rows
            ),
            "chips_per_worker": rows[-1][2],
        }
        return plan

    def optimize_worker_oom(
        self, signature: str, requested_mb: int
    ) -> int:
        """Memory for an OOM retry: above every observed peak."""
        rows = self._rows(signature)
        peaks = [r[4] for r in rows if r[4] > 0]
        candidate = int(max(peaks) * 1.5) if peaks else requested_mb * 2
        return max(candidate, int(requested_mb * 1.5))

    # keep this many newest samples per (job, node_type) — hotness
    # windows are tiny, unbounded telemetry would grow forever
    SAMPLE_RETENTION = 1000

    def persist_runtime_sample(self, s: RuntimeSample) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO runtime_samples VALUES "
                "(?,?,?,?,?,?,?,?,?)",
                (
                    s.job_name, s.node_type, s.node_id, s.used_cpu,
                    s.used_memory_mb, s.config_cpu,
                    s.config_memory_mb, s.speed,
                    s.timestamp or time.time(),
                ),
            )
            self._db.execute(
                "DELETE FROM runtime_samples WHERE rowid IN ("
                "  SELECT rowid FROM runtime_samples"
                "  WHERE job_name = ? AND node_type = ?"
                "  ORDER BY timestamp DESC"
                "  LIMIT -1 OFFSET ?)",
                (s.job_name, s.node_type, self.SAMPLE_RETENTION),
            )
            self._db.commit()

    def persist_fleet_sample(
        self,
        job_name: str,
        aggregates: Optional[Dict] = None,
        goodput_ratio: float = 0.0,
        health_score: float = 1.0,
        timestamp: float = 0.0,
    ) -> None:
        """One fleet-level telemetry snapshot per health-evaluation
        tick: the FleetAggregator's cross-host aggregates (stored as
        JSON) plus the goodput ratio and composite health score —
        the windowed history the worker-count / replanning policies
        consume."""
        with self._lock:
            self._db.execute(
                "INSERT INTO fleet_samples VALUES (?,?,?,?,?)",
                (
                    job_name,
                    json.dumps(aggregates or {}, sort_keys=True),
                    float(goodput_ratio),
                    float(health_score),
                    timestamp or time.time(),
                ),
            )
            self._db.execute(
                "DELETE FROM fleet_samples WHERE rowid IN ("
                "  SELECT rowid FROM fleet_samples"
                "  WHERE job_name = ?"
                "  ORDER BY timestamp DESC"
                "  LIMIT -1 OFFSET ?)",
                (job_name, self.SAMPLE_RETENTION),
            )
            self._db.commit()

    def recent_fleet_samples(
        self, job_name: str, limit: int = 100
    ) -> List[Dict]:
        """Newest-first fleet samples, aggregates decoded."""
        with self._lock:
            cur = self._db.execute(
                "SELECT aggregates, goodput_ratio, health_score, "
                "timestamp FROM fleet_samples WHERE job_name = ? "
                "ORDER BY timestamp DESC LIMIT ?",
                (job_name, limit),
            )
            rows = cur.fetchall()
        out = []
        for aggregates, ratio, score, ts in rows:
            try:
                decoded = json.loads(aggregates)
            except ValueError:
                decoded = {}
            out.append(
                {
                    "aggregates": decoded,
                    "goodput_ratio": ratio,
                    "health_score": score,
                    "timestamp": ts,
                }
            )
        return out

    def persist_capacity_interval(
        self,
        job_name: str,
        slice_id: int,
        state: str,
        tenant: str = "",
        job_id: str = "",
        start_ts: float = 0.0,
        end_ts: float = 0.0,
        chip_seconds: float = 0.0,
    ) -> None:
        """One closed slice state interval from the capacity ledger
        (``end_ts`` doubles as the retention-order timestamp)."""
        with self._lock:
            self._db.execute(
                "INSERT INTO capacity_intervals VALUES "
                "(?,?,?,?,?,?,?,?)",
                (
                    job_name, int(slice_id), state, tenant, job_id,
                    float(start_ts), float(end_ts),
                    float(chip_seconds),
                ),
            )
            self._db.execute(
                "DELETE FROM capacity_intervals WHERE rowid IN ("
                "  SELECT rowid FROM capacity_intervals"
                "  WHERE job_name = ?"
                "  ORDER BY end_ts DESC"
                "  LIMIT -1 OFFSET ?)",
                (job_name, self.SAMPLE_RETENTION),
            )
            self._db.commit()

    def recent_capacity_intervals(
        self, job_name: str, limit: int = 100
    ) -> List[Dict]:
        """Newest-first closed capacity intervals."""
        with self._lock:
            cur = self._db.execute(
                "SELECT slice_id, state, tenant, job_id, start_ts, "
                "end_ts, chip_seconds FROM capacity_intervals "
                "WHERE job_name = ? ORDER BY end_ts DESC LIMIT ?",
                (job_name, limit),
            )
            rows = cur.fetchall()
        return [
            {
                "slice_id": slice_id,
                "state": state,
                "tenant": tenant,
                "job_id": job_id,
                "start_ts": start_ts,
                "end_ts": end_ts,
                "chip_seconds": chip_seconds,
            }
            for slice_id, state, tenant, job_id, start_ts, end_ts,
            chip_seconds in rows
        ]

    def persist_tenant_goodput(
        self,
        job_name: str,
        tenant: str,
        chips: int = 0,
        held_chip_seconds: float = 0.0,
        productive_chip_seconds: float = 0.0,
        goodput_per_chip: float = 0.0,
        timestamp: float = 0.0,
    ) -> None:
        """One per-tenant chip-second rollup (held vs productive,
        goodput-per-chip) on the goodput-observation cadence."""
        with self._lock:
            self._db.execute(
                "INSERT INTO tenant_goodput VALUES (?,?,?,?,?,?,?)",
                (
                    job_name, tenant, int(chips),
                    float(held_chip_seconds),
                    float(productive_chip_seconds),
                    float(goodput_per_chip),
                    timestamp or time.time(),
                ),
            )
            self._db.execute(
                "DELETE FROM tenant_goodput WHERE rowid IN ("
                "  SELECT rowid FROM tenant_goodput"
                "  WHERE job_name = ?"
                "  ORDER BY timestamp DESC"
                "  LIMIT -1 OFFSET ?)",
                (job_name, self.SAMPLE_RETENTION),
            )
            self._db.commit()

    def recent_tenant_goodput(
        self, job_name: str, limit: int = 100
    ) -> List[Dict]:
        """Newest-first tenant goodput rollups."""
        with self._lock:
            cur = self._db.execute(
                "SELECT tenant, chips, held_chip_seconds, "
                "productive_chip_seconds, goodput_per_chip, "
                "timestamp FROM tenant_goodput "
                "WHERE job_name = ? ORDER BY timestamp DESC LIMIT ?",
                (job_name, limit),
            )
            rows = cur.fetchall()
        return [
            {
                "tenant": tenant,
                "chips": chips,
                "held_chip_seconds": held,
                "productive_chip_seconds": productive,
                "goodput_per_chip": gpc,
                "timestamp": ts,
            }
            for tenant, chips, held, productive, gpc, ts in rows
        ]

    def persist_health_verdict(
        self,
        job_name: str,
        detector: str,
        severity: str,
        node_id: int = -1,
        message: str = "",
        action: str = "",
        evidence: str = "",
        timestamp: float = 0.0,
    ) -> None:
        """One detector verdict transition (new verdict, severity
        change, or resolution). ``evidence`` is the JSON-encoded
        evidence window the verdict shipped."""
        with self._lock:
            self._db.execute(
                "INSERT INTO health_verdicts VALUES "
                "(?,?,?,?,?,?,?,?)",
                (
                    job_name, detector, severity, int(node_id),
                    message, action, evidence,
                    timestamp or time.time(),
                ),
            )
            self._db.execute(
                "DELETE FROM health_verdicts WHERE rowid IN ("
                "  SELECT rowid FROM health_verdicts"
                "  WHERE job_name = ?"
                "  ORDER BY timestamp DESC"
                "  LIMIT -1 OFFSET ?)",
                (job_name, self.SAMPLE_RETENTION),
            )
            self._db.commit()

    def recent_health_verdicts(
        self, job_name: str, limit: int = 100
    ) -> List[Dict]:
        with self._lock:
            cur = self._db.execute(
                "SELECT detector, severity, node_id, message, "
                "action, evidence, timestamp FROM health_verdicts "
                "WHERE job_name = ? ORDER BY timestamp DESC LIMIT ?",
                (job_name, limit),
            )
            rows = cur.fetchall()
        return [
            {
                "detector": detector,
                "severity": severity,
                "node_id": node_id,
                "message": message,
                "action": action,
                "evidence": evidence,
                "timestamp": ts,
            }
            for detector, severity, node_id, message, action,
            evidence, ts in rows
        ]

    def persist_remediation_decision(
        self,
        job_name: str,
        decision_id: int = 0,
        detector: str = "",
        node_id: int = -1,
        host: str = "",
        action: str = "",
        outcome: str = "",
        dry_run: int = 0,
        governors: str = "",
        message: str = "",
        timestamp: float = 0.0,
    ) -> None:
        """One remediation decision or outcome transition (the same
        decision_id appears once per outcome). ``governors`` is the
        JSON-encoded governor-check map."""
        with self._lock:
            self._db.execute(
                "INSERT INTO remediation_decisions VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?)",
                (
                    job_name, int(decision_id), detector,
                    int(node_id), host, action, outcome,
                    int(dry_run), governors, message,
                    timestamp or time.time(),
                ),
            )
            self._db.execute(
                "DELETE FROM remediation_decisions WHERE rowid IN ("
                "  SELECT rowid FROM remediation_decisions"
                "  WHERE job_name = ?"
                "  ORDER BY timestamp DESC"
                "  LIMIT -1 OFFSET ?)",
                (job_name, self.SAMPLE_RETENTION),
            )
            self._db.commit()

    def recent_remediation_decisions(
        self, job_name: str, limit: int = 100
    ) -> List[Dict]:
        with self._lock:
            cur = self._db.execute(
                "SELECT decision_id, detector, node_id, host, "
                "action, outcome, dry_run, governors, message, "
                "timestamp FROM remediation_decisions "
                "WHERE job_name = ? ORDER BY timestamp DESC LIMIT ?",
                (job_name, limit),
            )
            rows = cur.fetchall()
        out = []
        for (decision_id, detector, node_id, host, action, outcome,
             dry_run, governors, message, ts) in rows:
            try:
                decoded = json.loads(governors) if governors else {}
            except ValueError:
                decoded = {}
            out.append(
                {
                    "decision_id": decision_id,
                    "detector": detector,
                    "node_id": node_id,
                    "host": host,
                    "action": action,
                    "outcome": outcome,
                    "dry_run": bool(dry_run),
                    "governors": decoded,
                    "message": message,
                    "timestamp": ts,
                }
            )
        return out

    def persist_ps_job(
        self,
        job_name: str,
        signature: str,
        ps_count: int,
        ps_cpu: float,
        ps_memory_mb: int,
        recv_op_count: int = 0,
        oom: bool = False,
        completed: bool = True,
    ) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO ps_job_facts VALUES (?,?,?,?,?,?,?,?,?)",
                (
                    job_name, signature, ps_count, ps_cpu,
                    ps_memory_mb, recv_op_count, int(oom),
                    int(completed), time.time(),
                ),
            )
            self._db.commit()

    def _recent_samples(
        self, job_name: str, node_type: str, window: int
    ) -> Dict[int, List[tuple]]:
        """node_id -> newest-first [(used_cpu, used_mem, cfg_cpu,
        cfg_mem)] limited to ``window`` per node."""
        with self._lock:
            cur = self._db.execute(
                "SELECT node_id, used_cpu, used_memory_mb, "
                "config_cpu, config_memory_mb FROM runtime_samples "
                "WHERE job_name = ? AND node_type = ? "
                "ORDER BY timestamp DESC",
                (job_name, node_type),
            )
            rows = cur.fetchall()
        out: Dict[int, List[tuple]] = {}
        for node_id, ucpu, umem, ccpu, cmem in rows:
            bucket = out.setdefault(node_id, [])
            if len(bucket) < window:
                bucket.append((ucpu, umem, ccpu, cmem))
        return out

    def optimize_worker_count(
        self, signature: str, min_marginal_gain: float = 0.6
    ) -> Optional[int]:
        """Largest worker count whose marginal throughput per added
        worker stays above ``min_marginal_gain`` x linear scaling."""
        rows = [r for r in self._rows(signature) if r[3] > 0]
        if len(rows) < 2:
            return None
        by_workers: Dict[int, float] = {}
        for r in rows:
            by_workers[r[0]] = max(by_workers.get(r[0], 0.0), r[3])
        counts = sorted(by_workers)
        best = counts[0]
        for prev, cur in zip(counts, counts[1:]):
            gain = by_workers[cur] - by_workers[prev]
            linear = by_workers[prev] / prev * (cur - prev)
            if linear > 0 and gain / linear >= min_marginal_gain:
                best = cur
            else:
                break
        return best


    def optimize_worker_create_oom(
        self, signature: str, default_mb: int = 8192
    ) -> int:
        """Initial worker memory for a job family whose history shows
        OOMs (ref optimize_job_worker_create_oom_resource.go): above
        every OOM'd request and every observed peak."""
        rows = self._rows(signature)
        oom_requests = [r[1] for r in rows if r[5]]
        peaks = [r[4] for r in rows if r[4] > 0]
        if not oom_requests and not peaks:
            return default_mb
        floor = max(oom_requests + peaks)
        return int(floor * 1.5)

    # -- PS-strategy algorithms -----------------------------------------

    def _ps_rows(self, signature: str) -> List[tuple]:
        with self._lock:
            cur = self._db.execute(
                "SELECT ps_count, ps_cpu, ps_memory_mb, "
                "recv_op_count, oom, completed FROM ps_job_facts "
                "WHERE model_signature = ?",
                (signature,),
            )
            return cur.fetchall()

    def optimize_ps_create(self, signature: str) -> Optional[Dict]:
        """PS plan from similar completed jobs (ref
        optimize_job_ps_create_resource.go ->
        EstimateJobResourceByHistoricJobs): median count, max cpu, max
        memory that never OOM'd."""
        rows = [r for r in self._ps_rows(signature) if r[5]]
        if not rows:
            return None
        counts = sorted(r[0] for r in rows)
        no_oom = [r for r in rows if not r[4]]
        pool = no_oom or rows
        return {
            "ps_count": counts[len(counts) // 2],
            "ps_cpu": max(r[1] for r in pool),
            "ps_memory_mb": max(r[2] for r in pool),
        }

    def optimize_ps_cold_create(
        self,
        default_count: int = 2,
        default_cpu: float = 8.0,
        default_memory_mb: int = 8192,
    ) -> Dict:
        """Cold start — no history for the family (ref
        optimize_job_ps_cold_create_resource.go config defaults)."""
        return {
            "ps_count": default_count,
            "ps_cpu": default_cpu,
            "ps_memory_mb": default_memory_mb,
        }

    def optimize_ps_init_adjust(
        self,
        job_name: str,
        recv_op_count: int,
        ps_count: int,
        margin_cpu: float = 4.0,
        memory_margin_percent: float = 0.5,
    ) -> Optional[Dict]:
        """Right after the first steps: size PS cpu from the model's
        recv-op fan-in per PS (ref
        optimize_job_ps_init_adjust_resource.go: cpu =
        ceil(0.08 * recv_ops_per_ps) + margin, capped; memory = peak *
        (1 + margin))."""
        if ps_count <= 0:
            return None
        recv_per_ps = recv_op_count / ps_count
        if recv_per_ps <= 150:
            cpu = float(int(0.08 * recv_per_ps + 0.999)) + margin_cpu
        else:
            cpu = 16.0
        samples = self._recent_samples(
            job_name, "ps", self.HOT_WINDOW
        )
        peak_mem = 0
        observed_cpu = 0.0
        for rows in samples.values():
            for ucpu, umem, _, _ in rows:
                peak_mem = max(peak_mem, umem)
                observed_cpu = max(observed_cpu, ucpu)
        cpu = min(max(cpu, observed_cpu + margin_cpu),
                  self.MAX_PS_CPU)
        plan: Dict = {"ps_cpu": cpu}
        if peak_mem > 0:
            plan["ps_memory_mb"] = int(
                peak_mem * (1.0 + memory_margin_percent)
            )
        return plan

    def optimize_ps_oom(
        self, signature: str, requested_mb: int
    ) -> int:
        """Memory for an OOM'd PS relaunch (ref
        optimize_job_ps_oom_resource.go): above every observed PS
        request that OOM'd."""
        rows = self._ps_rows(signature)
        oomed = [r[2] for r in rows if r[4]]
        floor = max(oomed, default=requested_mb)
        return int(max(floor, requested_mb) * 1.5)

    def optimize_hot_ps(
        self,
        job_name: str,
        current_workers: int,
        target_workers: int,
        hot_cpu_util: float = 0.8,
        hot_memory_util: float = 0.8,
        memory_adjust_mb: int = 4096,
    ) -> Dict[int, Dict]:
        """Per-node hotness over the last HOT_WINDOW samples (ref
        optimize_job_hot_ps_resource.go): a PS averaging above the cpu
        threshold gets cpu scaled by target/current workers (capped at
        MAX_PS_CPU, every PS scaled by the same coefficient); one
        above the memory threshold gets a fixed memory bump. Returns
        {ps_id: {"cpu": new, "memory_mb": new}}."""
        samples = self._recent_samples(
            job_name, "ps", self.HOT_WINDOW
        )
        avg_cpu: Dict[int, float] = {}
        cfg_cpu: Dict[int, float] = {}
        hot_cpu: List[int] = []
        hot_mem: Dict[int, int] = {}
        for node_id, rows in samples.items():
            if len(rows) < self.HOT_WINDOW:
                continue
            a_cpu = sum(r[0] for r in rows) / len(rows)
            avg_cpu[node_id] = a_cpu
            cfg_cpu[node_id] = rows[0][2]
            if rows[0][2] > 0 and a_cpu / rows[0][2] >= hot_cpu_util:
                hot_cpu.append(node_id)
            a_mem = sum(r[1] for r in rows) / len(rows)
            if (rows[0][3] > 0
                    and a_mem / rows[0][3] >= hot_memory_util):
                hot_mem[node_id] = rows[0][3]
        plan: Dict[int, Dict] = {}
        if hot_cpu and current_workers > 0:
            coeff = target_workers / current_workers
            for n in hot_cpu:
                if avg_cpu[n] * coeff > self.MAX_PS_CPU:
                    coeff = self.MAX_PS_CPU / avg_cpu[n]
            # enlarge every PS by the same ratio (the ref scales the
            # whole group so the load stays balanced)
            for n, cpu in avg_cpu.items():
                opt = float(int(cpu * coeff + 0.999))
                if opt > cfg_cpu.get(n, 0.0):
                    plan[n] = {"cpu": min(opt, self.MAX_PS_CPU)}
        for n, cfg_mem in hot_mem.items():
            entry = plan.setdefault(n, {})
            entry["memory_mb"] = cfg_mem + memory_adjust_mb
        return plan


# Name -> bound-method registry, mirroring the reference's
# registerOptimizeAlgorithm table (optimize_algorithm.go).
ALGORITHMS = {
    "optimize_job_worker_create_resource": "optimize_job_resource",
    "optimize_job_worker_resource": "optimize_worker_count",
    "optimize_job_worker_create_oom_resource":
        "optimize_worker_create_oom",
    "optimize_job_worker_oom_resource": "optimize_worker_oom",
    "optimize_job_ps_create_resource": "optimize_ps_create",
    "optimize_job_ps_cold_create_resource": "optimize_ps_cold_create",
    "optimize_job_ps_init_adjust_resource": "optimize_ps_init_adjust",
    "optimize_job_ps_oom_resource": "optimize_ps_oom",
    "optimize_job_hot_ps_resource": "optimize_hot_ps",
}


def run_algorithm(brain: BrainService, name: str, /, *args, **kw):
    """Invoke a registered algorithm by its reference name."""
    try:
        method = ALGORITHMS[name]
    except KeyError:
        raise KeyError(
            f"unknown brain algorithm {name!r}; known: "
            f"{sorted(ALGORITHMS)}"
        ) from None
    return getattr(brain, method)(*args, **kw)


class BrainResourceOptimizer(ResourceOptimizer):
    """Plugs the Brain into the master's auto-scaler (ref
    brain_optimizer.py BrainResoureOptimizer)."""

    def __init__(
        self,
        brain: BrainService,
        signature: str,
        min_workers: int = 1,
        max_workers: int = 64,
        hosts_per_slice: int = 1,
    ):
        self.brain = brain
        self.signature = signature
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.hosts_per_slice = max(hosts_per_slice, 1)

    def optimize_oom_node(self, resource: NodeResource) -> NodeResource:
        grown = NodeResource.from_dict(resource.to_dict())
        grown.memory_mb = self.brain.optimize_worker_oom(
            self.signature, max(resource.memory_mb, 1024)
        )
        return grown

    def target_worker_count(
        self, current: int, speed_monitor: SpeedMonitor
    ) -> int:
        suggested = self.brain.optimize_worker_count(self.signature)
        target = suggested if suggested is not None else current
        target = max(self.min_workers, min(target, self.max_workers))
        target -= target % self.hosts_per_slice
        return max(target, self.hosts_per_slice)
