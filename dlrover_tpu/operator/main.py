"""Operator container entrypoint: ``python -m dlrover_tpu.operator.main``.

In-cluster by default (service-account token + CA); ``--apiserver``
points anywhere else (kind port-forward, the test's simulated
apiserver). Ref: go/operator/main.go manager setup.
"""

from __future__ import annotations

import argparse
import signal
import sys

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.operator.k8s_client import K8sApi
from dlrover_tpu.operator.runtime import OperatorRuntime

logger = get_logger("operator.main")


def parse_args(argv=None):
    p = argparse.ArgumentParser("dlrover-tpu-operator")
    p.add_argument(
        "--apiserver", default="",
        help="apiserver base URL (default: in-cluster config)",
    )
    p.add_argument("--namespace", default="")
    p.add_argument("--resync", type=float, default=30.0)
    p.add_argument(
        "--leader-elect", action="store_true", dest="leader_elect",
        help="coordination.k8s.io Lease leader election (run >1 "
        "replica safely)",
    )
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    api = (
        K8sApi(args.apiserver)
        if args.apiserver
        else K8sApi.in_cluster()
    )
    namespace = args.namespace or K8sApi.namespace()
    runtime = OperatorRuntime(
        api,
        namespace,
        resync_seconds=args.resync,
        leader_elect=args.leader_elect,
    )

    def _term(signum, frame):
        logger.info("signal %s; shutting down", signum)
        runtime.stop()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    runtime.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
