"""ElasticJob operator (controller/reconciler).

Functional parity with the reference's Go operator
(dlrover/go/operator/: ElasticJob + ScalePlan CRDs, reconciler that
creates the job-master pod and delegates pod lifecycle to it). The
reference requires a Go controller because it lives inside
kubernetes' controller-runtime; this build has no Go toolchain, so the
same reconcile semantics are implemented as a Python controller over
the ClusterClient seam — swap FakeClusterClient for the GKE client to
run it against a real cluster.
"""

from dlrover_tpu.operator.controller import (  # noqa: F401
    ElasticJob,
    ElasticJobController,
    JobPhase,
    ReplicaSpec,
)
