"""ElasticJob controller: reconcile jobs into master + worker pods.

Semantics ported from the reference's reconciler
(go/operator/pkg/controllers/elasticjob_controller.go:85 Reconcile,
master pod factory controllers/master/master.go, ScalePlan executor):

* An ElasticJob first gets a job-master pod; workers are NOT created
  by the operator — the master creates/scales them (the reference
  delegates pod lifecycle to the master the same way).
* ScalePlan custom objects written by an ElasticJobScaler are executed
  here (create/remove worker pods) for masters that don't own a pod
  scaler themselves.
* Job phase tracking: Pending -> Running -> Succeeded/Failed, with
  master-pod restart up to ``master_restart_limit``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import NodeResource
from dlrover_tpu.master.scaler import ClusterClient

logger = get_logger("operator")

_MEM_UNITS = {
    "Ki": 1 / 1024, "Mi": 1.0, "Gi": 1024.0, "Ti": 1024.0 * 1024,
    "K": 1e3 / (1 << 20), "M": 1e6 / (1 << 20),
    "G": 1e9 / (1 << 20), "T": 1e12 / (1 << 20),
}


def _parse_cpu(v) -> float:
    """k8s cpu quantity: cores or millicores ('500m' -> 0.5)."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    if not s:
        return 0.0
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    return float(s)


def _parse_memory_mb(v) -> int:
    """k8s memory quantity string -> MiB ('16Gi' -> 16384, '2048M' ->
    1953, bare numeric STRINGS are bytes per the k8s convention;
    python numbers are taken as MiB — our own NodeResource unit)."""
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip()
    if not s:
        return 0
    for suffix in sorted(_MEM_UNITS, key=len, reverse=True):
        if s.endswith(suffix):
            return int(float(s[:-len(suffix)]) * _MEM_UNITS[suffix])
    return int(float(s) / (1 << 20))  # bytes


class JobPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclasses.dataclass
class ReplicaSpec:
    """(ref ReplicaSpec in elasticjob_types.go:29-67)"""

    replicas: int = 1
    min_replicas: int = 0  # 0 -> replicas (no elasticity)
    resource: NodeResource = dataclasses.field(
        default_factory=NodeResource
    )
    restart_limit: int = 3


@dataclasses.dataclass
class ElasticJob:
    name: str
    workers: ReplicaSpec = dataclasses.field(default_factory=ReplicaSpec)
    master_resource: NodeResource = dataclasses.field(
        default_factory=lambda: NodeResource(cpu=2, memory_mb=4096)
    )
    master_restart_limit: int = 2
    # command/image fields would go in the pod template in production
    pod_template: Dict = dataclasses.field(default_factory=dict)
    # status
    phase: str = JobPhase.PENDING
    master_restarts: int = 0


class ElasticJobController:
    """One reconcile loop over a set of ElasticJobs."""

    def __init__(self, client: ClusterClient, interval: float = 5.0):
        self.client = client
        self.interval = interval
        self.jobs: Dict[str, ElasticJob] = {}
        self._executed_plans: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- API ----------------------------------------------------------------

    def create_job(self, job: ElasticJob) -> None:
        self.jobs[job.name] = job
        self.reconcile(job.name)

    def delete_job(self, name: str) -> None:
        job = self.jobs.pop(name, None)
        if job is None:
            return
        for pod in self.client.list_pods(name):
            try:
                self.client.delete_pod(pod["name"])
            except Exception:  # noqa: BLE001
                logger.warning(
                    "delete pod %s failed", pod["name"], exc_info=True
                )

    # -- reconcile ----------------------------------------------------------

    def master_pod_name(self, job_name: str) -> str:
        return f"{job_name}-master"

    def reconcile(self, name: str) -> None:
        """One reconcile pass for one job (ref Reconcile,
        elasticjob_controller.go:85)."""
        job = self.jobs.get(name)
        if job is None or job.phase in (
            JobPhase.SUCCEEDED,
            JobPhase.FAILED,
        ):
            return
        pods = {p["name"]: p for p in self.client.list_pods(name)}
        master_name = self.master_pod_name(name)
        master = pods.get(master_name)

        if master is None:
            if job.phase == JobPhase.RUNNING:
                # master pod vanished mid-job
                job.master_restarts += 1
                if job.master_restarts > job.master_restart_limit:
                    job.phase = JobPhase.FAILED
                    logger.error(
                        "job %s: master restart limit exceeded", name
                    )
                    return
                logger.warning(
                    "job %s: master pod gone; recreating (%d/%d)",
                    name,
                    job.master_restarts,
                    job.master_restart_limit,
                )
            self._create_master_pod(job)
            job.phase = JobPhase.RUNNING
            return

        phase = master.get("phase", "")
        if phase == "Succeeded":
            job.phase = JobPhase.SUCCEEDED
        elif phase == "Failed":
            job.master_restarts += 1
            if job.master_restarts > job.master_restart_limit:
                job.phase = JobPhase.FAILED
            else:
                self.client.delete_pod(master_name)
                self._create_master_pod(job)
        else:
            job.phase = JobPhase.RUNNING
        self._execute_scale_plans(job)

    def _create_master_pod(self, job: ElasticJob) -> None:
        spec = dict(job.pod_template)
        spec.update(
            {
                "name": self.master_pod_name(job.name),
                "job": job.name,
                "type": "master",
                "node_id": -1,
                "cpu": job.master_resource.cpu,
                "memory_mb": job.master_resource.memory_mb,
                # the master learns its world from the job spec
                "env": {
                    "DLROVER_TPU_NODE_NUM": str(job.workers.replicas),
                    "DLROVER_TPU_MIN_NODES": str(
                        job.workers.min_replicas
                        or job.workers.replicas
                    ),
                },
            }
        )
        self.client.create_pod(spec)

    # quantity parsing lives on the class-free module level so the
    # scaleplan path handles any k8s quantity the reference operator
    # (or a human) writes, not just "<int>Mi"

    def _execute_scale_plans(self, job: ElasticJob) -> None:
        """Execute ScalePlan custom objects written for this job (ref
        the operator's ScalePlan controller)."""
        plans = getattr(self.client, "custom_objects", {})
        for plan_name, body in list(plans.items()):
            # CRD ScaleSpec shape (scheduler/factory.py
            # scaleplan_manifest; ref scaleplan_types.go:39-84).
            spec_body = body.get("spec", {})
            if (
                spec_body.get("ownerJob") != job.name
                or plan_name in self._executed_plans
                # Durable marker: an operator restart / HA leader
                # failover starts with an empty in-memory set and
                # must not replay plans already executed by a
                # previous incarnation.
                or body.get("status", {}).get("executed")
            ):
                continue
            self._executed_plans.add(plan_name)
            for item in spec_body.get("createPods", []):
                try:
                    spec = dict(job.pod_template)
                    res = item.get("resource", {})
                    labels = item.get("labels", {})
                    spec.update(
                        {
                            "name": item.get(
                                "name",
                                f"{job.name}-worker-"
                                f"{item.get('id', 0)}",
                            ),
                            "job": job.name,
                            "type": item.get("type", "worker"),
                            "node_id": item.get("id", 0),
                            "rank": item.get(
                                "rankIndex", item.get("id", 0)
                            ),
                            "cpu": _parse_cpu(res.get("cpu", 0)),
                            "memory_mb": _parse_memory_mb(
                                res.get("memory", 0)
                            ),
                            # per-pod TPU shape from the plan; job
                            # template is the fallback for plans from
                            # the reference operator (whose PodMeta
                            # has no TPU fields)
                            "tpu_chips": int(
                                res.get(
                                    "google.com/tpu",
                                    job.pod_template.get(
                                        "tpu_chips", 0
                                    ),
                                )
                            ),
                            "tpu_accelerator": labels.get(
                                "dlrover-tpu/accelerator",
                                job.pod_template.get(
                                    "tpu_accelerator", ""
                                ),
                            ),
                        }
                    )
                    if "dlrover-tpu/slice" in labels:
                        spec["tpu_slice"] = int(
                            labels["dlrover-tpu/slice"]
                        )
                    self.client.create_pod(spec)
                except Exception:  # noqa: BLE001 — one bad pod must
                    # not abandon the rest of the plan
                    logger.warning(
                        "scaleplan %s: create pod %s failed",
                        plan_name,
                        item.get("name", "?"),
                        exc_info=True,
                    )
            for item in spec_body.get("removePods", []):
                try:
                    self.client.delete_pod(item["name"])
                except Exception:  # noqa: BLE001
                    pass
            try:
                self.client.patch_custom_object(
                    plan_name, {"status": {"executed": True}}
                )
            except Exception:  # noqa: BLE001 — worst case the
                # in-memory set still guards this incarnation; the
                # next one may replay (at-least-once, like the ref).
                logger.warning(
                    "scaleplan %s: executed-marker patch failed",
                    plan_name,
                    exc_info=True,
                )

    # -- loop ---------------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="elasticjob-controller",
                daemon=True,
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            for name in list(self.jobs):
                try:
                    self.reconcile(name)
                except Exception:  # noqa: BLE001
                    logger.warning(
                        "reconcile %s failed", name, exc_info=True
                    )
