"""Zero-dependency Kubernetes REST client for the operator.

The reference ships a Go controller-runtime operator
(go/operator/pkg/controllers/elasticjob_controller.go); this image has
no kubernetes Python SDK, so the deployable operator talks to the
apiserver with the stdlib only: bearer-token auth from the mounted
service account, CA-verified TLS, JSON in/out, line-delimited watch
streams, and Lease-based leader election. The same client pointed at
``http://127.0.0.1:<port>`` drives the reconcile e2e test against a
simulated apiserver — the HTTP layer is the seam, not hand-rolled
fakes.
"""

from __future__ import annotations

import calendar
import json
import os
import socket
import ssl
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Iterator, List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("operator.k8s")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"k8s api {status}: {message}")
        self.status = status


class K8sApi:
    """Thin typed-path REST client.

    Paths are absolute API paths ("/api/v1/namespaces/x/pods").
    ``base_url`` http(s)://host:port; token/ca for in-cluster auth.
    """

    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        timeout: float = 30.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self._ctx: Optional[ssl.SSLContext] = None
        if base_url.startswith("https"):
            self._ctx = ssl.create_default_context(cafile=ca_file)

    @classmethod
    def in_cluster(cls) -> "K8sApi":
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(SA_DIR, "token")) as f:
            token = f.read().strip()
        return cls(
            f"https://{host}:{port}",
            token=token,
            ca_file=os.path.join(SA_DIR, "ca.crt"),
        )

    @staticmethod
    def namespace() -> str:
        try:
            with open(os.path.join(SA_DIR, "namespace")) as f:
                return f.read().strip()
        except OSError:
            return os.environ.get("OPERATOR_NAMESPACE", "default")

    # -- http ------------------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[Dict] = None,
        params: Optional[Dict[str, str]] = None,
        content_type: str = "application/json",
        timeout: Optional[float] = None,
    ):
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = (
            json.dumps(body).encode("utf-8")
            if body is not None
            else None
        )
        req = urllib.request.Request(
            url, data=data, method=method
        )
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            return urllib.request.urlopen(
                req,
                timeout=timeout or self.timeout,
                context=self._ctx,
            )
        except urllib.error.HTTPError as exc:
            detail = ""
            try:
                detail = exc.read().decode("utf-8", "replace")[:500]
            except Exception:  # noqa: BLE001
                pass
            raise ApiError(exc.code, detail or exc.reason) from None
        except (urllib.error.URLError, socket.timeout, OSError) as exc:
            raise ApiError(0, str(exc)) from None

    def call(
        self,
        method: str,
        path: str,
        body: Optional[Dict] = None,
        params: Optional[Dict[str, str]] = None,
        content_type: str = "application/json",
    ) -> Dict:
        with self._request(
            method, path, body, params, content_type
        ) as resp:
            raw = resp.read()
        return json.loads(raw) if raw else {}

    # -- verbs -----------------------------------------------------------

    def get(self, path: str, params=None) -> Dict:
        return self.call("GET", path, params=params)

    def create(self, path: str, body: Dict) -> Dict:
        return self.call("POST", path, body)

    def delete(self, path: str) -> Dict:
        return self.call("DELETE", path)

    def patch_merge(self, path: str, body: Dict) -> Dict:
        return self.call(
            "PATCH", path, body,
            content_type="application/merge-patch+json",
        )

    def replace(self, path: str, body: Dict) -> Dict:
        """PUT (full update). With metadata.resourceVersion set this is
        the compare-and-swap write: a concurrent writer gets 409."""
        return self.call("PUT", path, body)

    def watch(
        self,
        path: str,
        params: Optional[Dict[str, str]] = None,
        timeout: float = 300.0,
    ) -> Iterator[Dict]:
        """Yield watch events (line-delimited JSON) until the server
        closes the stream. Raises ApiError if the server rejects the
        watch (callers fall back to list-based resync)."""
        p = dict(params or {})
        p["watch"] = "true"
        resp = self._request("GET", path, params=p, timeout=timeout)
        with resp:
            for line in resp:
                line = line.strip()
                if line:
                    yield json.loads(line)


class LeaderElector:
    """coordination.k8s.io/v1 Lease leader election — the controller-
    runtime recipe: acquire-if-expired, renew at a fraction of the
    lease duration, yield leadership on failure."""

    def __init__(
        self,
        api: K8sApi,
        namespace: str,
        name: str = "dlrover-tpu-operator",
        identity: Optional[str] = None,
        lease_seconds: int = 15,
    ):
        self.api = api
        self.path = (
            f"/apis/coordination.k8s.io/v1/namespaces/{namespace}"
            f"/leases/{name}"
        )
        self.create_path = (
            f"/apis/coordination.k8s.io/v1/namespaces/{namespace}"
            "/leases"
        )
        self.name = name
        self.identity = identity or f"{socket.gethostname()}-{os.getpid()}"
        self.lease_seconds = lease_seconds

    def _now(self) -> str:
        return time.strftime(
            "%Y-%m-%dT%H:%M:%S.000000Z", time.gmtime()
        )

    def try_acquire(self) -> bool:
        """One acquire-or-renew attempt; True while we are leader."""
        now = self._now()
        try:
            lease = self.api.get(self.path)
        except ApiError as exc:
            if exc.status != 404:
                logger.warning("lease get failed: %s", exc)
                return False
            try:
                self.api.create(
                    self.create_path,
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {"name": self.name},
                        "spec": {
                            "holderIdentity": self.identity,
                            "leaseDurationSeconds": self.lease_seconds,
                            "renewTime": now,
                        },
                    },
                )
                return True
            except ApiError:
                return False
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        renew = spec.get("renewTime", "")
        expired = True
        if renew:
            try:
                # renewTime is UTC; timegm avoids mktime's local-DST
                # offset (an hour of error flips the expiry verdict).
                t = calendar.timegm(
                    time.strptime(
                        # Fractional seconds are optional and a bare
                        # 'Z' survives the split — an unparsed live
                        # lease must not read as expired/stealable.
                        renew.split(".")[0].rstrip("Zz"),
                        "%Y-%m-%dT%H:%M:%S",
                    )
                )
                expired = (
                    time.time() - t
                    > spec.get(
                        "leaseDurationSeconds", self.lease_seconds
                    )
                )
            except ValueError:
                pass
        if holder not in (None, "", self.identity) and not expired:
            return False
        # Compare-and-swap: PUT with the read resourceVersion so two
        # electors seeing the same expired lease cannot both win (the
        # loser's write gets 409 — the controller-runtime recipe).
        lease.setdefault("metadata", {})
        lease["spec"] = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": self.lease_seconds,
            "renewTime": now,
        }
        try:
            self.api.replace(self.path, lease)
            return True
        except ApiError as exc:
            if exc.status == 409:
                logger.info("lost lease race to a peer")
            else:
                logger.warning("lease renew failed: %s", exc)
            return False


class RestClusterClient:
    """ClusterClient over the REST api (duck-typed to
    master/scaler.ClusterClient — create/delete/list pods and the
    custom_objects mapping the ScalePlan executor reads)."""

    def __init__(self, api: K8sApi, namespace: str, group: str,
                 version: str):
        self.api = api
        self.namespace = namespace
        self._group = group
        self._version = version

    def _pods_path(self) -> str:
        return f"/api/v1/namespaces/{self.namespace}/pods"

    def create_pod(self, spec: Dict) -> None:
        from dlrover_tpu.scheduler.factory import _pod_manifest

        self.api.create(
            self._pods_path(), _pod_manifest(spec, self.namespace)
        )

    def delete_pod(self, name: str) -> None:
        self.api.delete(f"{self._pods_path()}/{name}")

    def list_pods(self, job_name: str) -> List[Dict]:
        obj = self.api.get(
            self._pods_path(),
            params={"labelSelector": f"dlrover-job={job_name}"},
        )
        out = []
        for item in obj.get("items", []):
            meta = item.get("metadata", {})
            out.append(
                {
                    "name": meta.get("name", ""),
                    "job": job_name,
                    "phase": item.get("status", {}).get(
                        "phase", "Pending"
                    ),
                    "node_id": int(
                        meta.get("labels", {}).get(
                            "dlrover-node-id", -1
                        )
                    ),
                }
            )
        return out

    def _custom_path(self, plural: str, name: str = "") -> str:
        path = (
            f"/apis/{self._group}/{self._version}/namespaces/"
            f"{self.namespace}/{plural}"
        )
        return f"{path}/{name}" if name else path

    def list_custom(self, plural: str) -> List[Dict]:
        return self.api.get(self._custom_path(plural)).get(
            "items", []
        )

    def patch_custom_object(self, name: str, body: Dict) -> None:
        self.api.patch_merge(
            self._custom_path("scaleplans", name), body
        )

    def patch_status(
        self, plural: str, name: str, status: Dict
    ) -> None:
        # CRDs installed from deploy/ enable the status subresource,
        # where a patch to the ROOT silently drops the status stanza —
        # patch /status first; fall back to the root for apiservers /
        # CRDs without the subresource (404 there).
        body = {"status": status}
        try:
            self.api.patch_merge(
                self._custom_path(plural, name) + "/status", body
            )
        except ApiError as exc:
            if exc.status != 404:
                raise
            self.api.patch_merge(
                self._custom_path(plural, name), body
            )

    @property
    def custom_objects(self) -> Dict[str, Dict]:
        """name -> body of every ScalePlan in the namespace (the
        controller's ScalePlan executor reads this mapping)."""
        try:
            return {
                p.get("metadata", {}).get("name", ""): p
                for p in self.list_custom("scaleplans")
            }
        except ApiError as exc:
            logger.warning("list scaleplans failed: %s", exc)
            return {}
