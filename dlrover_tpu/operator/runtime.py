"""Operator runtime: watch ElasticJob/ScalePlan CRs, reconcile, report.

The deployable half of the operator (ref: controller-runtime manager in
go/operator/main.go + elasticjob_controller.go:85): a watch loop with
periodic full resync feeding the in-tree reconcile logic
(operator/controller.py), CR status write-back, and Lease leader
election. `kubectl apply -f deploy/` installs the CRDs, RBAC, and a
Deployment running this module; see deploy/README.md.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import NodeResource
from dlrover_tpu.operator.controller import (
    ElasticJob,
    ElasticJobController,
    ReplicaSpec,
    _parse_cpu,
    _parse_memory_mb,
)
from dlrover_tpu.operator.k8s_client import (
    ApiError,
    K8sApi,
    LeaderElector,
    RestClusterClient,
)

logger = get_logger("operator.runtime")

GROUP = "elastic.iml.github.io"
VERSION = "v1alpha1"


def elasticjob_from_cr(body: Dict) -> ElasticJob:
    """CR body (golden/elasticjob.yaml shape, ref
    elasticjob_types.go) -> controller model."""
    meta = body.get("metadata", {})
    spec = body.get("spec", {})
    replicas = spec.get("replicaSpecs", {})
    worker = replicas.get("worker", {})
    res = worker.get("resource", {})
    job = ElasticJob(
        name=meta.get("name", ""),
        workers=ReplicaSpec(
            replicas=int(worker.get("replicas", 1)),
            min_replicas=int(worker.get("minReplicas", 0)),
            resource=NodeResource(
                cpu=_parse_cpu(res.get("cpu", 0)),
                memory_mb=_parse_memory_mb(res.get("memory", 0)),
            ),
            restart_limit=int(worker.get("restartCount", 3)),
        ),
        master_restart_limit=int(spec.get("masterRestartLimit", 2)),
        pod_template=dict(spec.get("podTemplate", {})),
    )
    status = body.get("status", {})
    if status.get("phase"):
        job.phase = status["phase"]
        job.master_restarts = int(status.get("masterRestarts", 0))
    return job


class OperatorRuntime:
    """List/watch -> reconcile -> status write-back, with resync."""

    def __init__(
        self,
        api: K8sApi,
        namespace: str,
        resync_seconds: float = 30.0,
        leader_elect: bool = False,
    ):
        self.api = api
        self.namespace = namespace
        self.resync_seconds = resync_seconds
        self.client = RestClusterClient(api, namespace, GROUP, VERSION)
        self.controller = ElasticJobController(self.client)
        self.elector = (
            LeaderElector(api, namespace) if leader_elect else None
        )
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None

    # -- one pass ---------------------------------------------------------

    def _jobs_path(self) -> str:
        return (
            f"/apis/{GROUP}/{VERSION}/namespaces/{self.namespace}"
            "/elasticjobs"
        )

    def resync_once(self) -> None:
        """Full LIST + reconcile every job + status write-back. The
        level-triggered backbone; watch events only make it prompter."""
        try:
            items = self.api.get(self._jobs_path()).get("items", [])
        except ApiError as exc:
            logger.warning("list elasticjobs failed: %s", exc)
            return
        seen = set()
        for body in items:
            job = elasticjob_from_cr(body)
            if not job.name:
                continue
            seen.add(job.name)
            known = self.controller.jobs.get(job.name)
            if known is None:
                self.controller.jobs[job.name] = job
                known = job
            else:
                # Spec may have changed; status (phase/restarts) is
                # ours — keep the in-memory progression.
                known.workers = job.workers
                known.pod_template = job.pod_template
            try:
                self.controller.reconcile(known.name)
            except Exception:  # noqa: BLE001 — keep reconciling others
                logger.warning(
                    "reconcile %s failed", known.name, exc_info=True
                )
                continue
            # Level-triggered status write-back: compare against what
            # the apiserver actually has, so one failed patch (e.g. at
            # a terminal transition) is retried on every resync until
            # it lands, rather than being gated on an in-memory
            # transition that will never recur.
            cr_status = body.get("status", {})
            if (
                cr_status.get("phase") != known.phase
                or cr_status.get("masterRestarts", 0)
                != known.master_restarts
            ):
                try:
                    self.client.patch_status(
                        "elasticjobs",
                        known.name,
                        {
                            "phase": known.phase,
                            "masterRestarts": known.master_restarts,
                        },
                    )
                except ApiError as exc:
                    logger.warning(
                        "status update %s failed: %s", known.name, exc
                    )
        # Jobs deleted from the apiserver: tear their pods down.
        for name in list(self.controller.jobs):
            if name not in seen:
                logger.info("elasticjob %s deleted; cleaning up", name)
                self.controller.delete_job(name)

    # -- watch ------------------------------------------------------------

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                for event in self.api.watch(self._jobs_path()):
                    logger.info(
                        "watch event %s %s",
                        event.get("type"),
                        event.get("object", {})
                        .get("metadata", {})
                        .get("name"),
                    )
                    self._wake.set()
                    if self._stop.is_set():
                        return
            except ApiError as exc:
                # Simulated/old apiservers without watch support: the
                # resync loop alone carries reconciliation.
                logger.info(
                    "watch unavailable (%s); relying on resync", exc
                )
                if self._stop.wait(self.resync_seconds):
                    return
            except Exception:  # noqa: BLE001 — stream read errors
                # (idle-timeout socket errors, truncated JSON lines)
                # must re-open the watch, never kill the thread: a
                # dead watcher silently degrades to resync-only.
                logger.warning(
                    "watch stream broke; re-opening", exc_info=True
                )
                if self._stop.wait(1.0):
                    return

    # -- main loop --------------------------------------------------------

    def run(self) -> None:
        logger.info(
            "operator running: ns=%s resync=%ss leader_elect=%s",
            self.namespace,
            self.resync_seconds,
            self.elector is not None,
        )
        self._watch_thread = threading.Thread(
            target=self._watch_loop, name="elasticjob-watch",
            daemon=True,
        )
        self._watch_thread.start()
        # Tick fast enough to RENEW the lease well inside its
        # duration even when resync is long — a leader that only
        # renews every resync_seconds (default 30 s > the 15 s lease)
        # would hand leadership to the standby every cycle.
        tick = self.resync_seconds
        if self.elector is not None:
            tick = min(tick, self.elector.lease_seconds / 3.0)
        last_resync = float("-inf")
        while not self._stop.is_set():
            if self.elector is not None:
                if not self.elector.try_acquire():
                    logger.info("not leader; standing by")
                    self._stop.wait(tick)
                    continue
            due = (
                time.monotonic() - last_resync >= self.resync_seconds
            )
            if due or self._wake.is_set():
                self._wake.clear()
                self.resync_once()
                last_resync = time.monotonic()
            self._wake.wait(timeout=tick)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
