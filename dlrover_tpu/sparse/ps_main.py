"""Standalone parameter-server node process.

``python -m dlrover_tpu.sparse.ps_main --master host:port ...`` runs
one PsServer as its own OS process: it registers with the master's
PsManager (which assigns partitions, directs restores, and publishes
the map) and then heartbeats via periodic ``PsStatsReport``s — the
same report the hot-PS optimizer and the PS liveness monitor consume.

This is the process boundary the kill drills need: ``examples/ctr``
runs its PS nodes in-process (one SIGKILL would take the whole drill
down), while ``tools/stream_soak.py`` SIGKILLs individual PS
processes and lets the master's liveness monitor fail them over.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import Dict

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.sparse.partition import NUM_PARTITIONS
from dlrover_tpu.sparse.ps_server import PsServer

logger = get_logger("ps_main")


def parse_tables(spec: str) -> Dict[str, int]:
    """"name:dim[,name:dim...]" -> {name: dim}."""
    tables: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, dim = part.partition(":")
        tables[name.strip()] = int(dim)
    if not tables:
        raise ValueError(f"no tables in spec {spec!r}")
    return tables


def run_ps(
    node_id: int,
    master_addr: str,
    checkpoint_dir: str,
    tables: Dict[str, int],
    port: int = 0,
    num_partitions: int = NUM_PARTITIONS,
    seed: int = 0,
    stats_interval: float = 1.0,
    stop_event: threading.Event = None,
) -> None:
    server = PsServer(
        node_id,
        checkpoint_dir,
        tables,
        num_partitions=num_partitions,
        port=port,
        seed=seed,
    )
    server.start()
    client = RpcClient(master_addr)
    client.report(msg.PsRegisterRequest(node_id=node_id,
                                        addr=server.addr))
    logger.info("PS %d registered with master %s", node_id, master_addr)
    stop = stop_event or threading.Event()

    def _stop(*_):
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _stop)
        signal.signal(signal.SIGINT, _stop)
    except ValueError:
        pass  # not the main thread (embedded use in tests)
    # Stats reports double as the liveness heartbeat; a missed report
    # is fine (the monitor pings PS directly), a dead process is not.
    while not stop.wait(stats_interval):
        try:
            with server._lock:
                total = sum(len(t) for t in server._tables.values())
            client.report(msg.PsStatsReport(
                node_id=node_id, total_rows=total,
            ))
        except Exception:  # noqa: BLE001 — master may be mid-restart
            logger.warning("PS %d stats report failed", node_id)
    server.stop()
    client.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--node-id", type=int, required=True)
    ap.add_argument("--master", required=True,
                    help="master RPC address host:port")
    ap.add_argument("--checkpoint-dir", required=True,
                    help="shared delta-flush directory")
    ap.add_argument("--tables", default="emb:8",
                    help='embedding tables, "name:dim[,name:dim...]"')
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--num-partitions", type=int,
                    default=NUM_PARTITIONS)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--stats-interval", type=float, default=1.0)
    args = ap.parse_args(argv)
    run_ps(
        node_id=args.node_id,
        master_addr=args.master,
        checkpoint_dir=args.checkpoint_dir,
        tables=parse_tables(args.tables),
        port=args.port,
        num_partitions=args.num_partitions,
        seed=args.seed,
        stats_interval=args.stats_interval,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
