"""Sparse / embedding path (tfplus parity, TF-free).

KvVariable-style dynamically-growing embedding store (C++ host store,
dlrover_tpu/native/kv_store.cc) with fused sparse optimizers and a JAX
bridge for training CTR-style models on TPU.
"""

from dlrover_tpu.sparse.kv_variable import (  # noqa: F401
    KvVariable,
    SparseOptimizer,
    embedding_lookup,
)
