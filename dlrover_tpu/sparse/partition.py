"""Hash partitioning for the distributed embedding store.

Capability parity with the reference's PS sharding model
(dlrover/python/master/node/ps.py — fixed PS set per training session,
tfplus partitioned KvVariables): keys are mapped to a fixed number of
*virtual partitions* by a 64-bit mix hash, and partitions are assigned
to PS nodes by a versioned PartitionMap owned by the master. Scaling
moves whole partitions (not individual keys), so a reshard is a
bounded set of delta export/import transfers and the map version is
the only coordination point workers need.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

# Virtual partitions. Power of two, far above any realistic PS count,
# so every reshard moves ~1/P of the keyspace per partition moved.
NUM_PARTITIONS = 64


def key_partition(keys: np.ndarray, num_partitions: int = NUM_PARTITIONS
                  ) -> np.ndarray:
    """[n] int64 -> [n] int32 partition ids via a splitmix64-style mix
    (plain ``key % P`` would stripe structured id spaces onto few
    partitions)."""
    k = np.asarray(keys, np.uint64)
    k = (k ^ (k >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    k = (k ^ (k >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    k = k ^ (k >> np.uint64(31))
    return (k % np.uint64(num_partitions)).astype(np.int32)


def group_by_partition(
    keys: np.ndarray, num_partitions: int = NUM_PARTITIONS
) -> Dict[int, np.ndarray]:
    """partition id -> indices (into ``keys``) that hash to it. The
    shared grouping for per-partition work: delta-flush file layout
    and the replay fence's per-partition dedup both key on it, so a
    partition restored on a new owner sees exactly the key set the
    old owner's fence covered."""
    parts = key_partition(keys, num_partitions)
    out: Dict[int, np.ndarray] = {}
    for p in np.unique(parts):
        out[int(p)] = np.nonzero(parts == p)[0]
    return out


@dataclasses.dataclass
class PartitionMap:
    """Versioned assignment of virtual partitions to PS node ids.

    ``assignment[p]`` = ps node id owning partition p. The version
    increments on every change; PS servers reject requests carrying a
    stale version so workers refetch before retrying (the reference's
    worker SyncService barrier collapses into this version check).
    """

    version: int = 0
    assignment: List[int] = dataclasses.field(default_factory=list)
    # ps id -> "host:port" for direct worker connections
    ps_addrs: Dict[int, str] = dataclasses.field(default_factory=dict)

    @property
    def num_partitions(self) -> int:
        return len(self.assignment)

    def ps_ids(self) -> List[int]:
        return sorted(set(self.assignment))

    def partitions_of(self, ps_id: int) -> List[int]:
        return [p for p, owner in enumerate(self.assignment)
                if owner == ps_id]

    def group_keys(self, keys: np.ndarray) -> Dict[int, np.ndarray]:
        """ps id -> indices (into ``keys``) this ps owns."""
        parts = key_partition(keys, self.num_partitions)
        owners = np.asarray(self.assignment, np.int64)[parts]
        out: Dict[int, np.ndarray] = {}
        for ps_id in np.unique(owners):
            out[int(ps_id)] = np.nonzero(owners == ps_id)[0]
        return out


def balanced_assignment(
    ps_ids: List[int],
    num_partitions: int = NUM_PARTITIONS,
    previous: Optional[PartitionMap] = None,
) -> List[int]:
    """Assign partitions to ``ps_ids``, moving as few as possible from
    ``previous`` (consistent-hashing-style stability without the ring:
    keep owned partitions where the owner survives, rebalance the rest
    round-robin onto the least-loaded nodes)."""
    if not ps_ids:
        raise ValueError("no PS nodes to assign partitions to")
    alive = set(ps_ids)
    target = [-1] * num_partitions
    load: Dict[int, int] = {ps: 0 for ps in ps_ids}
    cap = -(-num_partitions // len(ps_ids))  # ceil: max partitions/ps
    if previous is not None and previous.assignment:
        for p, owner in enumerate(previous.assignment):
            if p < num_partitions and owner in alive and load[owner] < cap:
                target[p] = owner
                load[owner] += 1
    for p in range(num_partitions):
        if target[p] < 0:
            ps = min(ps_ids, key=lambda i: load[i])
            target[p] = ps
            load[ps] += 1
    return target
