"""Parameter-server node for the sparse embedding path.

Capability parity with the reference's PS pillar as a system: each PS
node hosts a shard (a set of hash partitions, sparse/partition.py) of
every KvVariable table behind the typed-msgpack RPC transport
(common/comm.py) — the TPU-native replacement for tfplus's in-graph
partitioned KvVariables served by TF PS servers
(tfplus/tfplus/kv_variable/kernels/kv_variable_ops.cc) and managed by
dlrover's PS node managers (dlrover/python/master/node/ps.py).

Elasticity protocol (master-directed, data moves PS-to-PS):

* every data-plane request carries the PartitionMap version; a stale
  or frozen-partition request is rejected with ``StaleMapError`` so the
  worker refetches the map and retries — the version check is the
  whole worker-sync story (ref sync_service.py's barrier).
* scale-up: master freezes moving partitions on the source, tells the
  target to PULL them (delta export / import of values + optimizer
  slots), bumps the map, unfreezes.
* failure: master reassigns the dead node's partitions to survivors,
  who restore them from the flush dir (delta checkpoint files written
  by ``flush`` — the sparse analogue of flash checkpoint).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu import obs
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient, RpcDispatcher, RpcServer
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.storage import get_storage
from dlrover_tpu.sparse.kv_variable import KvVariable
from dlrover_tpu.sparse.partition import (
    NUM_PARTITIONS,
    group_by_partition,
    key_partition,
)

logger = get_logger("ps_server")

_FENCED_APPLIES = obs.counter(
    "dlrover_stream_fenced_applies_total",
    "Replayed (client, seq) apply rows deduplicated by the per-"
    "partition replay fence (each one would have been a double-apply)",
    ("table",),
)
_STALE_EPOCH_REJECTS = obs.counter(
    "dlrover_stream_stale_epoch_rejects_total",
    "Apply requests rejected because their barrier epoch predates "
    "the PS fence epoch (a zombie writer from before a restore)",
    ("table",),
)


class StaleMapError(RuntimeError):
    """Client used an outdated PartitionMap (or hit a frozen/foreign
    partition); it must refetch the map and retry."""


class StaleEpochError(RuntimeError):
    """Apply carried a barrier epoch older than this PS's fence: the
    writer predates the last restore cut and must re-sync (unlike a
    stale map this is not retryable with the same request)."""


class PsServer:
    """One PS node: tables + partitions + RPC service.

    ``checkpoint_dir``: where delta flushes land; restore reads it.
    Table rows owned = rows whose ``key_partition`` is in
    ``self.partitions`` — enforcement is cooperative (clients route by
    the same map), with explicit checks on export/move paths.
    """

    def __init__(
        self,
        node_id: int,
        checkpoint_dir: str,
        embedding_dims: Dict[str, int],
        num_partitions: int = NUM_PARTITIONS,
        port: int = 0,
        seed: int = 0,
        storage=None,
        kv_options: Optional[dict] = None,
    ):
        """``kv_options`` forwards to every KvVariable — e.g.
        {"disk_tier_path": dir, "max_ram_rows": n} enables the hybrid
        RAM/disk tier on this PS node's tables."""
        self.node_id = node_id
        self.checkpoint_dir = checkpoint_dir.rstrip("/")
        self.num_partitions = num_partitions
        self.storage = storage or get_storage()
        kv_options = dict(kv_options or {})
        tier_path = kv_options.pop("disk_tier_path", None)
        self._tables: Dict[str, KvVariable] = {
            name: KvVariable(
                name,
                dim,
                seed=seed + i,
                disk_tier_path=(
                    f"{tier_path}/ps{node_id}_{name}.tier"
                    if tier_path
                    else None
                ),
                **kv_options,
            )
            for i, (name, dim) in enumerate(sorted(embedding_dims.items()))
        }
        self._lock = threading.RLock()
        self.partitions: List[int] = []
        self.frozen: set = set()
        self.map_version = -1
        # flush bookkeeping: per-table last flushed store version (the
        # KvVariable's version counter is the training step passed to
        # apply_gradients/assign)
        self._flushed_version: Dict[str, int] = {}
        # Replay fence: partition -> {client_id: highest applied seq}.
        # Applies are synchronous per client, so seqs arrive non-
        # decreasing; a repeat at or below the mark is a replay (the
        # commit succeeded but the response was lost, or the trainer
        # is replaying its post-barrier window after a failover) and
        # must be a no-op. Granularity is the partition because that
        # is the unit of flush/restore/rebalance: a restored partition
        # rewinds to its fence-at-flush while surviving partitions
        # keep their live marks — together they make trainer replay
        # exactly-once.
        self._part_seqs: Dict[int, Dict[int, int]] = {}
        # Highest barrier epoch flushed/restored on this PS; applies
        # stamped with an older epoch are rejected.
        self.fence_epoch = -1
        self._qps_count = 0
        self._qps_t0 = time.time()

        dispatcher = RpcDispatcher()
        dispatcher.register_get(msg.PsLookupRequest, self._lookup)
        dispatcher.register_get(msg.PsApplyRequest, self._apply)
        dispatcher.register_get(msg.PsExportRequest, self._export)
        dispatcher.register_get(msg.PsImportRequest, self._import)
        dispatcher.register_get(msg.PsPullPartitionsRequest, self._pull)
        dispatcher.register_get(msg.PsFreezeRequest, self._freeze)
        dispatcher.register_get(msg.PsStatsRequest, self._stats)
        dispatcher.register_get(msg.PsFlushRequest, self._flush)
        dispatcher.register_get(msg.PsRestoreRequest, self._restore)
        dispatcher.register_get(
            msg.PsSetPartitionsRequest, self._set_partitions
        )
        self._server = RpcServer(dispatcher, port=port)

    # -- lifecycle -------------------------------------------------------

    @property
    def addr(self) -> str:
        return self._server.addr

    def start(self) -> None:
        self._server.start()
        logger.info("PS %d serving on %s", self.node_id, self.addr)

    def stop(self) -> None:
        self._server.stop(grace=0.5)

    def set_partitions(self, partitions: List[int], map_version: int
                       ) -> None:
        with self._lock:
            self.partitions = sorted(partitions)
            self.map_version = map_version
            self.frozen -= set(self.partitions)

    def _set_partitions(self, req: msg.PsSetPartitionsRequest) -> None:
        self.set_partitions(req.partitions, req.map_version)

    def table(self, name: str) -> KvVariable:
        return self._tables[name]

    # -- helpers ---------------------------------------------------------

    def _check_version(self, version: int, keys: np.ndarray) -> None:
        if version >= 0 and version != self.map_version:
            raise StaleMapError(
                f"stale partition map: client v{version}, "
                f"ps v{self.map_version}"
            )
        if self.frozen:
            parts = set(np.unique(
                key_partition(keys, self.num_partitions)).tolist())
            hit = parts & self.frozen
            if hit:
                raise StaleMapError(
                    f"partitions {sorted(hit)} frozen for reshard"
                )

    def _count(self):
        self._qps_count += 1

    # -- data plane ------------------------------------------------------

    def _lookup(self, req: msg.PsLookupRequest) -> msg.PsLookupResponse:
        self._count()
        keys = req.keys.to_numpy()
        with self._lock:
            self._check_version(req.map_version, keys)
            vals = self._tables[req.table].gather(keys, train=req.train)
        return msg.PsLookupResponse(values=msg.Tensor.from_numpy(vals))

    def _fence_mask(self, req: msg.PsApplyRequest,
                    keys: np.ndarray) -> Optional[np.ndarray]:
        """Boolean keep-mask for a fenced apply (None = unfenced).
        Must hold the lock. Advances the per-partition fence for the
        partitions it admits."""
        if req.apply_seq < 0 or req.client_id < 0:
            return None
        if 0 <= req.epoch < self.fence_epoch:
            _STALE_EPOCH_REJECTS.inc(table=req.table)
            raise StaleEpochError(
                f"apply epoch {req.epoch} predates PS fence epoch "
                f"{self.fence_epoch} (post-restore zombie writer)"
            )
        keep = np.ones(keys.size, bool)
        for p, idx in group_by_partition(
            keys, self.num_partitions
        ).items():
            fence = self._part_seqs.setdefault(p, {})
            if req.apply_seq <= fence.get(req.client_id, -1):
                keep[idx] = False  # replayed duplicate for this cut
            else:
                fence[req.client_id] = req.apply_seq
        return keep

    def _apply(self, req: msg.PsApplyRequest) -> None:
        self._count()
        keys = req.keys.to_numpy()
        grads = req.grads.to_numpy()
        extra = {}
        if req.aux is not None:
            extra["hessian"] = req.aux.to_numpy()
        with self._lock:
            self._check_version(req.map_version, keys)
            keep = self._fence_mask(req, keys)
            if keep is not None and not keep.all():
                _FENCED_APPLIES.inc(
                    int((~keep).sum()), table=req.table
                )
                if not keep.any():
                    return
                keys, grads = keys[keep], grads[keep]
                if "hessian" in extra:
                    extra["hessian"] = extra["hessian"][keep]
            self._tables[req.table].apply_gradients(
                req.optimizer, keys, grads, req.step, lr=req.lr,
                **extra, **req.hyperparams,
            )

    # -- reshard / checkpoint -------------------------------------------

    def _dump_table(
        self, name: str, partitions: Optional[List[int]],
        since_version: int, include_slots: bool,
    ) -> msg.PsTableDump:
        table = self._tables[name]
        keys, values, freqs, versions = table.export(since_version)
        if partitions is not None:
            part_set = np.isin(
                key_partition(keys, self.num_partitions),
                np.asarray(partitions, np.int32),
            )
            keys, values = keys[part_set], values[part_set]
            freqs, versions = freqs[part_set], versions[part_set]
        dump = msg.PsTableDump(
            table=name,
            keys=msg.Tensor.from_numpy(keys),
            values=msg.Tensor.from_numpy(values),
            freqs=msg.Tensor.from_numpy(freqs),
            versions=msg.Tensor.from_numpy(versions),
            # Live moves must carry the replay fence with the rows:
            # without it the new owner would re-apply any replayed
            # (client, seq) the old owner had already absorbed.
            part_seqs={
                p: dict(self._part_seqs.get(p, {}))
                for p in (partitions if partitions is not None
                          else self.partitions)
            },
            fence_epoch=self.fence_epoch,
        )
        if include_slots:
            state = table.state_dict()
            for slot, (sk, sv) in state["slots"].items():
                if partitions is not None:
                    mask = np.isin(
                        key_partition(sk, self.num_partitions),
                        np.asarray(partitions, np.int32),
                    )
                    sk, sv = sk[mask], sv[mask]
                dump.slot_keys[slot] = msg.Tensor.from_numpy(sk)
                dump.slot_values[slot] = msg.Tensor.from_numpy(sv)
        return dump

    def _export(self, req: msg.PsExportRequest) -> msg.PsTableDump:
        with self._lock:
            return self._dump_table(
                req.table, req.partitions or None, req.since_version,
                req.include_slots,
            )

    def _import_dump(self, dump: msg.PsTableDump) -> int:
        table = self._tables[dump.table]
        keys = dump.keys.to_numpy()
        table.import_(
            keys,
            dump.values.to_numpy(),
            dump.freqs.to_numpy() if dump.freqs is not None else None,
            dump.versions.to_numpy() if dump.versions is not None else None,
        )
        for slot, sk in dump.slot_keys.items():
            sv = dump.slot_values[slot].to_numpy()
            sk = sk.to_numpy()
            table.import_slot(slot, sk, sv)
        for p, seqs in dump.part_seqs.items():
            fence = self._part_seqs.setdefault(int(p), {})
            for c, s in seqs.items():
                c = int(c)
                fence[c] = max(fence.get(c, -1), int(s))
        self.fence_epoch = max(self.fence_epoch, dump.fence_epoch)
        return keys.size

    def _import(self, req: msg.PsImportRequest) -> None:
        with self._lock:
            self._import_dump(req.dump)

    def _pull(self, req: msg.PsPullPartitionsRequest) -> None:
        """Pull partitions from another PS and import (master-directed
        move; the source froze them first)."""
        client = RpcClient(req.source_addr)
        try:
            for name in self._tables:
                dump = client.get(msg.PsExportRequest(
                    table=name, partitions=req.partitions,
                    since_version=0, include_slots=True,
                ))
                with self._lock:
                    n = self._import_dump(dump)
                logger.info(
                    "PS %d pulled %d rows of %s for partitions %s",
                    self.node_id, n, name, req.partitions,
                )
        finally:
            client.close()

    def _freeze(self, req: msg.PsFreezeRequest) -> None:
        with self._lock:
            if req.frozen:
                self.frozen |= set(req.partitions)
            else:
                self.frozen -= set(req.partitions)

    # -- stats / telemetry ----------------------------------------------

    def _stats(self, req: msg.PsStatsRequest) -> msg.PsStatsResponse:
        now = time.time()
        dt = max(now - self._qps_t0, 1e-6)
        qps = self._qps_count / dt
        self._qps_count = 0
        self._qps_t0 = now
        cpu = 0.0
        try:
            import psutil

            cpu = psutil.Process().cpu_percent(interval=None)
        except Exception:  # noqa: BLE001 — psutil optional
            pass
        with self._lock:
            tables = {n: len(t) for n, t in self._tables.items()}
            frozen = sorted(self.frozen)
        return msg.PsStatsResponse(
            ps_id=self.node_id, tables=tables, qps=qps,
            cpu_percent=cpu, frozen_partitions=frozen,
        )

    # -- checkpoint flush / restore -------------------------------------

    def _part_dir(self, table: str, partition: int) -> str:
        return f"{self.checkpoint_dir}/{table}/p{partition:04d}"

    def _fence_path(self, partition: int) -> str:
        return f"{self.checkpoint_dir}/_fence/p{partition:04d}.json"

    def _write_fences(self, step: int, epoch: int, hwm: Dict[str, int]
                      ) -> None:
        """Persist the replay fence of every owned partition alongside
        the delta files. Written on EVERY flush (not only barrier
        flushes): restore imports deltas up to the latest flush, so the
        fence must describe that same cut or replayed seqs between the
        last barrier and the last flush would double-apply."""
        import json

        for p in self.partitions:
            payload = {
                "epoch": epoch,
                "step": step,
                "hwm": dict(hwm or {}),
                # JSON object keys are strings; un-stringed on restore.
                "seqs": {
                    str(c): s
                    for c, s in self._part_seqs.get(p, {}).items()
                },
            }
            self.storage.write_bytes(
                json.dumps(payload).encode(), self._fence_path(p)
            )

    def _flush(self, req: msg.PsFlushRequest) -> msg.PsFlushResponse:
        """Delta-flush each owned partition to its own directory so any
        future owner can restore it (files are per-partition — that is
        what makes takeover after a PS death possible)."""
        import io

        flushed = 0
        with self._lock:
            for name, table in self._tables.items():
                since = self._flushed_version.get(name, 0)
                dump = self._dump_table(
                    name, self.partitions, since, include_slots=True)
                keys = dump.keys.to_numpy()
                if keys.size == 0:
                    continue
                parts = key_partition(keys, self.num_partitions)
                for p in np.unique(parts):
                    mask = parts == p
                    buf = io.BytesIO()
                    arrays = {
                        "keys": keys[mask],
                        "values": dump.values.to_numpy()[mask],
                        "freqs": dump.freqs.to_numpy()[mask],
                        "versions": dump.versions.to_numpy()[mask],
                    }
                    for slot, sk in dump.slot_keys.items():
                        sk_np = sk.to_numpy()
                        sv_np = dump.slot_values[slot].to_numpy()
                        smask = np.isin(sk_np, keys[mask])
                        arrays[f"slotk_{slot}"] = sk_np[smask]
                        arrays[f"slotv_{slot}"] = sv_np[smask]
                    np.savez(buf, **arrays)
                    self.storage.write_bytes(
                        buf.getvalue(),
                        f"{self._part_dir(name, int(p))}/"
                        f"{req.step:012d}.npz",
                    )
                    flushed += int(mask.sum())
                self._flushed_version[name] = req.step + 1
            if req.epoch >= 0:
                self.fence_epoch = max(self.fence_epoch, req.epoch)
            self._write_fences(req.step, self.fence_epoch, req.hwm)
        return msg.PsFlushResponse(
            flushed_rows=flushed, epoch=self.fence_epoch
        )

    def _restore(self, req: msg.PsRestoreRequest) -> None:
        """Import all delta files of the given partitions, oldest first
        (later flushes overwrite earlier rows on import)."""
        import io

        with self._lock:
            for name, table in self._tables.items():
                for p in req.partitions:
                    pdir = self._part_dir(name, p)
                    try:
                        files = sorted(
                            f for f in self.storage.listdir(pdir)
                            if f.endswith(".npz")
                        )
                    except (FileNotFoundError, OSError):
                        continue
                    for fname in files:
                        data = self.storage.read_bytes(f"{pdir}/{fname}")
                        arrays = np.load(io.BytesIO(data))
                        table.import_(
                            arrays["keys"], arrays["values"],
                            arrays["freqs"], arrays["versions"],
                        )
                        for arr_name in arrays.files:
                            if arr_name.startswith("slotk_"):
                                slot = arr_name[len("slotk_"):]
                                table.import_slot(
                                    slot, arrays[arr_name],
                                    arrays[f"slotv_{slot}"],
                                )
                    logger.info(
                        "PS %d restored partition %d of %s",
                        self.node_id, p, name,
                    )
            for p in req.partitions:
                self._restore_fence(p)

    def _restore_fence(self, partition: int) -> None:
        """Rewind the partition's replay fence to its fence-at-flush.
        Merging with max keeps the invariant that a seq the store has
        absorbed is never re-applied, while seqs lost with the dead
        node's RAM drop below the mark and are accepted on replay."""
        import json

        try:
            raw = self.storage.read_bytes(self._fence_path(partition))
        except (FileNotFoundError, OSError):
            return
        payload = json.loads(raw.decode())
        fence = self._part_seqs.setdefault(partition, {})
        for c, s in payload.get("seqs", {}).items():
            c = int(c)
            fence[c] = max(fence.get(c, -1), int(s))
        self.fence_epoch = max(
            self.fence_epoch, int(payload.get("epoch", -1))
        )
