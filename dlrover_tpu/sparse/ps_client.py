"""Worker-side client to the distributed embedding store.

The sparse analogue of the dense path's GSPMD sharding: keys are routed
to PS shards by the master-owned PartitionMap, requests fan out in
parallel, and a stale map (reshard in flight) is handled by refetch +
retry — no worker barrier needed (ref: dlrover sync_service.py solves
this with an explicit barrier; the version check subsumes it).

``embedding_lookup`` bridges lookups into jitted JAX programs with
``jax.pure_callback`` exactly like the single-host path
(sparse/kv_variable.py:embedding_lookup).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

import numpy as np

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import RpcClient, RpcError
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.sparse.partition import PartitionMap

logger = get_logger("ps_client")


class DistributedKvClient:
    """Routes lookups/updates for named embedding tables to PS shards.

    ``map_source``: callable returning the current PartitionMap (the
    master client's ``get_partition_map``, or a static map in tests).
    """

    def __init__(
        self,
        map_source,
        embedding_dims: Dict[str, int],
        max_retries: int = 12,
        retry_interval: float = 0.5,
        client_id: int = -1,
    ):
        # The default retry budget (backoff sleeps totalling ~39 s)
        # must comfortably exceed the PsManager liveness monitor's
        # worst-case detection latency (~10 s at its defaults): a
        # sparse op blocking on a dead PS has to still be retrying
        # when the rebalanced map is published.
        self._map_source = map_source
        self.embedding_dims = dict(embedding_dims)
        self.max_retries = max_retries
        self.retry_interval = retry_interval
        # Replay fence identity: with client_id >= 0 every apply is
        # stamped (epoch, client_id, apply_seq) so a post-failover
        # replay is deduped server-side instead of double-applied.
        # epoch is advanced by the trainer at each stream barrier.
        self.client_id = client_id
        self.epoch = -1
        self._apply_seq = -1
        self._map: Optional[PartitionMap] = None
        # Bumps whenever a refreshed map carries a new version — the
        # trainer watches it to know a rebalance/failover happened and
        # its post-barrier window must be replayed through the fence.
        self.map_changes = 0
        self._clients: Dict[str, RpcClient] = {}
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=16)

    # -- map / connections ----------------------------------------------

    def _refresh_map(self, force: bool = False) -> PartitionMap:
        with self._lock:
            if self._map is None or force:
                old = self._map.version if self._map else -1
                self._map = self._map_source()
                if self._map.version != old and old >= 0:
                    self.map_changes += 1
            return self._map

    def _client_for(self, addr: str) -> RpcClient:
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = RpcClient(addr)
                self._clients[addr] = c
            return c

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()

    # -- fan-out core ----------------------------------------------------

    def _fan_out(self, keys: np.ndarray, call):
        """Group flat ``keys`` by owning PS and run ``call(addr,
        version, sub_keys, idx)`` per shard in parallel.

        Success is tracked per key position: a shard whose call
        committed is never re-sent, so a retry after a mid-round PS
        death (stale map, rebalance in flight) only replays the keys
        that actually failed. This keeps ``apply_gradients`` — which
        is not idempotent — from double-applying updates on surviving
        partitions during failover.
        """
        last_err: Optional[Exception] = None
        pending = np.arange(keys.size)
        for attempt in range(self.max_retries):
            pmap = self._refresh_map(force=attempt > 0)
            groups = pmap.group_keys(keys[pending])
            futs = []
            for ps_id, local_idx in groups.items():
                idx = pending[local_idx]
                addr = pmap.ps_addrs.get(ps_id)
                if addr is None:
                    # Stays pending; a fresh map next attempt should
                    # route these keys to a live shard.
                    last_err = RpcError(f"no address for PS {ps_id}")
                    continue
                futs.append((idx, self._pool.submit(
                    call, addr, pmap.version, keys[idx], idx
                )))
            done = []
            for idx, f in futs:
                try:
                    f.result()
                    done.append(idx)
                except Exception as e:  # noqa: BLE001 — retried
                    last_err = e
            if done:
                pending = np.setdiff1d(
                    pending, np.concatenate(done), assume_unique=True
                )
            if pending.size == 0:
                return
            # A reshard is in flight or a PS died: wait for the master
            # to publish a new map, then retry the failed keys only.
            time.sleep(self.retry_interval * (1 + attempt))
        raise RpcError(
            f"sparse op failed after {self.max_retries} retries "
            f"({pending.size}/{keys.size} keys unapplied): {last_err}"
        )

    # -- API -------------------------------------------------------------

    def lookup(self, table: str, keys, train: bool = True) -> np.ndarray:
        keys = np.ascontiguousarray(keys, np.int64)
        flat = keys.ravel()
        dim = self.embedding_dims[table]
        out = np.zeros((flat.size, dim), np.float32)

        def call(addr, version, sub_keys, idx):
            resp = self._client_for(addr).get(msg.PsLookupRequest(
                table=table,
                keys=msg.Tensor.from_numpy(sub_keys),
                train=train,
                map_version=version,
            ))
            out[idx] = resp.values.to_numpy()

        self._fan_out(flat, call)
        return out.reshape(keys.shape + (dim,))

    def apply_gradients(
        self,
        table: str,
        keys,
        grads,
        step: int,
        optimizer: str = "adam",
        lr: float = 1e-3,
        hessian=None,
        apply_seq: Optional[int] = None,
        **hyperparams,
    ) -> int:
        """``hessian``: per-key auxiliary rows in the same layout as
        ``grads`` (adahessian's Hutchinson diagonal estimates); sliced
        per shard alongside the gradients.

        Returns the fence sequence number this apply was stamped with
        (-1 when unfenced). Pass ``apply_seq`` explicitly only when
        replaying a buffered apply after a failover — the original seq
        makes the replay idempotent against the PS fence."""
        keys = np.ascontiguousarray(keys, np.int64).ravel()
        dim = self.embedding_dims[table]
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            keys.size, dim
        )
        if hessian is not None:
            hessian = np.ascontiguousarray(
                hessian, np.float32
            ).reshape(keys.size, dim)
        if apply_seq is None:
            if self.client_id >= 0:
                self._apply_seq += 1
                apply_seq = self._apply_seq
            else:
                apply_seq = -1
        elif self.client_id >= 0:
            # Replays must never run ahead of fresh applies.
            self._apply_seq = max(self._apply_seq, apply_seq)

        def call(addr, version, sub_keys, idx):
            self._client_for(addr).get(msg.PsApplyRequest(
                table=table,
                optimizer=optimizer,
                keys=msg.Tensor.from_numpy(sub_keys),
                grads=msg.Tensor.from_numpy(grads[idx]),
                aux=(
                    msg.Tensor.from_numpy(hessian[idx])
                    if hessian is not None
                    else None
                ),
                step=step,
                lr=lr,
                hyperparams=dict(hyperparams),
                map_version=version,
                epoch=self.epoch,
                client_id=self.client_id,
                apply_seq=apply_seq,
            ))

        self._fan_out(keys, call)
        return apply_seq

    def table_size(self, table: str) -> int:
        """Total rows across reachable shards (stats fan-out; test/ops
        helper). A shard that died but has not been failed over yet is
        skipped — telemetry must not crash a loop that the sparse ops
        themselves would survive via their stale-map retries."""
        pmap = self._refresh_map(force=True)
        total = 0
        for ps_id in pmap.ps_ids():
            addr = pmap.ps_addrs.get(ps_id)
            if addr is None:
                continue
            try:
                stats = self._client_for(addr).get(
                    msg.PsStatsRequest()
                )
            except Exception:  # noqa: BLE001 — mid-failover shard
                continue
            total += stats.tables.get(table, 0)
        return total


def embedding_lookup(client: DistributedKvClient, table: str, keys,
                     train: bool = True):
    """JAX-visible distributed lookup, usable inside jit via
    pure_callback (same contract as the single-host
    kv_variable.embedding_lookup)."""
    import jax
    import jax.numpy as jnp

    keys = jnp.asarray(keys)
    dim = client.embedding_dims[table]
    out_shape = jax.ShapeDtypeStruct(keys.shape + (dim,), jnp.float32)

    def host_gather(k):
        return client.lookup(table, np.asarray(k), train=train)

    return jax.pure_callback(host_gather, out_shape, keys)
