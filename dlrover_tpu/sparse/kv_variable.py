"""KvVariable: Python API over the C++ host embedding store.

Parity with tfplus's Python surface (tfplus/python/ops/
kv_variable_ops.py ``get_kv_variable``, embedding_ops.py lookups,
python/training/*.py sparse optimizers) without TensorFlow: the store
is plain C++ behind ctypes (built on demand with g++, the same
just-in-time native build idea as atorch's op builder,
atorch/ops/op_builder/builder.py), and ``embedding_lookup`` bridges it
into jitted JAX programs with ``jax.pure_callback``.

Training flow (PS-style, host-resident sparse state):

    vals = embedding_lookup(kv, keys)        # inside jit, via callback
    ... dense math on TPU ...
    grads = jax.grad(...)                    # d loss / d vals
    kv.apply_gradients("adam", keys, grads, step)   # fused C++ apply
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Dict, Optional, Tuple

from dlrover_tpu.common.log import get_logger

logger = get_logger("kv_variable")

import numpy as np

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
    "kv_store.cc",
)
_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None


def _build_library() -> str:
    """Compile kv_store.cc to a cached .so keyed by source hash."""
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.path.join(
        os.getenv("DLROVER_TPU_CACHE", tempfile.gettempdir()),
        "dlrover_tpu_native",
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"kv_store_{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = so_path + f".build{os.getpid()}"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, _SRC,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, so_path)  # atomic vs concurrent builders
    return so_path


def _lib() -> ctypes.CDLL:
    global _LIB
    with _LIB_LOCK:
        if _LIB is None:
            lib = ctypes.CDLL(_build_library())
            lib.kv_create.restype = ctypes.c_void_p
            lib.kv_create.argtypes = [
                ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
                ctypes.c_float, ctypes.c_int,
            ]
            lib.kv_destroy.argtypes = [ctypes.c_void_p]
            lib.kv_size.restype = ctypes.c_int64
            lib.kv_size.argtypes = [ctypes.c_void_p]
            lib.kv_set_disk_tier.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ]
            lib.kv_set_disk_tier.restype = ctypes.c_int
            lib.kv_ram_size.argtypes = [ctypes.c_void_p]
            lib.kv_ram_size.restype = ctypes.c_int64
            lib.kv_disk_size.argtypes = [ctypes.c_void_p]
            lib.kv_disk_size.restype = ctypes.c_int64
            lib.kv_dim.restype = ctypes.c_int
            lib.kv_dim.argtypes = [ctypes.c_void_p]
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C")
            f32p = np.ctypeslib.ndpointer(np.float32, flags="C")
            u32p = np.ctypeslib.ndpointer(np.uint32, flags="C")
            lib.kv_gather_or_insert.argtypes = [
                ctypes.c_void_p, i64p, ctypes.c_int64, f32p,
            ]
            lib.kv_gather_or_zeros.argtypes = [
                ctypes.c_void_p, i64p, ctypes.c_int64, f32p,
            ]
            lib.kv_update.argtypes = [
                ctypes.c_void_p, i64p, ctypes.c_int64, f32p,
                ctypes.c_int64,
            ]
            lib.kv_evict.restype = ctypes.c_int64
            lib.kv_evict.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int64,
            ]
            lib.kv_export.restype = ctypes.c_int64
            lib.kv_export.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, i64p, f32p, u32p,
                i64p, ctypes.c_int64,
            ]
            lib.kv_import.argtypes = [
                ctypes.c_void_p, i64p, f32p, u32p, i64p,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_adagrad.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, i64p, f32p,
                ctypes.c_int64, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_adam.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_ftrl.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_momentum.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, i64p, f32p,
                ctypes.c_int64, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_sgd.argtypes = [
                ctypes.c_void_p, i64p, f32p, ctypes.c_int64,
                ctypes.c_float, ctypes.c_int64,
            ]
            lib.kv_sparse_apply_group_adam.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, i64p, f32p,
                ctypes.c_int64, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_int64,
            ]
            lib.kv_sparse_apply_group_ftrl.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_int64,
            ]
            lib.kv_sparse_apply_lamb.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_int64,
            ]
            lib.kv_sparse_apply_adabelief.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_amsgrad.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, i64p, f32p, ctypes.c_int64,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_int64,
            ]
            lib.kv_sparse_apply_radam.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_adadelta.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_int64,
            ]
            lib.kv_sparse_apply_adahessian.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_int64,
            ]
            lib.kv_sparse_apply_rmsprop.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_adamax.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_nadam.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_adadqh.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_group_adadqh.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, i64p, f32p, ctypes.c_int64,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_int64,
            ]
            lib.kv_sparse_apply_lamb_hessian.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                i64p, f32p, f32p, ctypes.c_int64, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_int64,
            ]
            lib.kv_sparse_apply_group_lamb_hessian.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, i64p, f32p, f32p,
                ctypes.c_int64, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_int64,
            ]
            _LIB = lib
    return _LIB


_INIT_RANDOM, _INIT_ZEROS, _INIT_CONST = 0, 1, 2


def _gather_or_zeros(lib, handle, keys: np.ndarray, dim: int):
    """Shared non-inserting gather: [n] int64 keys -> [n, dim] f32
    (zeros for absent keys) from any store handle."""
    keys = np.ascontiguousarray(keys, np.int64)
    out = np.empty((keys.size, dim), np.float32)
    lib.kv_gather_or_zeros(handle, keys, keys.size, out)
    return out


class _Store:
    """RAII over one C++ KvStore."""

    def __init__(self, dim, seed, shards, init_scale, init_mode):
        self._lib = _lib()
        self.dim = dim
        self._h = ctypes.c_void_p(
            self._lib.kv_create(dim, seed, shards, init_scale, init_mode)
        )

    def __del__(self):
        h, self._h = self._h, None
        if h:
            self._lib.kv_destroy(h)

    @property
    def handle(self):
        return self._h

    def __len__(self):
        return self._lib.kv_size(self._h)


class KvVariable:
    """Dynamically-growing embedding table keyed by int64 ids.

    (ref: get_kv_variable, tfplus python/ops/kv_variable_ops.py; the
    C++ store carries per-key frequency/version for eviction and
    incremental export, kv_variable.h.)
    """

    def __init__(
        self,
        name: str,
        embedding_dim: int,
        seed: int = 0,
        num_shards: int = 16,
        init_scale: float = 0.05,
        disk_tier_path: Optional[str] = None,
        max_ram_rows: int = 0,
    ):
        self.name = name
        self.embedding_dim = embedding_dim
        self._store = _Store(
            embedding_dim, seed, num_shards, init_scale, _INIT_RANDOM
        )
        # optimizer slot stores, created lazily per optimizer
        self._slots: Dict[str, _Store] = {}
        # which optimizer last wrote the slots (several families
        # share the "m"/"v" names with different semantics)
        self._last_optimizer: Optional[str] = None
        self._seed = seed
        self._num_shards = num_shards
        self._disk_tier_path = disk_tier_path
        self._max_ram_rows = max_ram_rows
        # Single-host replay fence: client_id -> highest apply_seq
        # absorbed (the in-process analogue of PsServer._part_seqs —
        # one mark per client, since there is no partition movement
        # on this path). Fenced applies at or below the mark are
        # replayed duplicates and no-op.
        self._fence_seqs: Dict[int, int] = {}
        if disk_tier_path and max_ram_rows > 0:
            self.enable_disk_tier(disk_tier_path, max_ram_rows)

    def enable_disk_tier(self, path: str, max_ram_rows: int) -> None:
        """Hybrid storage (ref tfplus hybrid_embedding/): keep at most
        ``max_ram_rows`` rows resident; the coldest (lowest
        frequency, oldest version) spill to ``path`` and promote back
        on access. Checkpoints/export cover both tiers. Optimizer
        slot stores stay RAM-only (their rows are touched exactly
        when the param row is — spilling them separately would double
        the IO for no memory win on the hot path)."""
        if max_ram_rows < self._num_shards:
            # budget granularity is per shard with a floor of one
            # resident row, so the effective cap is num_shards
            logger.warning(
                "max_ram_rows=%d < num_shards=%d: effective resident "
                "cap is %d",
                max_ram_rows, self._num_shards, self._num_shards,
            )
        rc = self._store._lib.kv_set_disk_tier(
            self._store.handle, path.encode(), max_ram_rows
        )
        if rc != 0:
            raise OSError(
                f"cannot enable disk tier at {path!r} (already "
                "enabled, or file not writable)"
            )

    def ram_rows(self) -> int:
        return self._store._lib.kv_ram_size(self._store.handle)

    def disk_rows(self) -> int:
        return self._store._lib.kv_disk_size(self._store.handle)

    def __len__(self) -> int:
        return len(self._store)

    # -- lookup -------------------------------------------------------------

    def gather(self, keys: np.ndarray, train: bool = True) -> np.ndarray:
        """[n] int64 -> [n, dim] f32. train=True inserts missing keys
        (GatherOrInsert); train=False returns zeros (GatherOrZeros)."""
        keys = np.ascontiguousarray(keys, np.int64)
        if train:
            out = np.empty(
                (keys.size, self.embedding_dim), np.float32
            )
            self._store._lib.kv_gather_or_insert(
                self._store.handle, keys.ravel(), keys.size, out
            )
        else:
            out = _gather_or_zeros(
                self._store._lib, self._store.handle, keys.ravel(),
                self.embedding_dim,
            )
        return out.reshape(keys.shape + (self.embedding_dim,))

    def assign(self, keys: np.ndarray, values: np.ndarray, step: int = 0):
        keys = np.ascontiguousarray(keys, np.int64).ravel()
        values = np.ascontiguousarray(values, np.float32).reshape(
            keys.size, self.embedding_dim
        )
        self._store._lib.kv_update(
            self._store.handle, keys, keys.size, values, step
        )

    # -- optimizer slots ----------------------------------------------------

    def _slot(self, slot_name: str, init_mode=_INIT_ZEROS, init=0.0):
        if slot_name not in self._slots:
            self._slots[slot_name] = _Store(
                self.embedding_dim,
                self._seed + hash(slot_name) % 1000,
                self._num_shards,
                init,
                init_mode,
            )
        return self._slots[slot_name]

    def gather_slot(self, slot_name: str, keys) -> np.ndarray:
        """[n] int64 -> [n, dim] f32 rows of an optimizer slot store
        (zeros for keys the optimizer has not touched). Raises on a
        slot name no optimizer has created — silent zeros would mask
        typos."""
        if slot_name not in self._slots:
            if not self._slots:
                # no optimizer ran yet: every slot is all-zeros
                return np.zeros(
                    (np.asarray(keys).size, self.embedding_dim),
                    np.float32,
                )
            raise KeyError(
                f"unknown slot {slot_name!r}; existing: "
                f"{sorted(self._slots)}"
            )
        store = self._slots[slot_name]
        keys = np.ascontiguousarray(keys, np.int64).ravel()
        return _gather_or_zeros(
            store._lib, store.handle, keys, self.embedding_dim
        )

    def adadqh_hypergradients(
        self,
        keys,
        lr: float,
        step: int,
        eps: float = 1e-5,
        beta1: float = 0.9,
        beta2: float = 0.999,
    ):
        """Per-row (lr_hg, eps_hg) for keys trained with the
        ``adadqh`` family — the sparse surface of the reference's
        KvVariableComputeAdaDQHHG op (tfplus
        kv_variable/ops/training_ops.cc), built from the m/v slot
        rows and the dense hypergradient math
        (optim/adadqh.py adadqh_hypergradients, finite-diff tested).

        Refuses tables whose slots were written by a different
        optimizer: adam/lamb/... also keep "m"/"v" slots, but their v
        tracks raw-gradient moments, not AdaDQH's gradient-difference
        curvature — hypergradients computed from them would be
        numerically plausible and semantically wrong."""
        if self._last_optimizer not in (
            None, "adadqh", "group_adadqh"
        ):
            raise ValueError(
                "adadqh_hypergradients needs adadqh-family slots; "
                f"this table was last trained with "
                f"{self._last_optimizer!r}"
            )
        from dlrover_tpu.optim import adadqh_hypergradients

        m = self.gather_slot("m", keys)
        v = self.gather_slot("v", keys)
        lr_hg, eps_hg = adadqh_hypergradients(
            m, v, lr, eps, beta1, beta2, step
        )
        return np.asarray(lr_hg), np.asarray(eps_hg)

    def _hessian_rows(self, kw, optimizer, keys, ukeys, inv):
        """Validate and dedupe trainer-supplied Hutchinson Hessian-
        diagonal rows (same [n, dim] layout and duplicate-key
        combining as the gradients) for the curvature optimizers
        (adahessian, lamb_hessian, group_lamb_hessian)."""
        hessian = kw.get("hessian")
        if hessian is None:
            raise ValueError(
                f"{optimizer} requires hessian= rows aligned with "
                "keys (Hutchinson diagonal estimates)"
            )
        hessian = np.ascontiguousarray(hessian, np.float32).reshape(
            keys.size, self.embedding_dim
        )
        uhess = np.zeros((ukeys.size, self.embedding_dim), np.float32)
        np.add.at(uhess, inv, hessian)
        return uhess

    def apply_gradients(
        self,
        optimizer: str,
        keys: np.ndarray,
        grads: np.ndarray,
        step: int,
        lr: float = 1e-3,
        client_id: int = -1,
        apply_seq: int = -1,
        **kw,
    ) -> None:
        """Fused sparse apply. Duplicate keys are combined first (sum)
        — the reference's kernels expect deduplicated ids too.

        ``(client_id, apply_seq)`` with both >= 0 engages the replay
        fence: a seq at or below this client's mark is a replayed
        duplicate and becomes a no-op instead of a double-apply."""
        if client_id >= 0 and apply_seq >= 0:
            if apply_seq <= self._fence_seqs.get(client_id, -1):
                return
            self._fence_seqs[client_id] = apply_seq
        keys = np.ascontiguousarray(keys, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            keys.size, self.embedding_dim
        )
        ukeys, inv = np.unique(keys, return_inverse=True)
        ugrads = np.zeros((ukeys.size, self.embedding_dim), np.float32)
        np.add.at(ugrads, inv, grads)

        self._last_optimizer = optimizer
        lib = self._store._lib
        h = self._store.handle
        if optimizer == "adam":
            lib.kv_sparse_apply_adam(
                h,
                self._slot("m").handle,
                self._slot("v").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-8), max(step, 1),
            )
        elif optimizer == "adagrad":
            lib.kv_sparse_apply_adagrad(
                h,
                self._slot("accum").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("eps", 1e-10), step,
            )
        elif optimizer == "ftrl":
            # TF/tfplus convention: lr_power <= 0 (typically -0.5); the
            # C++ kernel computes pow(accum, -lr_power), so a positive
            # value would grow the step as the accumulator grows
            # (ref: tfplus kv_variable/kernels/training_ops.cc Ftrl
            # validation).
            lr_power = kw.get("lr_power", -0.5)
            if lr_power > 0:
                raise ValueError(
                    f"ftrl lr_power must be <= 0, got {lr_power}"
                )
            lib.kv_sparse_apply_ftrl(
                h,
                self._slot(
                    "accum_ftrl", _INIT_CONST,
                    kw.get("initial_accumulator", 0.1),
                ).handle,
                self._slot("linear").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("l1", 0.0), kw.get("l2", 0.0),
                lr_power, step,
            )
        elif optimizer == "momentum":
            lib.kv_sparse_apply_momentum(
                h,
                self._slot("momentum").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("momentum", 0.9), step,
            )
        elif optimizer in ("sgd", "gradient_descent"):
            # ref: tfplus python/training/gradient_descent.py — the
            # slot-free baseline of the fused-apply family.
            lib.kv_sparse_apply_sgd(
                h, ukeys, ugrads, ukeys.size, lr, step
            )
        elif optimizer == "group_adam":
            # Adam + group lasso (ref tfplus group_adam.py /
            # training_ops.cc:1065): rows whose L21-shrunk linear norm
            # drops below l21*sqrt(dim) collapse to exact zeros.
            lib.kv_sparse_apply_group_adam(
                h,
                self._slot("accum_ga").handle,
                self._slot("linear_ga").handle,
                self._slot("m").handle,
                self._slot("v").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-8), kw.get("l1", 0.0),
                kw.get("l2", 0.0), kw.get("l21", 0.0), max(step, 1),
            )
        elif optimizer == "group_ftrl":
            lr_power = kw.get("lr_power", -0.5)
            if lr_power > 0:
                raise ValueError(
                    f"ftrl lr_power must be <= 0, got {lr_power}"
                )
            lib.kv_sparse_apply_group_ftrl(
                h,
                self._slot(
                    "accum_ftrl", _INIT_CONST,
                    kw.get("initial_accumulator", 0.1),
                ).handle,
                self._slot("linear").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("l1", 0.0), kw.get("l2", 0.0),
                kw.get("l21", 0.0), lr_power,
                kw.get("l2_shrinkage", 0.0), step,
            )
        elif optimizer == "lamb":
            lib.kv_sparse_apply_lamb(
                h,
                self._slot("m").handle,
                self._slot("v").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-6),
                kw.get("weight_decay", 0.0), max(step, 1),
            )
        elif optimizer == "adabelief":
            lib.kv_sparse_apply_adabelief(
                h,
                self._slot("m").handle,
                self._slot("s").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-16), max(step, 1),
            )
        elif optimizer == "amsgrad":
            lib.kv_sparse_apply_amsgrad(
                h,
                self._slot("m").handle,
                self._slot("v").handle,
                self._slot("vhat").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-8), max(step, 1),
            )
        elif optimizer == "radam":
            lib.kv_sparse_apply_radam(
                h,
                self._slot("m").handle,
                self._slot("v").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-8), max(step, 1),
            )
        elif optimizer == "adadelta":
            lib.kv_sparse_apply_adadelta(
                h,
                self._slot("accum_ad").handle,
                self._slot("accum_update").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("rho", 0.95), kw.get("eps", 1e-6), step,
            )
        elif optimizer == "adahessian":
            uhess = self._hessian_rows(kw, optimizer, keys, ukeys, inv)
            lib.kv_sparse_apply_adahessian(
                h,
                self._slot("m").handle,
                self._slot("v").handle,
                ukeys, ugrads, uhess, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-8),
                kw.get("hessian_power", 1.0), max(step, 1),
            )
        elif optimizer == "rmsprop":
            momentum = kw.get("momentum", 0.0)
            lib.kv_sparse_apply_rmsprop(
                h,
                self._slot("ms").handle,
                # Plain RMSProp keeps a single accumulator: don't
                # allocate a momentum table nobody reads.
                self._slot("mom_rms").handle if momentum else None,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("rho", 0.9), momentum,
                kw.get("eps", 1e-7), step,
            )
        elif optimizer == "adamax":
            lib.kv_sparse_apply_adamax(
                h,
                self._slot("m").handle,
                self._slot("u_inf").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-8), max(step, 1),
            )
        elif optimizer == "nadam":
            lib.kv_sparse_apply_nadam(
                h,
                self._slot("m").handle,
                self._slot("v").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-8), max(step, 1),
            )
        elif optimizer == "adadqh":
            # Ant's quasi-Hessian family (published as AGD; dense twin
            # optim/agd.py, ref tfplus ApplyAdaDQH registrations).
            lib.kv_sparse_apply_adadqh(
                h,
                self._slot("m").handle,
                self._slot("v").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-5), max(step, 1),
            )
        elif optimizer == "group_adadqh":
            # AdaDQH + group lasso (ref
            # KvVariableGroupSparseApplyAdaDQHV2): l1/l2/l21 in loss
            # units, scaled by lr inside the kernel (V2 convention).
            lib.kv_sparse_apply_group_adadqh(
                h,
                self._slot("linear_dqh").handle,
                self._slot("m").handle,
                self._slot("v").handle,
                ukeys, ugrads, ukeys.size,
                lr, kw.get("beta1", 0.9), kw.get("beta2", 0.999),
                kw.get("eps", 1e-5), kw.get("l1", 0.0),
                kw.get("l2", 0.0), kw.get("l21", 0.0), max(step, 1),
            )
        elif optimizer in ("lamb_hessian", "group_lamb_hessian"):
            # LAMB trust ratio with a curvature-driven second moment:
            # needs the same trainer-supplied Hutchinson rows as
            # adahessian.
            uhess = self._hessian_rows(kw, optimizer, keys, ukeys, inv)
            if optimizer == "lamb_hessian":
                lib.kv_sparse_apply_lamb_hessian(
                    h,
                    self._slot("m").handle,
                    self._slot("v").handle,
                    ukeys, ugrads, uhess, ukeys.size,
                    lr, kw.get("beta1", 0.9),
                    kw.get("beta2", 0.999),
                    kw.get("eps", 1e-6), max(step, 1),
                )
            else:
                lib.kv_sparse_apply_group_lamb_hessian(
                    h,
                    self._slot("accum_lh").handle,
                    self._slot("linear_lh").handle,
                    self._slot("m").handle,
                    self._slot("v").handle,
                    ukeys, ugrads, uhess, ukeys.size,
                    lr, kw.get("beta1", 0.9),
                    kw.get("beta2", 0.999),
                    kw.get("eps", 1e-6), kw.get("l1", 0.0),
                    kw.get("l2", 0.0), kw.get("l21", 0.0),
                    max(step, 1),
                )
        else:
            raise ValueError(f"unknown sparse optimizer {optimizer!r}")

    # -- eviction (under/over-flow policies) --------------------------------

    def evict(
        self, min_frequency: int = 0, min_version: int = 0
    ) -> int:
        return self._store._lib.kv_evict(
            self._store.handle, min_frequency, min_version
        )

    # -- checkpoint ---------------------------------------------------------

    def export(
        self, since_version: int = 0
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """(keys, values, freqs, versions); since_version>0 = delta
        export of rows touched at/after that step."""
        lib = self._store._lib
        h = self._store.handle
        cap = len(self._store)
        keys = np.empty(max(cap, 1), np.int64)
        values = np.empty((max(cap, 1), self.embedding_dim), np.float32)
        freqs = np.empty(max(cap, 1), np.uint32)
        versions = np.empty(max(cap, 1), np.int64)
        n = lib.kv_export(
            h, since_version, keys, values, freqs, versions, cap
        )
        if n > cap:  # store grew between size() and export
            cap = int(n)
            keys = np.empty(cap, np.int64)
            values = np.empty((cap, self.embedding_dim), np.float32)
            freqs = np.empty(cap, np.uint32)
            versions = np.empty(cap, np.int64)
            n = lib.kv_export(
                h, since_version, keys, values, freqs, versions, cap
            )
        n = int(n)
        return keys[:n], values[:n], freqs[:n], versions[:n]

    def import_(self, keys, values, freqs=None, versions=None) -> None:
        keys = np.ascontiguousarray(keys, np.int64)
        values = np.ascontiguousarray(values, np.float32)
        n = keys.size
        freqs = (
            np.ascontiguousarray(freqs, np.uint32)
            if freqs is not None
            else np.zeros(n, np.uint32)
        )
        versions = (
            np.ascontiguousarray(versions, np.int64)
            if versions is not None
            else np.zeros(n, np.int64)
        )
        self._store._lib.kv_import(
            self._store.handle, keys, values, freqs, versions, n
        )

    def state_dict(self) -> dict:
        keys, values, freqs, versions = self.export()
        slots = {}
        for name, store in self._slots.items():
            cap = len(store)
            sk = np.empty(max(cap, 1), np.int64)
            sv = np.empty((max(cap, 1), self.embedding_dim), np.float32)
            sf = np.empty(max(cap, 1), np.uint32)
            sver = np.empty(max(cap, 1), np.int64)
            n = int(
                store._lib.kv_export(
                    store.handle, 0, sk, sv, sf, sver, cap
                )
            )
            slots[name] = (sk[:n], sv[:n])
        return {
            "keys": keys,
            "values": values,
            "freqs": freqs,
            "versions": versions,
            "slots": slots,
        }

    def import_slot(self, name: str, keys, values) -> None:
        """Import optimizer-slot rows (checkpoint restore / PS move).
        Recreates the slot store with matching init semantics."""
        keys = np.ascontiguousarray(keys, np.int64)
        values = np.ascontiguousarray(values, np.float32)
        mode = _INIT_CONST if name == "accum_ftrl" else _INIT_ZEROS
        slot = self._slot(name, mode, 0.1 if mode == _INIT_CONST else 0.0)
        slot._lib.kv_update(
            slot.handle, keys, keys.size,
            values.reshape(keys.size, self.embedding_dim), 0,
        )

    def load_state_dict(self, state: dict) -> None:
        self.import_(
            state["keys"], state["values"], state.get("freqs"),
            state.get("versions"),
        )
        for name, (sk, sv) in state.get("slots", {}).items():
            self.import_slot(name, sk, sv)


class SparseOptimizer:
    """Convenience: one object applying the same rule to many
    KvVariables. Rules: sgd (alias gradient_descent) | adam |
    adagrad | ftrl | momentum | lamb | adabelief | amsgrad | radam |
    adadelta | adahessian | rmsprop | adamax | nadam | group_adam |
    group_ftrl — the group_* variants carry the reference's
    group-lasso L21 row sparsification
    (tfplus python/training/group_adam.py, sparse_group_ftrl.py;
    kernels in native/kv_store.cc)."""

    def __init__(self, optimizer: str = "adam", lr: float = 1e-3, **kw):
        self.optimizer = optimizer
        self.lr = lr
        self.kw = kw

    def apply(
        self,
        grads_by_var: Dict[KvVariable, Tuple[np.ndarray, np.ndarray]],
        step: int,
    ) -> None:
        for var, (keys, grads) in grads_by_var.items():
            var.apply_gradients(
                self.optimizer, keys, grads, step, lr=self.lr, **self.kw
            )


def embedding_lookup(kv: KvVariable, keys, train: bool = True):
    """JAX-visible lookup: usable inside jit via pure_callback.

    Returns f32 [batch..., dim]. Differentiable in the sense that the
    cotangent w.r.t. the *gathered values* flows out of jax.grad; feed
    it to ``kv.apply_gradients``. (The table itself is host state, not
    a traced array — by design, see module docstring.)
    """
    import jax
    import jax.numpy as jnp

    keys = jnp.asarray(keys)
    out_shape = jax.ShapeDtypeStruct(
        keys.shape + (kv.embedding_dim,), jnp.float32
    )

    def host_gather(k):
        return kv.gather(np.asarray(k), train=train)

    return jax.pure_callback(host_gather, out_shape, keys)
