"""dlrover-tpu: a TPU-native elastic distributed deep-learning framework.

A ground-up redesign of the capabilities of DLRover (elastic/fault-tolerant
training orchestration + auto-acceleration + sparse embedding) for TPU
hardware: JAX/XLA for compute, GSPMD meshes for parallelism, Pallas for
custom kernels, and a gRPC control plane for elasticity.

Layering (bottom-up):
  common/      shared primitives: config, node model, typed RPC messages, IPC
  master/      per-job master: rendezvous, data sharding, scaling, monitors
  agent/       per-host agent: process supervision, checkpoint persistence
  trainer/     in-process APIs: run CLI, ElasticTrainer, samplers
  parallel/    mesh/axis fabric, sharding rules, ring attention
  models/      flagship model families (GPT, Llama, MoE)
  ops/         Pallas TPU kernels (flash attention, quantization)
  optimizers/  AGD, WSAM, low-bit optimizer states (optax transforms)
  auto/        auto_accelerate strategy engine
  checkpoint/  flash checkpoint (shm staging + async persistence)
"""

__version__ = "0.1.0"
