"""Process-local metrics registry with Prometheus text exposition.

Counters, gauges, and histograms with labels, kept in plain dicts
behind a lock and rendered on demand into the Prometheus text format
(version 0.0.4) — the subset ``prometheus_client`` would produce, with
no dependency on it. ``MetricsRegistry.render()`` works without any
server, so tests stay hermetic; the master additionally serves it over
HTTP (obs/exposition.py) and over the control-plane RPC
(``MetricsRequest``).

Semantics follow the Prometheus client-library guidelines:

* a metric name is registered once with a fixed type and label names;
  re-requesting the same name returns the same object, and a
  conflicting re-registration raises.
* label values select a child series; unlabeled metrics have a single
  implicit series.
* histogram buckets are cumulative and always end with ``+Inf``;
  ``_sum`` and ``_count`` series accompany them.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Tuple

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0,
)


def _escape_label_value(value: str) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace("\n", r"\n")
        .replace('"', r"\"")
    )


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Shared label-handling for all three metric types."""

    type_name = ""

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...]):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.labelnames)}"
            )
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _series_name(self, key: Tuple[str, ...], suffix: str = "",
                     extra: str = "") -> str:
        pairs = [
            f'{ln}="{_escape_label_value(lv)}"'
            for ln, lv in zip(self.labelnames, key)
        ]
        if extra:
            pairs.append(extra)
        label_str = "{" + ",".join(pairs) + "}" if pairs else ""
        return f"{self.name}{suffix}{label_str}"

    def render(self) -> List[str]:
        raise NotImplementedError

    def dump(self) -> dict:
        raise NotImplementedError

    def remove(self, **labels) -> None:
        """Drop one labeled series (departed host, retired node)."""
        key = self._key(labels)
        store = getattr(self, "_values", None)
        if store is None:
            store = getattr(self, "_series")
        with self._lock:
            store.pop(key, None)


class Counter(_Metric):
    type_name = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]
        return [
            f"{self._series_name(k)} {_format_value(v)}"
            for k, v in items
        ]

    def dump(self) -> dict:
        with self._lock:
            series = [[list(k), v] for k, v in self._values.items()]
        return {
            "type": self.type_name,
            "help": self.help,
            "labelnames": list(self.labelnames),
            "series": series,
        }


class Gauge(_Metric):
    type_name = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]
        return [
            f"{self._series_name(k)} {_format_value(v)}"
            for k, v in items
        ]

    dump = Counter.dump


class Histogram(_Metric):
    type_name = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        bounds = sorted(float(b) for b in buckets)
        if not bounds or bounds[-1] != math.inf:
            bounds.append(math.inf)
        self.buckets = tuple(bounds)
        # key -> (per-bucket counts, sum, count)
        self._series: Dict[Tuple[str, ...], list] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        value = float(value)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = [[0] * len(self.buckets), 0.0, 0]
                self._series[key] = series
            counts, _, _ = series
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
            series[1] += value
            series[2] += 1

    def count(self, **labels) -> int:
        with self._lock:
            series = self._series.get(self._key(labels))
            return series[2] if series else 0

    def sum(self, **labels) -> float:
        with self._lock:
            series = self._series.get(self._key(labels))
            return series[1] if series else 0.0

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(
                (k, (list(c), s, n))
                for k, (c, s, n) in self._series.items()
            )
        lines: List[str] = []
        for key, (counts, total, n) in items:
            for bound, c in zip(self.buckets, counts):
                le = f'le="{_format_value(bound)}"'
                lines.append(
                    f"{self._series_name(key, '_bucket', le)} {c}"
                )
            lines.append(
                f"{self._series_name(key, '_sum')} "
                f"{_format_value(total)}"
            )
            lines.append(f"{self._series_name(key, '_count')} {n}")
        return lines

    def dump(self) -> dict:
        with self._lock:
            series = [
                [list(k), list(c), s, n]
                for k, (c, s, n) in self._series.items()
            ]
        return {
            "type": self.type_name,
            "help": self.help,
            "labelnames": list(self.labelnames),
            # +Inf is implied by the renderer; keep the dump msgpack-
            # friendly (inf is not representable in JSON either).
            "buckets": [b for b in self.buckets if b != math.inf],
            "series": series,
        }


class MetricsRegistry:
    """Holds named metrics; the factory methods are idempotent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        # Collectors append extra exposition lines at render time
        # (e.g. the master's FleetAggregator rendering host-labeled
        # series from agent snapshots). A collector returns a list of
        # text lines; a raising collector is skipped, never fatal.
        self._collectors: List = []

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or tuple(
                    labelnames
                ) != existing.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.type_name} with labels "
                        f"{existing.labelnames}"
                    )
                if "buckets" in kw:
                    bounds = sorted(float(b) for b in kw["buckets"])
                    if not bounds or bounds[-1] != math.inf:
                        bounds.append(math.inf)
                    if tuple(bounds) != existing.buckets:
                        raise ValueError(
                            f"histogram {name!r} already registered "
                            f"with buckets {existing.buckets}"
                        )
                return existing
            metric = cls(name, help, tuple(labelnames), **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self, name, help="", labelnames=(), buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def add_collector(self, fn) -> None:
        """Register a callable returning extra exposition lines."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def remove_collector(self, fn) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def dump(self) -> Dict[str, dict]:
        """Serializable snapshot of every metric (msgpack/JSON-able):
        ``{name: {type, help, labelnames, series, [buckets]}}`` — the
        payload an agent ships to the master's FleetAggregator."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m.dump() for m in metrics}

    def render(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        with self._lock:
            metrics = sorted(
                self._metrics.values(), key=lambda m: m.name
            )
            collectors = list(self._collectors)
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.type_name}")
            lines.extend(m.render())
        for fn in collectors:
            try:
                lines.extend(fn())
            except Exception:  # noqa: BLE001 — a broken collector
                # must never take the /metrics endpoint down.
                pass
        return "\n".join(lines) + "\n"


# The process-wide default registry every layer instruments into.
_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _DEFAULT


def counter(name, help="", labelnames=()) -> Counter:
    return _DEFAULT.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    return _DEFAULT.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(),
              buckets=DEFAULT_BUCKETS) -> Histogram:
    return _DEFAULT.histogram(name, help, labelnames, buckets)
