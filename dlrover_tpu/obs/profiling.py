"""Performance observability: step phases, compile accounting, MFU.

The control plane can observe everything about a job *except* where
its time goes; this module closes that gap for the training hot path:

* :class:`StepPhaseProfiler` attributes every step's wall time into
  five exhaustive phases — ``data_wait`` (blocking on the input
  pipeline's host side), ``h2d_stage`` (the host->device staging
  slice of the input wait), ``compile`` (dispatches that traced +
  XLA-compiled), ``dispatch`` (host-side enqueue of an
  already-compiled step), and ``device_execute`` (the residual: the
  device working while the host runs ahead) — into
  ``dlrover_step_phase_seconds_total{phase}``.
  The clock is injectable, so attribution is testable hermetically.
* :class:`CompileTracker` counts (re)compilations per jitted function
  via its dispatch-cache size (``dlrover_compile_total{fn}`` /
  ``dlrover_compile_seconds_total{fn}``): a shape drift that silently
  retraces every step shows up as a counter slope, not a mystery.
* :class:`MfuMeter` turns XLA's own cost model
  (``jit(f).lower(*args).cost_analysis()`` — trace+lower only, never
  a second XLA compile) plus measured step time into a live
  ``dlrover_train_mfu`` gauge (and ``dlrover_train_flops_per_step``).
* The **PROFILE action** file protocol: the master pushes a
  ``profile`` heartbeat action (straggler auto-trigger or operator
  RPC), the agent drops a request file, the trainer's profiler picks
  it up between steps, captures an N-step phase breakdown (plus an
  optional ``jax.profiler`` trace), and writes a digest file the
  agent ships back over the existing ``DiagnosticsReport`` channel.

Everything here is stdlib-only except the two lazily-imported jax
touchpoints (FLOPs derivation, optional profiler trace), so the phase
accounting and the capture protocol stay hermetically testable.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs.beacon import ProgressBeacon, default_beacon
from dlrover_tpu.obs.metrics import counter, gauge
from dlrover_tpu.obs.tracer import event as obs_event

logger = get_logger("profiling")

# The exhaustive per-step wall-time phases, in attribution precedence.
# ``data_wait`` is host-side input wait (pulling/collating the next
# batch); ``h2d_stage`` is the host->device staging slice of that wait
# (the split makes a device-prefetch win attributable: a healthy
# device-resident pipeline drives BOTH toward zero, while a hidden H2D
# stall shows up as h2d_stage specifically).
PHASES = ("data_wait", "h2d_stage", "compile", "dispatch", "device_execute")

PROFILE_REQUEST_ENV = "DLROVER_TPU_PROFILE_REQUEST_FILE"
PROFILE_DIGEST_ENV = "DLROVER_TPU_PROFILE_DIGEST_FILE"
PROFILE_STEPS_ENV = "DLROVER_TPU_PROFILE_STEPS"
PROFILE_TRACE_DIR_ENV = "DLROVER_TPU_PROFILE_TRACE_DIR"
PEAK_TFLOPS_ENV = "DLROVER_TPU_PEAK_TFLOPS"
MFU_ENV = "DLROVER_TPU_MFU"

DEFAULT_PROFILE_STEPS = 20

_PHASE_SECONDS = counter(
    "dlrover_step_phase_seconds_total",
    "Training wall time attributed by step phase (data_wait / "
    "h2d_stage / compile / dispatch / device_execute); the five "
    "phases partition each step's wall time exactly — data_wait is "
    "host-side input wait, h2d_stage the host->device staging slice "
    "of it",
    ("phase",),
)
_COMPILE_TOTAL = counter(
    "dlrover_compile_total",
    "XLA (re)compilations observed per jitted function",
    ("fn",),
)
_COMPILE_SECONDS = counter(
    "dlrover_compile_seconds_total",
    "Wall seconds spent in dispatches that traced + compiled, per "
    "jitted function",
    ("fn",),
)
_MFU = gauge(
    "dlrover_train_mfu",
    "Live model FLOPs utilisation: cost-analysis FLOPs per step over "
    "measured step time, vs the chip's peak (windowed mean)",
)
_FLOPS_PER_STEP = gauge(
    "dlrover_train_flops_per_step",
    "FLOPs one optimizer step costs per XLA cost analysis",
)
_PROFILE_CAPTURES = counter(
    "dlrover_profile_captures_total",
    "On-demand PROFILE captures completed by this trainer",
)


def _job_scoped(name: str) -> str:
    job = os.getenv("DLROVER_TPU_JOB_NAME", "default")
    return f"/tmp/dlrover_tpu_{name}_{job}.json"


def profile_request_file() -> str:
    """Agent -> trainer: where a PROFILE request is dropped. Job-
    scoped (two jobs on one host must not trigger each other)."""
    return os.getenv(PROFILE_REQUEST_ENV, _job_scoped("profile_request"))


def profile_digest_file() -> str:
    """Trainer -> agent: where the capture digest lands."""
    return os.getenv(PROFILE_DIGEST_ENV, _job_scoped("profile_digest"))


_request_counter = [0]
_request_lock = threading.Lock()


def write_profile_request(
    steps: int = 0, trace_dir: str = "", path: Optional[str] = None
) -> str:
    """Drop a PROFILE request for the co-hosted trainer; returns the
    request id the digest will echo. Atomic (tmp+rename) so the
    trainer never reads a torn request."""
    with _request_lock:
        _request_counter[0] += 1
        seq = _request_counter[0]
    req_id = f"{os.getpid()}-{int(time.time() * 1000)}-{seq}"
    req = {
        "id": req_id,
        "steps": int(
            steps
            or os.getenv(PROFILE_STEPS_ENV, str(DEFAULT_PROFILE_STEPS))
        ),
        "trace_dir": trace_dir or os.getenv(PROFILE_TRACE_DIR_ENV, ""),
        "ts": time.time(),
    }
    path = path or profile_request_file()
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(req, f)
    os.replace(tmp, path)
    return req_id


def read_profile_digest(
    expect_id: Optional[str] = None, path: Optional[str] = None
) -> Optional[dict]:
    """The digest the trainer wrote, or None when absent / not yet the
    one answering ``expect_id``."""
    path = path or profile_digest_file()
    try:
        with open(path) as f:
            digest = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(digest, dict):
        return None
    if expect_id is not None and digest.get("id") != expect_id:
        return None
    return digest


def peak_flops_per_s() -> float:
    """The chip's peak FLOP/s for the MFU denominator.

    ``DLROVER_TPU_PEAK_TFLOPS`` overrides (tests, exotic backends);
    otherwise the generation table in utils/profiler resolves the
    live device kind. Never raises — an unknown backend falls back to
    the v5e figure so the gauge stays a ranking, not a crash."""
    env = os.getenv(PEAK_TFLOPS_ENV, "")
    if env:
        try:
            return float(env) * 1e12
        except ValueError:
            logger.warning("unparseable %s=%r", PEAK_TFLOPS_ENV, env)
    try:
        from dlrover_tpu.utils.profiler import chip_peaks

        return chip_peaks()[0] * 1e12
    except Exception:  # noqa: BLE001 — no jax / no device
        return 197.0e12


def step_flops(jfn, *args) -> Optional[float]:
    """FLOPs per call of a jitted function, priced by XLA's own cost
    model on the *lowered* module — trace + lower only, which is
    cheap next to an XLA compile and never triggers a second one.
    Must be called BEFORE the first dispatch when arguments will be
    donated (lowering only reads shapes; dispatch deletes buffers).
    Returns None when the backend can't price the module."""
    try:
        cost = jfn.lower(*args).cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:  # noqa: BLE001 — backend-dependent analysis
        logger.debug("lowered cost_analysis unavailable", exc_info=True)
        return None


class CompileTracker:
    """Detects which dispatches of a jitted callable (re)compiled.

    Primary signal: growth of the jit dispatch cache
    (``jfn._cache_size()``), which catches silent retraces from shape
    or dtype drift mid-run. Fallback (no cache API): only the first
    observed call counts as the compile.
    """

    def __init__(self, fn_name: str, jfn=None):
        self.fn_name = fn_name
        self._jfn = jfn
        self._last_cache_size: Optional[int] = None
        self._calls = 0
        self.compiles = 0

    def _cache_size(self) -> Optional[int]:
        probe = getattr(self._jfn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 — private API, best-effort
            return None

    def observe_call(self, dur_s: float) -> bool:
        """Record one dispatch lasting ``dur_s``; True when it
        (re)compiled."""
        self._calls += 1
        size = self._cache_size()
        if size is None:
            compiled = self._calls == 1
        else:
            compiled = (
                self._last_cache_size is None
                or size > self._last_cache_size
            )
            self._last_cache_size = size
        if compiled:
            self.compiles += 1
            _COMPILE_TOTAL.inc(fn=self.fn_name)
            _COMPILE_SECONDS.inc(max(dur_s, 0.0), fn=self.fn_name)
            obs_event(
                "trainer.compile",
                fn=self.fn_name,
                dur_s=round(dur_s, 4),
                total=self.compiles,
            )
            if self.compiles > 1:
                logger.warning(
                    "%s recompiled (compile #%d, %.2fs): check for "
                    "shape/dtype drift in the input pipeline",
                    self.fn_name, self.compiles, dur_s,
                )
        return compiled


class MfuMeter:
    """FLOPs/step + measured step seconds -> live MFU gauge.

    Step times feed a bounded window; the gauge is the windowed-mean
    utilisation, which absorbs the host-side pacing jitter of the
    zero-sync loop (individual samples are dispatch pacing; their
    mean is true step time — see dlrover_train_step_seconds)."""

    def __init__(
        self,
        peak_flops: Optional[float] = None,
        window: int = 32,
    ):
        self._peak = peak_flops  # resolved lazily (may import jax)
        self.flops_per_step: Optional[float] = None
        self._times: collections.deque = collections.deque(maxlen=window)
        self.mfu: Optional[float] = None

    @property
    def peak(self) -> float:
        if self._peak is None:
            self._peak = peak_flops_per_s()
        return self._peak

    def set_flops(self, flops_per_step: Optional[float]) -> None:
        if not flops_per_step or flops_per_step <= 0:
            return
        self.flops_per_step = float(flops_per_step)
        _FLOPS_PER_STEP.set(self.flops_per_step)

    def observe_step(self, step_seconds: float) -> Optional[float]:
        """Fold one measured step; returns (and gauges) the updated
        windowed MFU, or None until FLOPs are known."""
        if step_seconds > 0:
            self._times.append(float(step_seconds))
        if self.flops_per_step is None or not self._times:
            return None
        mean = sum(self._times) / len(self._times)
        if mean <= 0:
            return None
        self.mfu = self.flops_per_step / (mean * self.peak)
        _MFU.set(self.mfu)
        return self.mfu


class StepPhaseProfiler:
    """Per-step wall-time attribution + on-demand N-step capture.

    The owning loop reports what it knows::

        prof.note_data_wait(dt)         # blocked on next(batches)
        prof.note_dispatch(dt, compiled)  # from the trainer's step
        prof.end_step()                 # once per optimizer step

    ``end_step`` measures the step's total wall time on its own
    (injectable) clock and books the residual — wall minus the noted
    phases — as ``device_execute``: in a zero-sync loop that residual
    is exactly the time the host spent ahead of (or waiting on) the
    device. The five phases therefore partition wall time exactly.

    Capture protocol: every ``end_step`` polls the request file
    (mtime-gated, so the steady-state cost is one ``stat``); a fresh
    request arms an N-step capture whose per-step breakdowns fold
    into a digest written to the digest file (and, when a trace dir
    is requested, brackets the steps with ``jax.profiler``).
    """

    def __init__(
        self,
        fn_name: str = "train_step",
        clock: Callable[[], float] = time.perf_counter,
        mfu: Optional[MfuMeter] = None,
        compile_tracker: Optional[CompileTracker] = None,
        request_file: Optional[str] = None,
        digest_file: Optional[str] = None,
        poll_requests: bool = True,
        beacon: object = "auto",
    ):
        self.fn_name = fn_name
        self._clock = clock
        self.mfu = mfu
        self.compile_tracker = compile_tracker
        self._request_file = request_file or profile_request_file()
        self._digest_file = digest_file or profile_digest_file()
        self._poll_requests = poll_requests
        # Stall-localization beacon: the profiler stamps every phase
        # boundary the loop already reports, so cross-host progress
        # comparison costs the hot path one mmap memcpy per note.
        # "auto" = job-scoped beacon unless DLROVER_TPU_BEACON=0;
        # pass None/False to run beacon-less, or inject an instance.
        if beacon == "auto":
            beacon = default_beacon()
        self.beacon: Optional[ProgressBeacon] = beacon or None
        self._step_start: Optional[float] = None
        self._noted: Dict[str, float] = dict.fromkeys(PHASES, 0.0)
        self.steps = 0
        # capture state
        self._capture: Optional[dict] = None
        self._last_request_mtime: Optional[int] = None
        self._last_request_id: Optional[str] = None

    # -- per-step notes ---------------------------------------------------

    def note_data_wait(
        self, seconds: float, h2d_seconds: float = 0.0
    ) -> None:
        """Input wait for this step: ``seconds`` of host-side wait
        (pull/collate/queue) plus ``h2d_seconds`` of host->device
        staging (the split an input pipeline reports via
        ``wait_breakdown()``). Callers without the split pass the
        whole wait as ``seconds`` — attribution stays exhaustive
        either way."""
        host = max(seconds, 0.0)
        h2d = max(h2d_seconds, 0.0)
        if self._step_start is None:
            self._step_start = self._clock() - (host + h2d)
        self._noted["data_wait"] += host
        self._noted["h2d_stage"] += h2d
        if self.beacon is not None:
            self.beacon.stamp(step=self.steps + 1, phase="data_wait")

    def note_dispatch(self, seconds: float, compiled: bool = False) -> None:
        if self._step_start is None:
            self._step_start = self._clock() - max(seconds, 0.0)
        phase = "compile" if compiled else "dispatch"
        self._noted[phase] += max(seconds, 0.0)
        if self.beacon is not None:
            self.beacon.stamp(step=self.steps + 1, phase=phase)

    def end_step(self) -> Dict[str, float]:
        """Close the step: attribute its wall time and return the
        breakdown ``{phase: seconds, "wall_s": total}``."""
        now = self._clock()
        start = self._step_start if self._step_start is not None else now
        wall = max(now - start, 0.0)
        noted = sum(self._noted.values())
        breakdown = dict(self._noted)
        breakdown["device_execute"] = max(wall - noted, 0.0)
        # Clock skew guard: noted phases can (rarely) overshoot the
        # wall clock by scheduler jitter; scale them down so the
        # partition invariant (sum == wall) holds.
        if noted > wall > 0:
            scale = wall / noted
            for k in ("data_wait", "h2d_stage", "compile", "dispatch"):
                breakdown[k] *= scale
            breakdown["device_execute"] = 0.0
        for phase in PHASES:
            if breakdown[phase] > 0:
                _PHASE_SECONDS.inc(breakdown[phase], phase=phase)
        self.steps += 1
        self._noted = dict.fromkeys(PHASES, 0.0)
        self._step_start = now
        breakdown["wall_s"] = wall
        if self.beacon is not None:
            self.beacon.stamp(step=self.steps, phase="device_execute")
        mfu = None
        if self.mfu is not None:
            # Compile-tainted steps stay OUT of the MFU window (same
            # exclusion the profiler-less trainer path applies to its
            # compile-boundary sample): one multi-second XLA compile
            # in a 32-sample mean would underreport utilisation for
            # the whole window — exactly when a straggler-triggered
            # PROFILE is most likely to read it.
            if breakdown["compile"] > 0:
                mfu = self.mfu.mfu
            else:
                mfu = self.mfu.observe_step(wall)
        obs_event(
            "trainer.step_phases",
            step=self.steps,
            wall_s=round(wall, 6),
            data_wait_s=round(breakdown["data_wait"], 6),
            h2d_s=round(breakdown["h2d_stage"], 6),
            compile_s=round(breakdown["compile"], 6),
            dispatch_s=round(breakdown["dispatch"], 6),
            device_s=round(breakdown["device_execute"], 6),
            **({"mfu": round(mfu, 4)} if mfu is not None else {}),
        )
        if self._capture is not None:
            self._capture_step(breakdown)
        if self._poll_requests:
            self.poll_request()
        return breakdown

    # -- on-demand capture ------------------------------------------------

    @property
    def capturing(self) -> bool:
        return self._capture is not None

    def poll_request(self) -> bool:
        """Arm a capture when a fresh request file appeared. Steady-
        state cost: one stat() per step."""
        if self._capture is not None:
            return False
        try:
            mtime = os.stat(self._request_file).st_mtime_ns
        except OSError:
            return False
        if mtime == self._last_request_mtime:
            return False
        self._last_request_mtime = mtime
        try:
            with open(self._request_file) as f:
                req = json.load(f)
        except (OSError, ValueError):
            return False
        if not isinstance(req, dict):
            return False
        req_id = str(req.get("id", ""))
        if not req_id or req_id == self._last_request_id:
            return False
        self._last_request_id = req_id
        self.start_capture(
            steps=int(req.get("steps", 0) or DEFAULT_PROFILE_STEPS),
            trace_dir=str(req.get("trace_dir", "") or ""),
            request_id=req_id,
        )
        return True

    def start_capture(
        self,
        steps: int = DEFAULT_PROFILE_STEPS,
        trace_dir: str = "",
        request_id: str = "",
    ) -> None:
        """Record the next ``steps`` step breakdowns into a digest."""
        if self._capture is not None:
            return
        self._capture = {
            "id": request_id,
            "want": max(int(steps), 1),
            "rows": [],
            "compiles_at_start": (
                self.compile_tracker.compiles
                if self.compile_tracker is not None
                else 0
            ),
            "trace_dir": trace_dir,
            "tracing": False,
        }
        if trace_dir:
            try:
                import jax.profiler

                os.makedirs(trace_dir, exist_ok=True)
                jax.profiler.start_trace(trace_dir)
                self._capture["tracing"] = True
            except Exception:  # noqa: BLE001 — a broken trace backend
                # must not block the phase capture
                logger.warning(
                    "jax.profiler trace unavailable; capturing "
                    "phases only", exc_info=True,
                )
        obs_event(
            "trainer.profile_start",
            steps=self._capture["want"],
            request_id=request_id,
        )

    def _capture_step(self, breakdown: Dict[str, float]) -> None:
        cap = self._capture
        cap["rows"].append(breakdown)
        if len(cap["rows"]) >= cap["want"]:
            self._finish_capture()

    def _finish_capture(self) -> dict:
        cap, self._capture = self._capture, None
        if cap["tracing"]:
            try:
                import jax.profiler

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                logger.warning("stop_trace failed", exc_info=True)
        rows: List[Dict[str, float]] = cap["rows"]
        n = len(rows)
        walls = sorted(r["wall_s"] for r in rows)
        phases = {}
        for phase in PHASES:
            total = sum(r[phase] for r in rows)
            phases[phase] = {
                "total_s": round(total, 6),
                "mean_s": round(total / n, 6) if n else 0.0,
            }
        digest = {
            "id": cap["id"],
            "fn": self.fn_name,
            "steps": n,
            "phases": phases,
            "step_time_mean_s": round(sum(walls) / n, 6) if n else 0.0,
            "step_time_min_s": round(walls[0], 6) if walls else 0.0,
            "step_time_max_s": round(walls[-1], 6) if walls else 0.0,
            "compiles_during_capture": (
                self.compile_tracker.compiles - cap["compiles_at_start"]
                if self.compile_tracker is not None
                else 0
            ),
            "mfu": (
                round(self.mfu.mfu, 4)
                if self.mfu is not None and self.mfu.mfu is not None
                else None
            ),
            "flops_per_step": (
                self.mfu.flops_per_step if self.mfu is not None else None
            ),
            "trace_dir": cap["trace_dir"],
            "ts": time.time(),
        }
        try:
            tmp = f"{self._digest_file}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(digest, f)
            os.replace(tmp, self._digest_file)
        except OSError:
            logger.warning(
                "could not write profile digest %s",
                self._digest_file, exc_info=True,
            )
        _PROFILE_CAPTURES.inc()
        obs_event(
            "trainer.profile_done",
            steps=n,
            request_id=cap["id"],
            mfu=digest["mfu"],
        )
        return digest
