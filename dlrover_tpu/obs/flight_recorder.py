"""Always-on flight recorder: the black box every role carries.

The healthy-job telemetry (metrics registry, tracer, fleet
aggregation) answers "what is the job doing"; this module answers
"what was it doing when it died or wedged". Each process installs one
:class:`FlightRecorder` at startup (``install_flight_recorder(role)``)
holding a bounded in-memory ring — recent WARNING+ log records, the
last step/loss notes the trainer drops, tracer-event and metric
snapshots taken only at dump time — with near-zero steady-state cost:
no background thread, no I/O off the crash path, every hot-path hook
is a deque append or dict assignment.

Crash capture, three layers:

* ``faulthandler.enable`` on a pre-opened per-process *stacks file*
  (``<forensics_dir>/stacks_<pid>.txt``): fatal signals (SIGSEGV,
  SIGABRT, SIGBUS, SIGFPE, SIGILL) dump every thread's Python stack
  from the C handler — works even when the interpreter is wedged in a
  C extension call.
* a chained ``sys.excepthook`` / ``threading.excepthook``: any
  unhandled Python exception writes a full JSON *bundle* (ring
  contents + all-thread stacks + process/env/JAX platform info) to
  the forensics dir before the previous hook runs.
* trainer role only: ``faulthandler.register(SIGUSR1)`` on the same
  stacks file, so the supervising agent can snapshot the training
  process's stacks *while it is hung* (a Python-level signal handler
  would never run with the main thread stuck in a collective; the
  C-level faulthandler does).

The agent folds the stacks-file tail + ring digest into its failure
report when the hang detector trips, and ships a
``DiagnosticsReport`` to the master — see agent/agent.py and
master/servicer.py. ``tools/obs_report.py --postmortem <dir>`` renders
the bundles (obs/postmortem.py).

Knobs: ``DLROVER_TPU_FORENSICS_DIR`` (default
``/tmp/dlrover_tpu_forensics_<job>``), ``DLROVER_TPU_FLIGHT_RECORDER=0``
disables installation, ``DLROVER_TPU_FORENSICS_KEEP`` bounds retained
bundles per process (default 8, oldest deleted first).
"""

from __future__ import annotations

import collections
import faulthandler
import json
import logging
import os
import platform
import signal
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

FORENSICS_DIR_ENV = "DLROVER_TPU_FORENSICS_DIR"
FLIGHT_RECORDER_ENV = "DLROVER_TPU_FLIGHT_RECORDER"
FORENSICS_KEEP_ENV = "DLROVER_TPU_FORENSICS_KEEP"

BUNDLE_SCHEMA_VERSION = 1

# Ring / bundle size caps: the recorder must stay cheap while alive
# and the bundle must stay shippable when dead.
_LOG_RING_SIZE = 128
_EVENT_TAIL = 256
_MAX_FRAMES_PER_THREAD = 50
_DIGEST_CAP = 4096


def forensics_dir() -> str:
    """Per-run directory every role's recorder writes into."""
    configured = os.getenv(FORENSICS_DIR_ENV, "")
    if configured:
        return configured
    job = os.getenv("DLROVER_TPU_JOB_NAME", "default")
    return f"/tmp/dlrover_tpu_forensics_{job}"


def stacks_file_path(pid: Optional[int] = None,
                     dir_: Optional[str] = None) -> str:
    """The faulthandler dump target for ``pid`` — deterministic, so
    the agent can find its training process's stacks knowing only the
    pid (the SIGUSR1 contract)."""
    return os.path.join(
        dir_ or forensics_dir(), f"stacks_{pid or os.getpid()}.txt"
    )


class _RecorderLogHandler(logging.Handler):
    """Feeds WARNING+ records into the recorder's bounded ring."""

    def __init__(self, recorder: "FlightRecorder"):
        super().__init__(level=logging.WARNING)
        self._recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._recorder._log_ring.append(
                {
                    "ts": round(record.created, 3),
                    "level": record.levelname,
                    "logger": record.name,
                    "msg": record.getMessage()[:500],
                }
            )
        except Exception:  # noqa: BLE001 — logging must never raise
            pass


def _thread_stacks() -> List[dict]:
    """Python stacks of every live thread (bounded frames each)."""
    names = {t.ident: t for t in threading.enumerate()}
    stacks = []
    current = threading.get_ident()
    for ident, frame in sys._current_frames().items():
        thread = names.get(ident)
        frames = [
            f"{os.path.basename(fs.filename)}:{fs.lineno} in {fs.name}"
            for fs in traceback.extract_stack(
                frame, limit=_MAX_FRAMES_PER_THREAD
            )
        ]
        stacks.append(
            {
                "thread": thread.name if thread else f"ident-{ident}",
                "ident": ident,
                "daemon": bool(thread.daemon) if thread else None,
                "current": ident == current,
                "frames": frames,
            }
        )
    return stacks


def _process_info() -> dict:
    info = {
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "cwd": os.getcwd(),
    }
    # NEVER import jax here (a crash handler must not initialize a
    # backend); report its platform only if the process already did.
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            info["jax_platform"] = jax.default_backend()
        except Exception:  # noqa: BLE001 — backend init failed/raced
            info["jax_platform"] = "error"
    else:
        info["jax_platform"] = "not_imported"
    return info


def _env_snapshot() -> Dict[str, str]:
    keep = ("DLROVER_TPU_", "JAX_", "TPU_", "XLA_")
    return {
        k: v[:200]
        for k, v in sorted(os.environ.items())
        if any(k.startswith(p) for p in keep)
    }


class FlightRecorder:
    """One per process; see module docstring. Use
    :func:`install_flight_recorder`, not the constructor."""

    def __init__(
        self,
        role: str,
        rank: int = -1,
        dir_: Optional[str] = None,
        keep: Optional[int] = None,
    ):
        self.role = role or "unknown"
        self.rank = rank
        self.dir = dir_ or forensics_dir()
        if keep is None:
            try:
                keep = int(os.getenv(FORENSICS_KEEP_ENV, "") or 8)
            except ValueError:
                keep = 8
        self.keep = max(keep, 1)
        self._lock = threading.Lock()
        self._log_ring: collections.deque = collections.deque(
            maxlen=_LOG_RING_SIZE
        )
        self._notes: Dict[str, Any] = {}
        self._bundle_seq = 0
        self._bundle_paths: collections.deque = collections.deque()
        self._log_handler: Optional[_RecorderLogHandler] = None
        self._prev_excepthook = None
        self._prev_threading_excepthook = None
        self._sigusr1_registered = False
        self._stacks_file = None
        self.stacks_path = stacks_file_path(os.getpid(), self.dir)
        os.makedirs(self.dir, exist_ok=True)

    # -- steady-state surface (hot-path cheap) ---------------------------

    def note(self, **kv) -> None:
        """Record 'last known' facts (step, loss, phase): one bounded
        dict update, the whole per-step cost of the black box."""
        with self._lock:
            self._notes.update(kv)

    # -- installation ----------------------------------------------------

    def install(self, register_sigusr1: bool = False) -> None:
        """Wire the crash hooks. Idempotent per process."""
        # Pre-opened, line-buffered: a C signal handler cannot open
        # files, so faulthandler needs the fd ready before the crash.
        if self._stacks_file is None:
            try:
                self._stacks_file = open(
                    self.stacks_path, "a", buffering=1
                )
            except OSError:
                self._stacks_file = None
        if self._stacks_file is not None:
            try:
                faulthandler.enable(
                    file=self._stacks_file, all_threads=True
                )
            except (OSError, ValueError, RuntimeError):
                pass
            if register_sigusr1 and hasattr(signal, "SIGUSR1"):
                # C-level handler: dumps even when the main thread is
                # wedged inside a C call (blocked collective) where a
                # Python signal handler would never run.
                try:
                    faulthandler.register(
                        signal.SIGUSR1,
                        file=self._stacks_file,
                        all_threads=True,
                        chain=False,
                    )
                    self._sigusr1_registered = True
                except (OSError, ValueError, RuntimeError):
                    pass
            # Header written AFTER the SIGUSR1 registration attempt,
            # and only when it did not fail: a non-empty stacks file
            # is the agent's ack that signaling this pid is SAFE
            # (default SIGUSR1 disposition kills the process, so the
            # agent must never signal blind — sigusr1_ready()).
            if self._sigusr1_registered or not register_sigusr1:
                try:
                    self._stacks_file.write(
                        f"# flight recorder role={self.role} "
                        f"rank={self.rank} pid={os.getpid()} "
                        f"sigusr1={int(self._sigusr1_registered)} "
                        f"ts={time.time():.3f}\n"
                    )
                except OSError:
                    pass
        if self._prev_excepthook is None:
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._excepthook
        if self._prev_threading_excepthook is None and hasattr(
            threading, "excepthook"
        ):
            self._prev_threading_excepthook = threading.excepthook
            threading.excepthook = self._threading_excepthook
        if self._log_handler is None:
            from dlrover_tpu.common.log import default_logger

            self._log_handler = _RecorderLogHandler(self)
            default_logger.addHandler(self._log_handler)

    def uninstall(self) -> None:
        """Restore hooks (tests; a real process crashes with them on)."""
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._prev_threading_excepthook is not None:
            threading.excepthook = self._prev_threading_excepthook
            self._prev_threading_excepthook = None
        if self._log_handler is not None:
            from dlrover_tpu.common.log import default_logger

            default_logger.removeHandler(self._log_handler)
            self._log_handler = None
        if self._sigusr1_registered:
            try:
                faulthandler.unregister(signal.SIGUSR1)
            except (OSError, ValueError, RuntimeError):
                pass
            self._sigusr1_registered = False
        if self._stacks_file is not None:
            try:
                # Re-point faulthandler at stderr before closing the
                # file it holds, else a later crash writes to a
                # closed fd.
                faulthandler.enable(file=sys.stderr, all_threads=True)
            except (OSError, ValueError, RuntimeError):
                try:
                    faulthandler.disable()
                except (OSError, ValueError, RuntimeError):
                    pass
            try:
                self._stacks_file.close()
            except OSError:
                pass
            self._stacks_file = None

    # -- crash hooks -----------------------------------------------------

    def _excepthook(self, exc_type, exc, tb) -> None:
        try:
            reason = "".join(
                traceback.format_exception_only(exc_type, exc)
            ).strip()[:500]
            formatted = "".join(
                traceback.format_exception(exc_type, exc, tb)
            )[-4096:]
            self.dump(
                "exception",
                reason=reason,
                extra={"traceback": formatted},
            )
        except Exception:  # noqa: BLE001 — the original traceback
            # must still reach the user even if the black box fails
            pass
        if self._prev_excepthook is not None:
            self._prev_excepthook(exc_type, exc, tb)

    def _threading_excepthook(self, args) -> None:
        try:
            reason = "".join(
                traceback.format_exception_only(
                    args.exc_type, args.exc_value
                )
            ).strip()[:500]
            thread = getattr(args.thread, "name", "?")
            self.dump(
                "thread_exception",
                reason=f"[thread {thread}] {reason}",
            )
        except Exception:  # noqa: BLE001
            pass
        if self._prev_threading_excepthook is not None:
            self._prev_threading_excepthook(args)

    # -- bundles ---------------------------------------------------------

    def snapshot(self, kind: str = "manual", reason: str = "") -> dict:
        """The black-box contents as one JSON-able dict."""
        from dlrover_tpu import obs

        with self._lock:
            logs = list(self._log_ring)
            notes = dict(self._notes)
        tracer = obs.get_tracer()
        events = tracer.events()[-_EVENT_TAIL:] if tracer else []
        try:
            metrics = obs.get_registry().dump()
        except Exception:  # noqa: BLE001 — a half-poisoned registry
            # must not block the crash dump
            metrics = {}
        return {
            "schema": BUNDLE_SCHEMA_VERSION,
            "kind": kind,
            "reason": reason,
            "ts": time.time(),
            "role": self.role,
            "rank": self.rank,
            "pid": os.getpid(),
            "proc": _process_info(),
            "env": _env_snapshot(),
            "notes": notes,
            "logs": logs,
            "events": events,
            "metrics": metrics,
            "stacks": _thread_stacks(),
            "stacks_file": self.stacks_path,
        }

    def dump(
        self,
        kind: str,
        reason: str = "",
        extra: Optional[dict] = None,
        incident: Optional[dict] = None,
    ) -> Optional[str]:
        """Write one bundle file; returns its path (None on failure).
        ``incident`` facts (hang_seconds, exit_code, ...) merge into
        THIS bundle's notes only — never into the recorder's
        persistent notes, which must keep describing the live process
        (a later diagnose snapshot must not replay a past hang's
        facts). Retention: at most ``keep`` bundles per process."""
        try:
            bundle = self.snapshot(kind=kind, reason=reason)
            if incident:
                bundle["notes"] = {**bundle["notes"], **incident}
            if extra:
                bundle.update(extra)
            with self._lock:
                self._bundle_seq += 1
                seq = self._bundle_seq
            fname = (
                f"bundle_{self.role}_r{self.rank}_{os.getpid()}"
                f"_{seq:03d}_{kind}.json"
            )
            path = os.path.join(self.dir, fname)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, default=str)
            os.replace(tmp, path)
            self._bundle_paths.append(path)
            while len(self._bundle_paths) > self.keep:
                stale = self._bundle_paths.popleft()
                try:
                    os.remove(stale)
                except OSError:
                    pass
            return path
        except Exception:  # noqa: BLE001 — the black box must never
            # turn a crash into a different crash
            return None


def make_digest(
    kind: str,
    stacks_text: str = "",
    recorder: Optional[FlightRecorder] = None,
    incident: Optional[dict] = None,
    cap: int = _DIGEST_CAP,
) -> str:
    """Size-capped human-readable digest for failure reports and the
    master's per-node diagnostics history: top stack frames first
    (they carry the verdict), then this incident's facts and the
    recorder's last notes/events."""
    parts: List[str] = [f"-- forensics digest ({kind}) --"]
    if stacks_text:
        parts.append(stacks_text.strip())
    notes: Dict[str, Any] = {}
    logs: List[dict] = []
    if recorder is not None:
        with recorder._lock:
            notes = dict(recorder._notes)
            logs = list(recorder._log_ring)[-5:]
    if incident:
        notes.update(incident)
    if notes:
        parts.append(
            "notes: "
            + json.dumps(notes, default=str, sort_keys=True)[:500]
        )
    for rec in logs:
        parts.append(
            f"log {rec.get('level')}: {rec.get('msg', '')[:200]}"
        )
    digest = "\n".join(parts)
    return digest[:cap]


def sigusr1_ready(pid: int, dir_: Optional[str] = None) -> bool:
    """True when ``pid``'s recorder registered the SIGUSR1 stack-dump
    handler (its stacks file carries the post-registration header
    line). The agent MUST check this before signaling: the default
    SIGUSR1 disposition terminates the process, so signaling a
    trainer whose recorder is disabled (``DLROVER_TPU_FLIGHT_RECORDER
    =0``), not yet installed (still importing), or whose registration
    failed would turn a diagnostics snapshot into a kill."""
    try:
        with open(stacks_file_path(pid, dir_), "rb") as f:
            header = f.readline()
    except OSError:
        return False
    return b"sigusr1=1" in header


def read_stacks_tail(
    path: str, since: int = 0, cap: int = 8192
) -> str:
    """Bytes ``since``.. of a stacks file (capped): the agent reads
    the growth the SIGUSR1 dump produced, not the whole history."""
    try:
        with open(path, "rb") as f:
            f.seek(since)
            data = f.read(cap + 1)
    except OSError:
        return ""
    return data[:cap].decode("utf-8", "replace")


# -- module-level singleton -------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_install_lock = threading.Lock()


def install_flight_recorder(
    role: str,
    rank: Optional[int] = None,
    dir_: Optional[str] = None,
) -> Optional[FlightRecorder]:
    """Install the process's recorder (idempotent; first caller wins).
    Trainer role additionally gets the SIGUSR1 stack-dump handler so
    the agent can snapshot it while hung. Returns None when disabled
    via ``DLROVER_TPU_FLIGHT_RECORDER=0``."""
    if os.getenv(FLIGHT_RECORDER_ENV, "") == "0":
        return None
    global _recorder
    with _install_lock:
        if _recorder is not None:
            return _recorder
        if rank is None:
            from dlrover_tpu.common.log import role_and_rank

            _, rank = role_and_rank()
        rec = FlightRecorder(role, rank=rank, dir_=dir_)
        try:
            rec.install(register_sigusr1=(role == "trainer"))
        except Exception:  # noqa: BLE001 — a broken forensics dir
            # must not stop the process from starting
            return None
        _recorder = rec
        return rec


def get_flight_recorder() -> Optional[FlightRecorder]:
    return _recorder


def uninstall_flight_recorder() -> None:
    """Tear down the singleton (tests)."""
    global _recorder
    with _install_lock:
        if _recorder is not None:
            _recorder.uninstall()
            _recorder = None


def recorder_note(**kv) -> None:
    """Record 'last known' facts into the black box; a single
    None-check when no recorder is installed."""
    rec = _recorder
    if rec is not None:
        rec.note(**kv)
