"""Recovery-timeline reconstruction from an obs event stream.

Folds the JSONL events the tracer exports into the canonical recovery
breakdown the chaos drills and the BASELINE contract reason about::

    failure-detect -> rendezvous -> build -> restore -> first-step
                                                   [-> throughput-90]

The trainer-side marks are the ``trainer.*`` events mirrored from
``TrainingMonitor.mark_phase`` (agent/monitor.py): ``proc_start``,
``dist_ready``, ``built``, ``restore_done``, ``first_step_done``.
``failure-detect`` runs from the failure instant (a master-side
``node.fail``/``node.gone``/``node.heartbeat_timeout`` event, or an
externally observed kill time) to the relaunched trainer's
``proc_start`` — i.e. it includes the watchdog detection AND the agent
respawn, matching the drills' ``detect_respawn_s`` segment.

Reconstruction is resilient to multi-attempt logs: the sink file
appends across trainer restarts, so the reconstructor picks the FIRST
``trainer.proc_start`` at or after the failure instant and then walks
the remaining marks forward in time from there.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, List, Optional

# Trainer phase marks, in causal order (names as emitted by the
# mark_phase mirror: "trainer." + mark).
TRAINER_MARKS = (
    "trainer.proc_start",
    "trainer.dist_ready",
    "trainer.built",
    "trainer.restore_done",
    "trainer.first_step_done",
)

# Master-side events that pin the failure instant when the caller does
# not supply one.
FAILURE_EVENTS = (
    "node.fail",
    "node.gone",
    "node.heartbeat_timeout",
)

# Canonical phase names, in order. "build" (strategy build + sharded
# init, the first cold compile) sits between rendezvous and restore so
# restore time is not blamed on compilation.
PHASE_ORDER = (
    "failure-detect",
    "rendezvous",
    "build",
    "restore",
    "first-step",
    "throughput-90",
)

REQUIRED_PHASES = (
    "failure-detect", "rendezvous", "restore", "first-step",
)


@dataclasses.dataclass
class RecoveryTimeline:
    """Structured recovery report: absolute marks plus per-phase
    durations. ``complete`` is True when every required phase is
    present; ``throughput-90`` stays None unless a recovery signal was
    observed (it needs a pre-failure throughput baseline)."""

    t_failure: float
    marks: Dict[str, float]
    phases: Dict[str, Optional[float]]
    total_s: float
    complete: bool

    def to_dict(self) -> dict:
        return {
            "t_failure": self.t_failure,
            "marks": {k: round(v, 3) for k, v in self.marks.items()},
            "phases": {
                k: (round(v, 3) if v is not None else None)
                for k, v in self.phases.items()
            },
            "total_s": round(self.total_s, 3),
            "complete": self.complete,
        }


def load_events(path: str) -> List[dict]:
    """Read a tracer JSONL file; skips unparsable lines (a crashed
    writer may leave a torn final line)."""
    events: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "name" in rec:
                    events.append(rec)
    except OSError:
        return []
    return events


def _first_at_or_after(
    events: List[dict], name: str, not_before: float
) -> Optional[dict]:
    for ev in events:
        if ev.get("name") == name and ev.get("ts", 0.0) >= not_before:
            return ev
    return None


def reconstruct_recovery_timeline(
    events: Iterable[dict],
    t_failure: Optional[float] = None,
    throughput_recovered_ts: Optional[float] = None,
) -> Optional[RecoveryTimeline]:
    """Fold ``events`` into a :class:`RecoveryTimeline`.

    ``t_failure``: the failure instant; derived from the first
    master-side failure event when omitted. Returns None when neither
    is available (nothing to anchor the timeline on).
    ``throughput_recovered_ts``: wall time the job regained >=90% of
    pre-failure throughput, when the caller measured it (the master's
    ``SpeedMonitor.recovery_seconds`` or a drill's metrics poll).
    """
    evs = sorted(
        (e for e in events if "ts" in e and "name" in e),
        key=lambda e: e["ts"],
    )
    if t_failure is None:
        for ev in evs:
            if ev["name"] in FAILURE_EVENTS:
                t_failure = float(ev["ts"])
                break
    if t_failure is None:
        return None

    marks: Dict[str, float] = {}
    cursor = t_failure
    for name in TRAINER_MARKS:
        ev = _first_at_or_after(evs, name, cursor)
        if ev is None:
            break
        marks[name] = float(ev["ts"])
        cursor = marks[name]

    def seg(a: str, b: str) -> Optional[float]:
        if a in marks and b in marks:
            return marks[b] - marks[a]
        return None

    phases: Dict[str, Optional[float]] = {
        "failure-detect": (
            marks["trainer.proc_start"] - t_failure
            if "trainer.proc_start" in marks else None
        ),
        "rendezvous": seg("trainer.proc_start", "trainer.dist_ready"),
        "build": seg("trainer.dist_ready", "trainer.built"),
        "restore": seg("trainer.built", "trainer.restore_done"),
        "first-step": seg(
            "trainer.restore_done", "trainer.first_step_done"
        ),
        "throughput-90": None,
    }
    if throughput_recovered_ts is None:
        ev = _first_at_or_after(evs, "trainer.throughput_recovered",
                                t_failure)
        if ev is not None:
            throughput_recovered_ts = float(ev["ts"])
    last = max(marks.values()) if marks else t_failure
    if (
        throughput_recovered_ts is not None
        and "trainer.first_step_done" in marks
        # A recovery stamp that predates the first step is from a
        # previous attempt (or a caller bug): a negative phase would
        # poison budget checks, so the phase stays unknown instead.
        and throughput_recovered_ts
        >= marks["trainer.first_step_done"]
    ):
        phases["throughput-90"] = (
            throughput_recovered_ts - marks["trainer.first_step_done"]
        )
        last = max(last, throughput_recovered_ts)

    complete = all(phases[p] is not None for p in REQUIRED_PHASES)
    return RecoveryTimeline(
        t_failure=t_failure,
        marks=marks,
        phases=phases,
        total_s=last - t_failure,
        complete=complete,
    )


def render_timeline(tl: RecoveryTimeline) -> str:
    """Human-readable one-timeline report (tools/obs_report.py)."""
    lines = [
        f"recovery timeline (t_failure={tl.t_failure:.3f}, "
        f"total {tl.total_s:.2f}s, "
        f"{'complete' if tl.complete else 'INCOMPLETE'})",
    ]
    offset = 0.0
    for name in PHASE_ORDER:
        dur = tl.phases.get(name)
        if dur is None:
            lines.append(f"  {name:<16} -")
            continue
        lines.append(
            f"  {name:<16} {dur:8.2f}s  (t+{offset:.2f}s)"
        )
        offset += dur
    return "\n".join(lines)
