"""Bounded in-memory time-series store for the master's health plane.

The measurement plane (fleet snapshots, goodput recomputes, speed-
monitor EWMAs, compile counters) produces *instantaneous* values; the
brain layer (PAPER.md §1.1's optimize service) needs *history* —
"throughput over the last two minutes vs the two before that", "is
this host's RSS still climbing". This module is that substrate: a
stdlib-only, lock-guarded store of labeled series with

* **ring retention** — the newest ``raw_points`` samples per series
  are kept at full resolution; older samples are folded into coarse
  buckets of ``coarse_resolution`` seconds (mean/min/max/count per
  bucket, ``coarse_points`` buckets retained), so a series costs
  O(raw + coarse) memory forever;
* **windowed queries** — :meth:`query` (count/mean/min/max/p50/p90),
  :meth:`rate` for cumulative counters, and :meth:`slope` (robust
  Theil–Sen estimator, so one outlier sample cannot fake a trend).
  Every query takes an ``end_offset_s`` so detectors can compare a
  recent window against the *baseline* window that preceded it;
* an **injectable clock** so detector tests drive simulated hours in
  microseconds.

Series names are internal dotted identifiers (``host.step_time``,
``goodput.ratio``) — this store feeds detectors and reports, not the
Prometheus endpoint (the registry in obs/metrics.py owns exposition).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.common.log import get_logger

logger = get_logger("obs.timeseries")

LabelsKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Dict[str, str]) -> LabelsKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _percentile(ordered: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not ordered:
        return 0.0
    rank = max(
        0,
        min(len(ordered) - 1, int(round(q / 100.0 * (len(ordered) - 1)))),
    )
    return ordered[rank]


@dataclasses.dataclass
class WindowStats:
    """Summary of one series over one query window."""

    count: int
    mean: float
    minimum: float
    maximum: float
    p50: float
    p90: float
    first_ts: float
    last_ts: float
    first: float
    last: float

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "mean": round(self.mean, 6),
            "min": round(self.minimum, 6),
            "max": round(self.maximum, 6),
            "p50": round(self.p50, 6),
            "p90": round(self.p90, 6),
            "first_ts": round(self.first_ts, 3),
            "last_ts": round(self.last_ts, 3),
        }


class Series:
    """One labeled series: raw ring (full resolution, newest
    ``raw_points`` samples) + coarse downsampled history (one
    mean/min/max/count bucket per ``coarse_resolution`` seconds)."""

    def __init__(self, raw_points: int, coarse_points: int,
                 coarse_resolution: float):
        self.raw_max = max(int(raw_points), 2)
        self.raw: deque = deque()
        self.coarse: deque = deque(maxlen=max(int(coarse_points), 1))
        self.coarse_resolution = max(float(coarse_resolution), 1e-9)
        self.bucket: Optional[list] = None  # [key, sum, count, min, max]

    def append(self, ts: float, value: float) -> None:
        self.raw.append((ts, value))
        while len(self.raw) > self.raw_max:
            old_ts, old_v = self.raw.popleft()
            self._fold(old_ts, old_v)

    def _fold(self, ts: float, value: float) -> None:
        key = int(ts // self.coarse_resolution)
        if self.bucket is None or self.bucket[0] != key:
            self.flush_bucket()
            self.bucket = [key, 0.0, 0, value, value]
        b = self.bucket
        b[1] += value
        b[2] += 1
        b[3] = min(b[3], value)
        b[4] = max(b[4], value)

    def flush_bucket(self) -> None:
        if self.bucket is None:
            return
        key, total, count, vmin, vmax = self.bucket
        center = (key + 0.5) * self.coarse_resolution
        self.coarse.append((center, total / count, vmin, vmax, count))
        self.bucket = None

    def extremes(
        self, t0: float, t1: float
    ) -> Tuple[Optional[float], Optional[float]]:
        """True (min, max) over [t0, t1]: raw samples plus the
        per-bucket extremes the downsampled history retains — a spike
        that has aged into a coarse bucket must still show up in a
        long-window max, not be hidden behind the bucket mean."""
        vmin: Optional[float] = None
        vmax: Optional[float] = None

        def take(lo: float, hi: float) -> None:
            nonlocal vmin, vmax
            vmin = lo if vmin is None else min(vmin, lo)
            vmax = hi if vmax is None else max(vmax, hi)

        for ts, _, bmin, bmax, _ in self.coarse:
            if t0 <= ts <= t1:
                take(bmin, bmax)
        if self.bucket is not None:
            key, _, _, bmin, bmax = self.bucket
            center = (key + 0.5) * self.coarse_resolution
            if t0 <= center <= t1:
                take(bmin, bmax)
        for ts, v in self.raw:
            if t0 <= ts <= t1:
                take(v, v)
        return vmin, vmax

    def points(
        self, t0: float, t1: float
    ) -> List[Tuple[float, float]]:
        """(ts, value) in [t0, t1], coarse means then raw samples.

        The open bucket (folded but not yet flushed) is included so a
        long query never has a blind spot between coarse and raw."""
        out: List[Tuple[float, float]] = [
            (ts, mean)
            for ts, mean, _, _, _ in self.coarse
            if t0 <= ts <= t1
        ]
        if self.bucket is not None:
            key, total, count, _, _ = self.bucket
            center = (key + 0.5) * self.coarse_resolution
            if t0 <= center <= t1:
                out.append((center, total / count))
        out.extend(
            (ts, v) for ts, v in self.raw if t0 <= ts <= t1
        )
        return out


class TimeSeriesStore:
    """Bounded store of labeled series with windowed queries.

    Thread-safe; every public method takes and releases one lock.
    ``clock`` defaults to wall time because the feeding sources stamp
    wall timestamps (agent snapshots, goodput windows) — tests inject
    a fake clock and stamp records explicitly.
    """

    def __init__(
        self,
        raw_points: int = 512,
        coarse_points: int = 512,
        coarse_resolution: float = 30.0,
        max_series: int = 4096,
        clock: Callable[[], float] = time.time,
    ):
        self.raw_points = raw_points
        self.coarse_points = coarse_points
        self.coarse_resolution = coarse_resolution
        self.max_series = max_series
        self.clock = clock
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, LabelsKey], Series] = {}
        self._dropped_series = 0

    # -- ingest -----------------------------------------------------------

    def record(
        self,
        name: str,
        value: float,
        ts: Optional[float] = None,
        **labels: str,
    ) -> None:
        """Append one sample. Never raises on bad input — telemetry
        must not take its producer down."""
        try:
            value = float(value)
        except (TypeError, ValueError):
            return
        if value != value:  # NaN
            return
        stamp = float(ts) if ts is not None else self.clock()
        key = (str(name), _labels_key(labels))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    # Bounded by contract: a label-cardinality bug
                    # upstream must not grow master memory forever.
                    self._dropped_series += 1
                    if self._dropped_series == 1:
                        logger.warning(
                            "time-series store full (%d series); "
                            "dropping new series %s%s",
                            self.max_series, name, dict(labels),
                        )
                    return
                series = Series(
                    self.raw_points,
                    self.coarse_points,
                    self.coarse_resolution,
                )
                self._series[key] = series
            series.append(stamp, value)

    def drop_series(self, name: str, **labels: str) -> None:
        """Forget one series (departed host)."""
        with self._lock:
            self._series.pop((str(name), _labels_key(labels)), None)

    def drop_label(self, label: str, value: str) -> None:
        """Forget every series carrying ``label == value`` — the one
        call sites need when a host leaves the fleet."""
        pair = (str(label), str(value))
        with self._lock:
            gone = [
                k for k in self._series if pair in k[1]
            ]
            for k in gone:
                self._series.pop(k, None)

    # -- introspection ----------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted({name for name, _ in self._series})

    def series_labels(self, name: str) -> List[Dict[str, str]]:
        """The label sets under ``name`` (one dict per series)."""
        with self._lock:
            return [
                dict(lk)
                for n, lk in sorted(self._series)
                if n == name
            ]

    def size(self) -> int:
        with self._lock:
            return len(self._series)

    # -- queries ----------------------------------------------------------

    def _window(
        self,
        name: str,
        window_s: Optional[float],
        end_offset_s: float,
        labels: Dict[str, str],
    ) -> List[Tuple[float, float]]:
        key = (str(name), _labels_key(labels))
        now = self.clock()
        t1 = now - max(end_offset_s, 0.0)
        t0 = t1 - window_s if window_s is not None else -float("inf")
        with self._lock:
            series = self._series.get(key)
            if series is None:
                return []
            return series.points(t0, t1)

    def points(
        self,
        name: str,
        window_s: Optional[float] = None,
        end_offset_s: float = 0.0,
        **labels: str,
    ) -> List[Tuple[float, float]]:
        """Samples in ``[now - end_offset - window, now - end_offset]``
        (the whole retained history when ``window_s`` is None), oldest
        first. Points older than the raw ring arrive downsampled to
        one mean per ``coarse_resolution`` bucket."""
        return sorted(self._window(name, window_s, end_offset_s, labels))

    def query(
        self,
        name: str,
        window_s: Optional[float] = None,
        end_offset_s: float = 0.0,
        **labels: str,
    ) -> Optional[WindowStats]:
        """Window summary, or None when the window holds no samples."""
        pts = self.points(
            name, window_s, end_offset_s=end_offset_s, **labels
        )
        if not pts:
            return None
        values = sorted(v for _, v in pts)
        # min/max from the true per-bucket extremes: points() carries
        # only bucket means for downsampled history, which would hide
        # spikes older than the raw ring.
        key = (str(name), _labels_key(labels))
        now = self.clock()
        t1 = now - max(end_offset_s, 0.0)
        t0 = t1 - window_s if window_s is not None else -float("inf")
        with self._lock:
            series = self._series.get(key)
            vmin, vmax = (
                series.extremes(t0, t1)
                if series is not None
                else (None, None)
            )
        return WindowStats(
            count=len(pts),
            mean=sum(values) / len(values),
            minimum=values[0] if vmin is None else vmin,
            maximum=values[-1] if vmax is None else vmax,
            p50=_percentile(values, 50.0),
            p90=_percentile(values, 90.0),
            first_ts=pts[0][0],
            last_ts=pts[-1][0],
            first=pts[0][1],
            last=pts[-1][1],
        )

    def latest(
        self, name: str, **labels: str
    ) -> Optional[Tuple[float, float]]:
        pts = self._window(name, None, 0.0, labels)
        return max(pts) if pts else None

    def rate(
        self,
        name: str,
        window_s: float,
        end_offset_s: float = 0.0,
        **labels: str,
    ) -> Optional[float]:
        """Per-second rate of a CUMULATIVE series over the window
        ((last - first) / elapsed). None without two samples, and None
        on a negative delta — a counter reset (process restart) must
        not read as a negative rate."""
        pts = self.points(
            name, window_s, end_offset_s=end_offset_s, **labels
        )
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0 or v1 < v0:
            return None
        return (v1 - v0) / (t1 - t0)

    # Theil–Sen is O(n^2) pairs; cap the sample count so a full raw
    # ring cannot turn one detector tick into ~130k slope pairs.
    SLOPE_MAX_POINTS = 64

    def slope(
        self,
        name: str,
        window_s: float,
        end_offset_s: float = 0.0,
        **labels: str,
    ) -> Optional[float]:
        """Robust linear trend (units/second) over the window: the
        Theil–Sen estimator — median of pairwise slopes — so a single
        outlier sample cannot fake or mask a trend. None without two
        samples spanning nonzero time."""
        pts = self.points(
            name, window_s, end_offset_s=end_offset_s, **labels
        )
        if len(pts) > self.SLOPE_MAX_POINTS:
            stride = len(pts) / float(self.SLOPE_MAX_POINTS)
            pts = [
                pts[int(i * stride)]
                for i in range(self.SLOPE_MAX_POINTS)
            ]
        if len(pts) < 2:
            return None
        slopes = [
            (v2 - v1) / (t2 - t1)
            for i, (t1, v1) in enumerate(pts)
            for t2, v2 in pts[i + 1:]
            if t2 > t1
        ]
        if not slopes:
            return None
        slopes.sort()
        mid = len(slopes) // 2
        if len(slopes) % 2:
            return slopes[mid]
        return (slopes[mid - 1] + slopes[mid]) / 2.0

    def first_ts(self, name: str, **labels: str) -> Optional[float]:
        pts = self._window(name, None, 0.0, labels)
        return min(pts)[0] if pts else None
