"""Prometheus text-format exposition over HTTP (stdlib only).

The master opts in with ``--metrics_port`` (or
``DLROVER_TPU_METRICS_PORT``); scraping is then::

    curl http://<master-host>:<port>/metrics

Built on ``http.server.ThreadingHTTPServer`` — no ``prometheus_client``
``start_http_server``, keeping the zero-dependency contract. Tests that
only need the payload call ``registry.render()`` directly and never
bind a socket.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs.metrics import MetricsRegistry, get_registry

logger = get_logger("obs.http")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _make_handler(registry: MetricsRegistry):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                body = registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif path in ("/", "/healthz"):
                body = b"ok\n"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def log_message(self, fmt, *args):
            # Scrapes land every few seconds; keep them out of stderr.
            logger.debug("http: " + fmt, *args)

    return Handler


class MetricsHTTPServer:
    """Serves ``GET /metrics`` for a registry on a daemon thread."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        port: int = 0,
        host: str = "0.0.0.0",
    ):
        self.registry = registry or get_registry()
        self._server = ThreadingHTTPServer(
            (host, port), _make_handler(self.registry)
        )
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="metrics-http",
                daemon=True,
            )
            self._thread.start()
            logger.info(
                "metrics endpoint on http://127.0.0.1:%d/metrics",
                self.port,
            )

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
