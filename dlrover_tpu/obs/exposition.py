"""Prometheus text-format exposition over HTTP (stdlib only).

The master opts in with ``--metrics_port`` (or
``DLROVER_TPU_METRICS_PORT``); scraping is then::

    curl http://<master-host>:<port>/metrics

Built on ``http.server.ThreadingHTTPServer`` — no ``prometheus_client``
``start_http_server``, keeping the zero-dependency contract. Tests that
only need the payload call ``registry.render()`` directly and never
bind a socket.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs.metrics import MetricsRegistry, get_registry

logger = get_logger("obs.http")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _make_handler(
    registry: MetricsRegistry,
    health: Optional[Callable[[], dict]] = None,
):
    class Handler(BaseHTTPRequestHandler):
        def _send(self, status: int, body: bytes, ctype: str) -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                self._send(200, registry.render().encode(), CONTENT_TYPE)
            elif path == "/":
                # Pure liveness, always 200: a critical verdict about
                # a WORKER must not make the master process look dead
                # to a probe pointed at the root path.
                self._send(200, b"ok\n", "text/plain")
            elif path == "/healthz":
                if health is None:
                    # No health plane attached (bare exposition
                    # server): liveness-only answer, as before.
                    self._send(200, b"ok\n", "text/plain")
                    return
                try:
                    payload = health()
                except Exception:  # noqa: BLE001 — a broken health
                    # provider must not 500 the liveness probe
                    logger.warning(
                        "health provider failed", exc_info=True
                    )
                    payload = {"ok": True, "error": "health provider failed"}
                # Readiness semantics for the deploy/ CRD probes: 200
                # while no CRITICAL verdict is active, 503 otherwise —
                # the JSON body carries the score either way so a
                # smarter prober can apply its own floor.
                status = 200 if payload.get("ok", True) else 503
                self._send(
                    status,
                    (json.dumps(payload, sort_keys=True) + "\n").encode(),
                    "application/json",
                )
            else:
                self.send_error(404)

        def log_message(self, fmt, *args):
            # Scrapes land every few seconds; keep them out of stderr.
            logger.debug("http: " + fmt, *args)

    return Handler


class MetricsHTTPServer:
    """Serves ``GET /metrics`` for a registry on a daemon thread."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        port: int = 0,
        host: str = "0.0.0.0",
        health: Optional[Callable[[], dict]] = None,
    ):
        """``health`` — a callable returning the /healthz JSON body
        (``HealthMonitor.healthz_payload``); /healthz then answers
        200 (healthy) / 503 (critical verdicts active) with the
        score, instead of the bare liveness ``ok``."""
        self.registry = registry or get_registry()
        self._server = ThreadingHTTPServer(
            (host, port), _make_handler(self.registry, health=health)
        )
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="metrics-http",
                daemon=True,
            )
            self._thread.start()
            logger.info(
                "metrics endpoint on http://127.0.0.1:%d/metrics",
                self.port,
            )

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
