"""Cross-layer observability substrate: metrics + event tracing.

Every layer of the stack (master node management, rendezvous,
auto-scaling, flash checkpoint, elastic trainer) records what it is
doing through this package, so "what is the job doing right now" and
"where did the recovery time go" are answerable from one place:

* :mod:`dlrover_tpu.obs.metrics` — a process-local registry of
  counters/gauges/histograms with labels, rendered in Prometheus text
  exposition format by ``registry.render()`` (no ``prometheus_client``
  dependency — the whole package is stdlib-only by contract, enforced
  by tests/test_obs.py::test_no_prometheus_or_otel_imports).
* :mod:`dlrover_tpu.obs.tracer` — lightweight events/spans with
  monotonic timestamps and process/role/rank tags, exported as JSON
  lines when ``DLROVER_TPU_TRACE_FILE`` is set. Disabled (the
  default) every hook is a None-check costing well under a
  microsecond, so instrumented hot paths stay hot.
* :mod:`dlrover_tpu.obs.trace_store` — the master-side distributed-
  trace assembler: bounded per-trace span timelines (serving request
  hops with TTFT phase spans, remediation decision chains, rendezvous
  rounds) fed by the in-master planes and the snapshot event channel,
  queryable via the ``TraceQueryRequest`` RPC and
  ``obs_report --trace``.
* :mod:`dlrover_tpu.obs.timeline` — folds an event stream into the
  canonical recovery breakdown ``failure-detect -> rendezvous ->
  restore -> first-step -> 90%-throughput`` that the chaos drills
  assert on.
* :mod:`dlrover_tpu.obs.exposition` — a stdlib HTTP server giving the
  master a ``GET /metrics`` Prometheus endpoint.
* :mod:`dlrover_tpu.obs.fleet` — the master-side
  :class:`FleetAggregator` merging per-host registry snapshots
  (shipped by agents over the control plane) into host-labeled series
  and cross-host aggregates, with TTL age-out for departed nodes.
* :mod:`dlrover_tpu.obs.goodput` — exhaustive goodput/badput wall-time
  attribution (productive / compile / data_wait / checkpoint /
  recovery / idle_unknown) over the job's event stream.
* :mod:`dlrover_tpu.obs.flight_recorder` — the always-on black box:
  a bounded in-memory ring (WARNING+ logs, last step/loss notes)
  plus faulthandler / excepthook / SIGUSR1 crash hooks that dump a
  JSON bundle with all-thread Python stacks to the per-run forensics
  dir on any crash or hang.
* :mod:`dlrover_tpu.obs.postmortem` — folds a forensics dir (bundles,
  faulthandler stack dumps, traces) into the "last 60 seconds before
  failure" report ``tools/obs_report.py --postmortem`` prints.
* :mod:`dlrover_tpu.obs.profiling` — perf observability for the hot
  path: per-step wall-time attribution (data_wait / h2d_stage /
  compile / dispatch / device_execute), recompile counters per jitted
  function,
  a live MFU gauge from XLA cost analysis, and the on-demand PROFILE
  capture protocol (master action -> agent request file -> trainer
  digest -> diagnostics history).
* :mod:`dlrover_tpu.obs.beacon` — the collective-stall progress
  beacon: a fixed-size mmap'd progress stamp (step / microbatch /
  phase / monotonic ts) the trainer rewrites at every phase boundary,
  readable by other processes even when the trainer is wedged inside
  a C-level collective.
* :mod:`dlrover_tpu.obs.stall` — the master-side
  :class:`StallCorrelator` over the fleet's shipped beacons: splits
  fleet-wide stalls from single-host laggards, emits the localized
  ``collective_stall`` verdict, mints ``stall.incident`` traces, and
  queues the coordinated all-host DIAGNOSE+PROFILE capture.
* :mod:`dlrover_tpu.obs.timeseries` — the bounded in-memory
  time-series store (labeled series, ring retention with coarse
  downsampling, windowed mean/percentile/rate/robust-slope queries)
  the measurement plane records history into.
* :mod:`dlrover_tpu.obs.health` — the detector engine over that
  history: throughput-degradation / goodput-SLO / data-starvation /
  recompile-storm / RSS-growth / straggler-persistence /
  heartbeat-gap verdicts with evidence windows, the composite
  ``dlrover_job_health_score``, auto-queued PROFILE/DIAGNOSE actions,
  and brain persistence — plus the per-tenant SLO error-budget engine
  with multi-window burn-rate alerting.
* :mod:`dlrover_tpu.obs.capacity` — the pool capacity accounting
  plane: a per-slice state-interval ledger (idle / allocated /
  preempting / draining / restoring) producing per-tenant chip-second
  totals, productive chip-seconds from goodput joins, and
  goodput-per-chip — the substrate for capacity-aware autoscaling.

The functions re-exported here are the instrumentation surface the
rest of the codebase uses::

    from dlrover_tpu import obs

    _RELAUNCHES = obs.counter("dlrover_node_relaunch_total", "...")
    _RELAUNCHES.inc(type="worker")
    obs.event("node.relaunch", node_id=3)
    with obs.span("ckpt.save"):
        ...
"""

from dlrover_tpu.obs.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    gauge,
    get_registry,
    histogram,
)
from dlrover_tpu.obs.tracer import (  # noqa: F401
    EventTracer,
    IdSource,
    TraceContext,
    activate,
    configure_tracer,
    current_context,
    disable_tracer,
    event,
    extract,
    get_tracer,
    inject,
    new_span_id,
    new_trace_context,
    new_trace_id,
    set_id_source,
    span,
    tracing_enabled,
)
from dlrover_tpu.obs.trace_store import (  # noqa: F401
    TraceStore,
    render_trace,
    span_tree,
)
from dlrover_tpu.obs.beacon import (  # noqa: F401
    ProgressBeacon,
    beacon_file,
    progress_key,
    read_beacon,
    stamp_age,
)
from dlrover_tpu.obs.fleet import FleetAggregator  # noqa: F401
from dlrover_tpu.obs.flight_recorder import (  # noqa: F401
    FlightRecorder,
    forensics_dir,
    get_flight_recorder,
    install_flight_recorder,
    recorder_note,
    uninstall_flight_recorder,
)
from dlrover_tpu.obs.goodput import (  # noqa: F401
    GoodputAccountant,
    GoodputReport,
    attribute_goodput,
    render_goodput,
)
from dlrover_tpu.obs.profiling import (  # noqa: F401
    CompileTracker,
    MfuMeter,
    StepPhaseProfiler,
)
from dlrover_tpu.obs.timeseries import (  # noqa: F401
    TimeSeriesStore,
    WindowStats,
)

# Imported last: health.py and capacity.py instrument through
# `dlrover_tpu.obs` itself (obs.counter/obs.gauge are bound above by
# the time this executes), mirroring how the master modules import
# the package.
from dlrover_tpu.obs.health import (  # noqa: E402,F401
    HealthMonitor,
    HealthVerdict,
    SLOSpec,
    render_health,
    slos_from_env,
)
from dlrover_tpu.obs.capacity import (  # noqa: E402,F401
    CapacityLedger,
    SliceInterval,
    render_capacity,
)
from dlrover_tpu.obs.stall import (  # noqa: E402,F401
    StallCorrelator,
    render_stall,
)
