"""Lightweight event tracer: spans/events with process/role/rank tags.

An event is one dict — ``{"name", "ts" (wall), "mono" (monotonic),
"pid", "role", "rank", ...tags}`` — appended to an in-memory ring and,
when a JSONL sink is configured, written as one line per event (flushed
immediately, so a SIGKILLed process loses at most the event in flight).
Spans are paired events: entering emits nothing, exiting emits
``name`` with ``dur_s`` and the span's start timestamps; nesting is
tracked per-thread and recorded as a ``parent`` tag.

Tracing is OFF by default. It turns on when ``DLROVER_TPU_TRACE_FILE``
(JSONL export path) or ``DLROVER_TPU_TRACE=1`` (in-memory only) is set
in the environment at first use, or explicitly via
:func:`configure_tracer`. Disabled, the module-level :func:`event` is
a single None-check and :func:`span` returns a shared no-op context
manager — well under a microsecond either way, cheap enough for
per-step hot paths.

Role/rank tags come from the environment: ``DLROVER_TPU_ROLE`` (set by
the elastic launcher) and ``JAX_PROCESS_INDEX`` /
``DLROVER_TPU_NODE_RANK``.

**Distributed tracing** (docs/OBSERVABILITY.md "Distributed
tracing"): a W3C-trace-context-shaped :class:`TraceContext`
(``trace_id`` / ``span_id`` / ``parent_span_id``, deterministic hex
ids from an injectable RNG seam — :func:`set_id_source`) can be
*activated* on the current thread (:func:`activate`); while active,
every span minted here chains onto it (child span ids, the same
trace id) and every event is tagged with the trace. :func:`inject`
serializes the active context for an RPC envelope and
:func:`extract` rebuilds it on the receiving side — the propagation
pair ``common/comm.py`` rides on every control-plane RPC. With no
active context both are a dict-lookup + ``None``, cheap enough for
the serving hot loop.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import random
import threading
import time
from typing import Dict, List, Optional

TRACE_FILE_ENV = "DLROVER_TPU_TRACE_FILE"
TRACE_ENV = "DLROVER_TPU_TRACE"

_RING_SIZE = 4096

# Per-thread stack maps (span parents, active trace contexts) are
# swept for dead threads once they grow past this many entries: a
# churny replica/supervisor thread pool must not grow tracer state
# unboundedly. Entries also delete eagerly when their stack empties,
# so balanced span/activation usage never reaches the sweep.
_STACKS_SWEEP_AT = 64


class TraceContext:
    """One position in a distributed trace: which trace this process
    is contributing to (``trace_id``), the span it is inside
    (``span_id``), and that span's parent (``parent_span_id``, ""
    at the root)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(
        self,
        trace_id: str,
        span_id: str,
        parent_span_id: str = "",
    ):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id

    def child(self) -> "TraceContext":
        """A new context for work caused by this one (same trace,
        fresh span id, parented here)."""
        return TraceContext(self.trace_id, new_span_id(), self.span_id)

    def to_dict(self) -> Dict[str, str]:
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_span_id:
            d["parent_span_id"] = self.parent_span_id
        return d

    def __repr__(self) -> str:  # debugging only
        return (
            f"TraceContext({self.trace_id[:8]}…/{self.span_id[:8]}…)"
        )


class IdSource:
    """Hex trace/span id generator over an injectable ``random.Random``
    — tests seed it for fully deterministic ids (there is no wall-
    clock or os.urandom dependence anywhere in id minting)."""

    def __init__(self, rng: Optional[random.Random] = None):
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()

    def trace_id(self) -> str:
        with self._lock:
            return f"{self._rng.getrandbits(128):032x}"

    def span_id(self) -> str:
        with self._lock:
            return f"{self._rng.getrandbits(64):016x}"


_id_source = IdSource()


def set_id_source(source: IdSource) -> IdSource:
    """Swap the id generator (tests pass ``IdSource(random.Random(0))``
    for reproducible ids). Returns the previous source."""
    global _id_source
    prev = _id_source
    _id_source = source
    return prev


def new_trace_id() -> str:
    return _id_source.trace_id()


def new_span_id() -> str:
    return _id_source.span_id()


def new_trace_context() -> TraceContext:
    """A root context for a brand-new trace."""
    return TraceContext(new_trace_id(), new_span_id(), "")


# -- per-thread active context ----------------------------------------------
# Keyed by the Thread OBJECT in a plain dict (NOT threading.local:
# local values can linger with churny thread pools, and an explicit
# map is sweepable; NOT the thread ident: the OS recycles idents, so
# an ident-keyed entry orphaned by a thread that died mid-span could
# be inherited — and its trace context mis-attributed — by an
# unrelated new thread. Thread objects are never recycled). Entries
# are deleted the moment their stack empties; the sweep below
# catches stacks orphaned by threads that died mid-activation.

_ctx_lock = threading.Lock()
_ctx_stacks: Dict[threading.Thread, list] = {}


def _sweep_dead_threads(stacks: Dict[threading.Thread, list]) -> None:
    """Drop stack entries belonging to dead threads. Caller holds the
    map's lock. O(entries) — only invoked past the high-water mark."""
    if len(stacks) < _STACKS_SWEEP_AT:
        return
    for t in [t for t in stacks if not t.is_alive()]:
        del stacks[t]


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]):
    """Make ``ctx`` the current trace context for this thread for the
    duration of the ``with`` block (None = no-op). Server handlers
    activate the extracted caller context so the spans/events they
    emit land in the caller's trace."""
    if ctx is None:
        yield None
        return
    thread = threading.current_thread()
    with _ctx_lock:
        stack = _ctx_stacks.get(thread)
        if stack is None:
            _sweep_dead_threads(_ctx_stacks)
            stack = _ctx_stacks[thread] = []
        stack.append(ctx)
    try:
        yield ctx
    finally:
        with _ctx_lock:
            stack = _ctx_stacks.get(thread)
            if stack:
                stack.pop()
                if not stack:
                    del _ctx_stacks[thread]


def current_context() -> Optional[TraceContext]:
    """The active trace context on this thread (None when outside any
    activation/span)."""
    stack = _ctx_stacks.get(threading.current_thread())
    return stack[-1] if stack else None


def inject() -> Optional[Dict[str, str]]:
    """The active context as an envelope dict for an outgoing RPC
    (None — and no allocation — when no trace is active)."""
    ctx = current_context()
    return ctx.to_dict() if ctx is not None else None


def extract(carrier) -> Optional[TraceContext]:
    """Rebuild a :class:`TraceContext` from an envelope dict (the
    value :func:`inject` produced on the caller). Returns None for
    None/empty/malformed carriers — propagation must never make an
    RPC fail."""
    if not isinstance(carrier, dict):
        return None
    trace_id = carrier.get("trace_id")
    span_id = carrier.get("span_id")
    if not trace_id or not span_id:
        return None
    return TraceContext(
        str(trace_id),
        str(span_id),
        str(carrier.get("parent_span_id", "") or ""),
    )


def _process_tags() -> Dict[str, object]:
    # Shared role/rank env contract (one definition for logs + traces).
    from dlrover_tpu.common.log import role_and_rank

    role, rank = role_and_rank()
    return {
        "pid": os.getpid(),
        "role": role or "unknown",
        "rank": rank,
    }


class Span:
    """Context manager produced by :meth:`EventTracer.span`.

    When a :class:`TraceContext` is active on the thread, the span
    mints a child span id, becomes the active context for its body
    (so nested spans and RPCs issued inside it chain correctly), and
    records ``trace_id`` / ``span_id`` / ``parent_span_id`` on its
    exit event. With no active context it costs exactly what it
    always did — names-only nesting, no id minting."""

    __slots__ = (
        "_tracer", "name", "tags", "_t0_wall", "_t0_mono", "_ctx",
    )

    def __init__(self, tracer: "EventTracer", name: str, tags: dict):
        self._tracer = tracer
        self.name = name
        self.tags = tags
        self._t0_wall = 0.0
        self._t0_mono = 0.0
        self._ctx: Optional[TraceContext] = None

    def __enter__(self) -> "Span":
        self._t0_wall = time.time()
        self._t0_mono = time.monotonic()
        parent_ctx = current_context()
        if parent_ctx is not None:
            self._ctx = parent_ctx.child()
            thread = threading.current_thread()
            with _ctx_lock:
                stack = _ctx_stacks.get(thread)
                if stack is None:
                    _sweep_dead_threads(_ctx_stacks)
                    stack = _ctx_stacks[thread] = []
                stack.append(self._ctx)
        self._tracer._span_stack().append(self.name)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        stack = self._tracer._span_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        parent = stack[-1] if stack else ""
        if not stack:
            self._tracer._drop_span_stack()
        if self._ctx is not None:
            thread = threading.current_thread()
            with _ctx_lock:
                cstack = _ctx_stacks.get(thread)
                if cstack and cstack[-1] is self._ctx:
                    cstack.pop()
                    if not cstack:
                        del _ctx_stacks[thread]
        dur = time.monotonic() - self._t0_mono
        extra = dict(self.tags)
        if parent:
            extra["parent"] = parent
        if self._ctx is not None:
            extra["trace_id"] = self._ctx.trace_id
            extra["span_id"] = self._ctx.span_id
            if self._ctx.parent_span_id:
                extra["parent_span_id"] = self._ctx.parent_span_id
        if exc_type is not None:
            extra["error"] = exc_type.__name__
        self._tracer._emit(
            self.name,
            ts=self._t0_wall,
            mono=self._t0_mono,
            dur_s=round(dur, 6),
            **extra,
        )


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NOOP_SPAN = _NoopSpan()


class EventTracer:
    def __init__(
        self,
        sink_path: Optional[str] = None,
        ring_size: int = _RING_SIZE,
    ):
        self.sink_path = sink_path
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=ring_size
        )
        # Total events ever emitted: the arrival-order cursor for
        # events_since (a mono-timestamp watermark would silently drop
        # spans, which are emitted at exit but stamped with their
        # START mono).
        self._count = 0
        self._file = None
        # Per-thread span-name stacks, keyed by Thread OBJECT in an
        # explicit dict (NOT threading.local, and not the recyclable
        # thread ident — see _ctx_stacks): entries delete when their
        # stack empties, and a sweep drops stacks orphaned by threads
        # that died mid-span — a churny replica/supervisor thread
        # pool can't grow tracer state unboundedly.
        self._stacks_lock = threading.Lock()
        self._stacks: Dict[threading.Thread, list] = {}
        if sink_path:
            # Line-buffered append; O_APPEND keeps concurrent
            # single-line writes from interleaving mid-line.
            self._file = open(sink_path, "a", buffering=1)

    def _span_stack(self) -> list:
        thread = threading.current_thread()
        stack = self._stacks.get(thread)
        if stack is None:
            with self._stacks_lock:
                stack = self._stacks.get(thread)
                if stack is None:
                    _sweep_dead_threads(self._stacks)
                    stack = self._stacks[thread] = []
        return stack

    def _drop_span_stack(self) -> None:
        """Delete this thread's (now empty) span stack entry."""
        thread = threading.current_thread()
        with self._stacks_lock:
            stack = self._stacks.get(thread)
            if stack is not None and not stack:
                del self._stacks[thread]

    # -- emission --------------------------------------------------------

    def _emit(self, name: str, ts: Optional[float] = None,
              mono: Optional[float] = None, **tags) -> dict:
        record = {
            "name": name,
            "ts": ts if ts is not None else time.time(),
            "mono": mono if mono is not None else time.monotonic(),
            **_process_tags(),
            **tags,
        }
        if "trace_id" not in record:
            # A point event inside an active trace belongs to the
            # current span (parent_span_id); spans set their own ids
            # above and skip this.
            ctx = current_context()
            if ctx is not None:
                record["trace_id"] = ctx.trace_id
                record["parent_span_id"] = ctx.span_id
        with self._lock:
            self._ring.append(record)
            self._count += 1
            if self._file is not None:
                try:
                    self._file.write(
                        json.dumps(record, default=str) + "\n"
                    )
                except (OSError, ValueError):
                    # A dead sink must never take training down.
                    self._file = None
        return record

    def event(self, name: str, **tags) -> dict:
        return self._emit(name, **tags)

    def span(self, name: str, **tags) -> Span:
        return Span(self, name, tags)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def events_since(self, cursor: int):
        """``(new_events, next_cursor)`` in ARRIVAL order. ``cursor``
        is the value returned by the previous call (0 to start).
        Events that fell off the bounded ring before being read are
        lost; a cursor from a replaced tracer (> count) resets."""
        with self._lock:
            count = self._count
            if cursor < 0 or cursor > count:
                cursor = max(0, count - len(self._ring))
            new = count - max(cursor, count - len(self._ring))
            events = list(self._ring)[-new:] if new > 0 else []
            return events, count

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


# -- module-level fast path -------------------------------------------------

_tracer: Optional[EventTracer] = None
_init_done = False
_init_lock = threading.Lock()


def _lazy_init() -> Optional[EventTracer]:
    global _tracer, _init_done
    with _init_lock:
        if _init_done:
            return _tracer
        path = os.getenv(TRACE_FILE_ENV, "")
        if path:
            _tracer = EventTracer(sink_path=path)
        elif os.getenv(TRACE_ENV, "") == "1":
            _tracer = EventTracer()
        _init_done = True
        return _tracer


def configure_tracer(
    sink_path: Optional[str] = None, ring_size: int = _RING_SIZE
) -> EventTracer:
    """Explicitly enable tracing (tests, notebooks). Replaces any
    active tracer."""
    global _tracer, _init_done
    with _init_lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = EventTracer(sink_path=sink_path, ring_size=ring_size)
        _init_done = True
        return _tracer


def disable_tracer() -> None:
    global _tracer, _init_done
    with _init_lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = None
        _init_done = True


def get_tracer() -> Optional[EventTracer]:
    if not _init_done:
        return _lazy_init()
    return _tracer


def tracing_enabled() -> bool:
    return get_tracer() is not None


def event(name: str, **tags) -> Optional[dict]:
    """Record an event; a no-op None-check when tracing is disabled."""
    tr = _tracer if _init_done else _lazy_init()
    if tr is None:
        return None
    return tr.event(name, **tags)


def span(name: str, **tags):
    """Span context manager; a shared no-op when tracing is disabled."""
    tr = _tracer if _init_done else _lazy_init()
    if tr is None:
        return _NOOP_SPAN
    return tr.span(name, **tags)
