"""Lightweight event tracer: spans/events with process/role/rank tags.

An event is one dict — ``{"name", "ts" (wall), "mono" (monotonic),
"pid", "role", "rank", ...tags}`` — appended to an in-memory ring and,
when a JSONL sink is configured, written as one line per event (flushed
immediately, so a SIGKILLed process loses at most the event in flight).
Spans are paired events: entering emits nothing, exiting emits
``name`` with ``dur_s`` and the span's start timestamps; nesting is
tracked per-thread and recorded as a ``parent`` tag.

Tracing is OFF by default. It turns on when ``DLROVER_TPU_TRACE_FILE``
(JSONL export path) or ``DLROVER_TPU_TRACE=1`` (in-memory only) is set
in the environment at first use, or explicitly via
:func:`configure_tracer`. Disabled, the module-level :func:`event` is
a single None-check and :func:`span` returns a shared no-op context
manager — well under a microsecond either way, cheap enough for
per-step hot paths.

Role/rank tags come from the environment: ``DLROVER_TPU_ROLE`` (set by
the elastic launcher) and ``JAX_PROCESS_INDEX`` /
``DLROVER_TPU_NODE_RANK``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

TRACE_FILE_ENV = "DLROVER_TPU_TRACE_FILE"
TRACE_ENV = "DLROVER_TPU_TRACE"

_RING_SIZE = 4096


def _process_tags() -> Dict[str, object]:
    # Shared role/rank env contract (one definition for logs + traces).
    from dlrover_tpu.common.log import role_and_rank

    role, rank = role_and_rank()
    return {
        "pid": os.getpid(),
        "role": role or "unknown",
        "rank": rank,
    }


class Span:
    """Context manager produced by :meth:`EventTracer.span`."""

    __slots__ = ("_tracer", "name", "tags", "_t0_wall", "_t0_mono")

    def __init__(self, tracer: "EventTracer", name: str, tags: dict):
        self._tracer = tracer
        self.name = name
        self.tags = tags
        self._t0_wall = 0.0
        self._t0_mono = 0.0

    def __enter__(self) -> "Span":
        self._t0_wall = time.time()
        self._t0_mono = time.monotonic()
        self._tracer._span_stack().append(self.name)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        stack = self._tracer._span_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        parent = stack[-1] if stack else ""
        dur = time.monotonic() - self._t0_mono
        extra = dict(self.tags)
        if parent:
            extra["parent"] = parent
        if exc_type is not None:
            extra["error"] = exc_type.__name__
        self._tracer._emit(
            self.name,
            ts=self._t0_wall,
            mono=self._t0_mono,
            dur_s=round(dur, 6),
            **extra,
        )


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NOOP_SPAN = _NoopSpan()


class EventTracer:
    def __init__(
        self,
        sink_path: Optional[str] = None,
        ring_size: int = _RING_SIZE,
    ):
        self.sink_path = sink_path
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=ring_size
        )
        # Total events ever emitted: the arrival-order cursor for
        # events_since (a mono-timestamp watermark would silently drop
        # spans, which are emitted at exit but stamped with their
        # START mono).
        self._count = 0
        self._file = None
        self._local = threading.local()
        if sink_path:
            # Line-buffered append; O_APPEND keeps concurrent
            # single-line writes from interleaving mid-line.
            self._file = open(sink_path, "a", buffering=1)

    def _span_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
        return stack

    # -- emission --------------------------------------------------------

    def _emit(self, name: str, ts: Optional[float] = None,
              mono: Optional[float] = None, **tags) -> dict:
        record = {
            "name": name,
            "ts": ts if ts is not None else time.time(),
            "mono": mono if mono is not None else time.monotonic(),
            **_process_tags(),
            **tags,
        }
        with self._lock:
            self._ring.append(record)
            self._count += 1
            if self._file is not None:
                try:
                    self._file.write(
                        json.dumps(record, default=str) + "\n"
                    )
                except (OSError, ValueError):
                    # A dead sink must never take training down.
                    self._file = None
        return record

    def event(self, name: str, **tags) -> dict:
        return self._emit(name, **tags)

    def span(self, name: str, **tags) -> Span:
        return Span(self, name, tags)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def events_since(self, cursor: int):
        """``(new_events, next_cursor)`` in ARRIVAL order. ``cursor``
        is the value returned by the previous call (0 to start).
        Events that fell off the bounded ring before being read are
        lost; a cursor from a replaced tracer (> count) resets."""
        with self._lock:
            count = self._count
            if cursor < 0 or cursor > count:
                cursor = max(0, count - len(self._ring))
            new = count - max(cursor, count - len(self._ring))
            events = list(self._ring)[-new:] if new > 0 else []
            return events, count

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


# -- module-level fast path -------------------------------------------------

_tracer: Optional[EventTracer] = None
_init_done = False
_init_lock = threading.Lock()


def _lazy_init() -> Optional[EventTracer]:
    global _tracer, _init_done
    with _init_lock:
        if _init_done:
            return _tracer
        path = os.getenv(TRACE_FILE_ENV, "")
        if path:
            _tracer = EventTracer(sink_path=path)
        elif os.getenv(TRACE_ENV, "") == "1":
            _tracer = EventTracer()
        _init_done = True
        return _tracer


def configure_tracer(
    sink_path: Optional[str] = None, ring_size: int = _RING_SIZE
) -> EventTracer:
    """Explicitly enable tracing (tests, notebooks). Replaces any
    active tracer."""
    global _tracer, _init_done
    with _init_lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = EventTracer(sink_path=sink_path, ring_size=ring_size)
        _init_done = True
        return _tracer


def disable_tracer() -> None:
    global _tracer, _init_done
    with _init_lock:
        if _tracer is not None:
            _tracer.close()
        _tracer = None
        _init_done = True


def get_tracer() -> Optional[EventTracer]:
    if not _init_done:
        return _lazy_init()
    return _tracer


def tracing_enabled() -> bool:
    return get_tracer() is not None


def event(name: str, **tags) -> Optional[dict]:
    """Record an event; a no-op None-check when tracing is disabled."""
    tr = _tracer if _init_done else _lazy_init()
    if tr is None:
        return None
    return tr.event(name, **tags)


def span(name: str, **tags):
    """Span context manager; a shared no-op when tracing is disabled."""
    tr = _tracer if _init_done else _lazy_init()
    if tr is None:
        return _NOOP_SPAN
    return tr.span(name, **tags)
