"""Fleet health plane: detectors over time-series history -> verdicts.

This is the interpretation layer between "we export metrics" and "the
control plane acts on them" (ROADMAP items 1 and 2): a master-side
:class:`HealthMonitor` periodically evaluates a set of detectors over
the :class:`~dlrover_tpu.obs.timeseries.TimeSeriesStore` the
measurement plane feeds (fleet snapshots, goodput recomputes, speed
EWMAs, compile counters) and turns history into typed
:class:`HealthVerdict` s:

========================  =====================================================
detector                  fires when
========================  =====================================================
throughput_degradation    a host's recent step-time window is materially
                          slower than its own preceding baseline window
                          AND the robust slope confirms a worsening trend
goodput_slo               the job's goodput ratio sits below the SLO
                          (after a startup grace period)
data_starvation           a host spends more than a threshold fraction of
                          wall time blocked on input (data_wait rate)
recompile_storm           a host's compile counter is climbing at storm
                          rate (retracing in the steady state)
rss_growth                a host's RSS shows a sustained robust upward
                          slope plus material relative growth (leak)
straggler_persistence     the speed monitor has scored the same host a
                          straggler for N consecutive evaluations
heartbeat_gap             an alive node's last heartbeat is a large
                          fraction of the way to the timeout
replica_unhealthy         a serving replica holds dispatched requests
                          without progress past the router's timeout
                          (or is draining and never came back) — the
                          verdict the remediation ladder drains,
                          restarts, then replaces on
slo_burn                  a tenant SLO's error budget burns past the
                          fast (5m AND 1h, critical) or slow (6h AND
                          3d, warn) multi-window burn-rate threshold
========================  =====================================================

Each verdict carries a severity (``info``/``warn``/``critical``), the
evidence window of series samples that convicted it, and a suggested
:class:`~dlrover_tpu.common.constants.EventAction`. Critical verdicts
with an action auto-queue it through the servicer's per-node action
FIFO (cooldown-limited), so a degrading host gets a PROFILE capture
*while it is still slow*. All verdicts land in a bounded history
served by the ``HealthQueryRequest`` RPC, are exported as
``dlrover_health_verdicts_total{detector,severity}`` plus the
composite ``dlrover_job_health_score`` gauge, and are persisted to
the brain datastore so the policy engine (ROADMAP item 2) consumes
the same channel.

On top of the detector suite sits the **SLO budget engine**
(ROADMAP item 5's accountability half): declarative per-tenant
:class:`SLOSpec` objectives (training goodput >= X, serving
TTFT/TPOT p99 <= Y) tracked as error budgets over the time-series
store, with Google-SRE-style multi-window burn-rate detection — the
fast pair (5m AND 1h) at >= 14.4x budget burn fires a ``critical``
``slo_burn`` verdict (page), the slow pair (6h AND 3d) at >= 1x
fires ``warn`` (ticket). Budget remaining over each SLO's period is
exported as ``dlrover_slo_budget_remaining{tenant,slo}`` every
evaluation, and :meth:`HealthMonitor.slo_snapshot` feeds the
``CapacityQueryRequest`` RPC / ``obs_report --capacity``.

Every threshold reads ``DLROVER_TPU_HEALTH_<KNOB>`` (see DEFAULTS),
overridable per-instance via the ``config`` dict; the clock is
injectable so detector tests drive simulated hours hermetically.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.constants import EventAction
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs.timeseries import TimeSeriesStore

logger = get_logger("obs.health")

HEALTH_ENV_PREFIX = "DLROVER_TPU_HEALTH_"

SEVERITY_INFO = "info"
SEVERITY_WARN = "warn"
SEVERITY_CRITICAL = "critical"
SEVERITIES = (SEVERITY_INFO, SEVERITY_WARN, SEVERITY_CRITICAL)

# Composite-score penalty per ACTIVE verdict of each severity;
# score = max(0, 1 - sum(penalties)).
SEVERITY_PENALTY = {
    SEVERITY_INFO: 0.0,
    SEVERITY_WARN: 0.1,
    SEVERITY_CRITICAL: 0.3,
}

# How many evidence samples ride a verdict (the tail of the window).
EVIDENCE_POINTS = 32

_VERDICTS_TOTAL = obs.counter(
    "dlrover_health_verdicts_total",
    "Health verdicts emitted by the master's detector engine, "
    "by detector and severity",
    ("detector", "severity"),
)
_HEALTH_SCORE = obs.gauge(
    "dlrover_job_health_score",
    "Composite job health in [0, 1]: 1 minus severity-weighted "
    "penalties of the currently-active health verdicts",
)
_SLO_BUDGET_REMAINING = obs.gauge(
    "dlrover_slo_budget_remaining",
    "Fraction of each tenant SLO's error budget left over its "
    "period (1 = untouched, 0 = exhausted)",
    ("tenant", "slo"),
)

# Every knob a detector reads, with its default. Override per knob via
# DLROVER_TPU_HEALTH_<NAME-upper> or the HealthMonitor(config=) dict
# (config wins). Windows are seconds.
DEFAULTS: Dict[str, float] = {
    # engine
    "interval_s": 15.0,
    "window_s": 120.0,
    "min_points": 3.0,
    "action_cooldown_s": 300.0,
    "history": 256.0,
    # throughput degradation (per-host step time, recent vs baseline)
    "degradation_warn_ratio": 1.3,
    "degradation_crit_ratio": 1.8,
    # goodput SLO
    "goodput_slo": 0.75,
    "goodput_critical": 0.4,
    "goodput_grace_s": 300.0,
    # data starvation (fraction of wall time blocked on input)
    "starvation_warn_frac": 0.25,
    "starvation_crit_frac": 0.5,
    # recompile storm (compiles per minute in the steady state)
    "recompile_warn_per_min": 2.0,
    "recompile_crit_per_min": 6.0,
    # RSS growth (robust MB/s slope + relative growth over the window)
    "rss_warn_mb_per_s": 0.5,
    "rss_crit_mb_per_s": 4.0,
    "rss_min_growth_frac": 0.05,
    # straggler persistence (consecutive evaluations scored slow)
    "straggler_warn_ticks": 3.0,
    "straggler_crit_ticks": 6.0,
    # heartbeat gap (fraction of the heartbeat timeout)
    "heartbeat_warn_frac": 0.5,
    "heartbeat_crit_frac": 0.8,
    # replica_unhealthy: staleness as a multiple of the serving
    # router's progress timeout that escalates warn -> critical
    "replica_stall_crit_ratio": 2.0,
    # SLO burn-rate windows + thresholds (Google SRE multi-window
    # multi-burn-rate): the fast pair pages, the slow pair tickets.
    # 14.4x on a 30d budget spends ~2% of it in one hour.
    "slo_fast_burn": 14.4,
    "slo_slow_burn": 1.0,
    "slo_fast_short_s": 300.0,       # 5m
    "slo_fast_long_s": 3600.0,       # 1h
    "slo_slow_short_s": 21600.0,     # 6h
    "slo_slow_long_s": 259200.0,     # 3d
}


@dataclasses.dataclass
class SLOSpec:
    """One declarative per-tenant service-level objective.

    ``direction`` says which side of ``objective`` is good:
    ``"min"`` — the series must stay AT OR ABOVE the objective
    (training goodput >= 0.8); ``"max"`` — it must stay at or below
    (serving TTFT p99 <= 0.5s). ``budget`` is the allowed bad-sample
    fraction over ``period_s``; burn rate is bad_fraction / budget.
    ``labels`` scope the series query (e.g. ``{"tenant": "a"}``).
    """

    tenant: str
    slo: str
    series: str
    objective: float
    direction: str = "min"
    budget: float = 0.05
    period_s: float = 3.0 * 86400.0
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)

    def key(self) -> str:
        return f"{self.tenant}/{self.slo}"

    def to_dict(self) -> dict:
        return {
            "tenant": self.tenant,
            "slo": self.slo,
            "series": self.series,
            "objective": self.objective,
            "direction": self.direction,
            "budget": self.budget,
            "period_s": self.period_s,
            "labels": dict(self.labels),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SLOSpec":
        return cls(
            tenant=str(d.get("tenant", "default")),
            slo=str(d.get("slo", "slo")),
            series=str(d.get("series", "")),
            objective=float(d.get("objective", 0.0)),
            direction=str(d.get("direction", "min")),
            budget=float(d.get("budget", 0.05)),
            period_s=float(d.get("period_s", 3.0 * 86400.0)),
            labels={
                str(k): str(v)
                for k, v in (d.get("labels") or {}).items()
            },
        )


def slos_from_env() -> List["SLOSpec"]:
    """Parse ``DLROVER_TPU_HEALTH_SLOS`` (a JSON list of SLOSpec
    dicts) — the deploy-time way to declare objectives without code.
    Bad JSON degrades to no SLOs, never to a crash."""
    raw = os.getenv(HEALTH_ENV_PREFIX + "SLOS", "")
    if not raw:
        return []
    try:
        data = json.loads(raw)
        return [SLOSpec.from_dict(d) for d in data]
    except Exception:  # noqa: BLE001
        logger.warning(
            "bad %sSLOS JSON %r; ignoring", HEALTH_ENV_PREFIX, raw
        )
        return []


@dataclasses.dataclass
class HealthVerdict:
    """One detector's finding about one subject (a host or the job)."""

    detector: str
    severity: str
    message: str
    node_id: int = -1
    host: str = ""
    suggested_action: str = ""  # an EventAction value, or ""
    evidence_series: str = ""
    # The convicting samples: (ts, value) tail of the query window.
    evidence: List[Tuple[float, float]] = dataclasses.field(
        default_factory=list
    )
    # Detector-specific numbers (baseline mean, recent mean, ratio,
    # slope, ...), for renderers and the policy engine.
    metrics: Dict[str, float] = dataclasses.field(default_factory=dict)
    timestamp: float = 0.0
    resolved: bool = False

    def key(self) -> Tuple[str, str, int]:
        return (self.detector, self.host, self.node_id)

    def to_dict(self) -> dict:
        return {
            "detector": self.detector,
            "severity": self.severity,
            "message": self.message,
            "node_id": self.node_id,
            "host": self.host,
            "suggested_action": self.suggested_action,
            "evidence_series": self.evidence_series,
            "evidence": [
                [round(ts, 3), round(v, 6)] for ts, v in self.evidence
            ],
            "metrics": {
                k: round(float(v), 6) for k, v in self.metrics.items()
            },
            "timestamp": round(self.timestamp, 3),
            "resolved": self.resolved,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "HealthVerdict":
        """Inverse of :meth:`to_dict` — warm-restart snapshots and
        tools rebuild verdicts from the JSON shape."""
        return cls(
            detector=str(d.get("detector", "")),
            severity=str(d.get("severity", SEVERITY_INFO)),
            message=str(d.get("message", "")),
            node_id=int(d.get("node_id", -1)),
            host=str(d.get("host", "")),
            suggested_action=str(d.get("suggested_action", "")),
            evidence_series=str(d.get("evidence_series", "")),
            evidence=[
                (float(p[0]), float(p[1]))
                for p in d.get("evidence", [])
                if isinstance(p, (list, tuple)) and len(p) == 2
            ],
            metrics={
                str(k): float(v)
                for k, v in (d.get("metrics") or {}).items()
            },
            timestamp=float(d.get("timestamp", 0.0)),
            resolved=bool(d.get("resolved", False)),
        )


def _verdict_sort_key(v: HealthVerdict):
    return (-SEVERITIES.index(v.severity), v.detector, v.host, v.node_id)


class HealthMonitor:
    """Evaluates the detector suite on a cadence and owns the verdict
    lifecycle (transitions, history, score, action queueing, brain
    persistence).

    Everything is injectable so the engine is hermetically testable:
    ``clock`` drives windows, ``action_sink(node_id, action)`` receives
    auto-queued actions (the JobMaster wires ``servicer.push_action``),
    ``brain`` is any object with the BrainService persistence surface,
    and ``heartbeat_ages`` overrides the job-manager probe.
    """

    def __init__(
        self,
        store: TimeSeriesStore,
        speed_monitor=None,
        job_manager=None,
        fleet=None,
        goodput=None,
        action_sink: Optional[Callable[[int, str], None]] = None,
        serving=None,
        brain=None,
        job_name: str = "default",
        heartbeat_timeout: float = 180.0,
        heartbeat_ages: Optional[Callable[[], Dict[int, float]]] = None,
        clock: Callable[[], float] = time.time,
        config: Optional[Dict[str, float]] = None,
        interval: Optional[float] = None,
        slos: Optional[List[SLOSpec]] = None,
    ):
        self.store = store
        self.speed_monitor = speed_monitor
        self.job_manager = job_manager
        self.fleet = fleet
        self.goodput = goodput
        self.action_sink = action_sink
        # Serving router (or any provider of ``unhealthy_replicas()``
        # facts) — the replica_unhealthy detector's feed; None on
        # training-only masters.
        self.serving = serving
        self.brain = brain
        self.job_name = job_name
        self.heartbeat_timeout = heartbeat_timeout
        self._heartbeat_ages = heartbeat_ages
        self.clock = clock
        self.slos: List[SLOSpec] = (
            list(slos) if slos is not None else slos_from_env()
        )
        # spec.key() -> last computed budget/burn numbers, refreshed
        # every evaluation tick (read by slo_snapshot()).
        self._slo_last: Dict[str, dict] = {}
        self._config = dict(config or {})
        self.interval = (
            interval
            if interval is not None
            else self._cfg("interval_s")
        )
        self._lock = threading.Lock()
        self._active: Dict[Tuple[str, str, int], HealthVerdict] = {}
        self._history: deque = deque(maxlen=int(self._cfg("history")))
        self._last_action: Dict[Tuple[str, str, int], float] = {}
        self._straggler_ticks: Dict[int, int] = {}
        self._evaluations = 0
        # Per-tick caches populated by evaluate_once (None outside a
        # tick, so directly-invoked detectors still compute live).
        self._tick_hosts: Optional[List[str]] = None
        self._tick_nodes: Optional[Dict[str, int]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Fired after verdict transitions (new/severity-change/
        # resolution) so the master's state journal can snapshot the
        # active set + cooldown stamps — a warm restart must not
        # re-fire a sticky verdict's action.
        self.on_state_change = None
        # Stall correlator (obs/stall.py), attached via attach_stall:
        # runs as a detector on this tick and feeds the
        # heartbeat_gap DIAGNOSE upgrade its silent-suspect set.
        self.stall = None
        self.detectors: List[Callable[[], List[HealthVerdict]]] = [
            self._detect_throughput_degradation,
            self._detect_goodput_slo,
            self._detect_data_starvation,
            self._detect_recompile_storm,
            self._detect_rss_growth,
            self._detect_straggler_persistence,
            self._detect_heartbeat_gap,
            self._detect_replica_unhealthy,
            self._detect_slo_burn,
        ]
        _HEALTH_SCORE.set(1.0)

    def attach_stall(self, correlator) -> None:
        """Plug a stall correlator (obs/stall.py) into the tick: its
        evaluate() joins the detector list — so collective_stall /
        fleet_stall verdicts get the engine's full transition
        lifecycle, action cooldowns, and persistence — and it gains
        the silent-node probe (heartbeat ages already past the
        critical fraction) that backs fleet-stall attribution."""
        self.stall = correlator

        def _silent_nodes():
            crit = (
                self._cfg("heartbeat_crit_frac")
                * max(self.heartbeat_timeout, 1e-9)
            )
            return {
                node_id: age
                for node_id, age in self.heartbeat_ages().items()
                if age >= crit
            }

        if getattr(correlator, "silent_probe", None) is None:
            correlator.silent_probe = _silent_nodes
        self.detectors.append(correlator.evaluate)

    # -- config -----------------------------------------------------------

    def _cfg(self, knob: str) -> float:
        if knob in self._config:
            return float(self._config[knob])
        env = os.getenv(HEALTH_ENV_PREFIX + knob.upper(), "")
        if env:
            try:
                return float(env)
            except ValueError:
                logger.warning(
                    "bad %s%s=%r; using default %s",
                    HEALTH_ENV_PREFIX, knob.upper(), env,
                    DEFAULTS[knob],
                )
        return DEFAULTS[knob]

    # -- engine lifecycle --------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="health-monitor", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — a detector bug must
                # not kill the monitor thread (and with it all future
                # verdicts)
                logger.warning("health evaluation failed", exc_info=True)

    # -- helpers -----------------------------------------------------------

    def _hosts(self) -> List[str]:
        """Hosts with a step-time series (the subjects of per-host
        detectors). Served from the per-tick cache when
        :meth:`evaluate_once` populated one — five detectors plus the
        brain persist would otherwise each rescan the full series
        table under the store lock every tick."""
        if self._tick_hosts is not None:
            return self._tick_hosts
        return self._scan_hosts()

    def _scan_hosts(self) -> List[str]:
        hosts = {
            ls.get("host", "")
            for ls in self.store.series_labels("host.step_time")
        } | {
            ls.get("host", "")
            for ls in self.store.series_labels("host.memory_mb")
        }
        return sorted(hosts - {""})

    def _node_for_host(self, host: str) -> int:
        if self._tick_nodes is not None:
            return self._tick_nodes.get(host, -1)
        if self.fleet is not None:
            node = self.fleet.node_for_host(host)
            if node is not None:
                return node
        return -1

    def _evidence(
        self, name: str, window_s: float, **labels: str
    ) -> List[Tuple[float, float]]:
        pts = self.store.points(name, window_s, **labels)
        return pts[-EVIDENCE_POINTS:]

    # -- detectors ---------------------------------------------------------

    def _detect_throughput_degradation(self) -> List[HealthVerdict]:
        """Recent step-time window vs the host's own preceding
        baseline window, confirmed by the robust slope — a host that
        *became* slow, as opposed to one that always was."""
        w = self._cfg("window_s")
        min_pts = int(self._cfg("min_points"))
        warn_r = self._cfg("degradation_warn_ratio")
        crit_r = self._cfg("degradation_crit_ratio")
        out: List[HealthVerdict] = []
        for host in self._hosts():
            recent = self.store.query(
                "host.step_time", w, host=host
            )
            baseline = self.store.query(
                "host.step_time", w, end_offset_s=w, host=host
            )
            if (
                recent is None
                or baseline is None
                or recent.count < min_pts
                or baseline.count < min_pts
                or baseline.mean <= 0
            ):
                continue
            ratio = recent.mean / baseline.mean
            slope = self.store.slope(
                "host.step_time", 2 * w, host=host
            )
            if ratio < warn_r or not slope or slope <= 0:
                continue
            severity = (
                SEVERITY_CRITICAL if ratio >= crit_r else SEVERITY_WARN
            )
            out.append(
                HealthVerdict(
                    detector="throughput_degradation",
                    severity=severity,
                    message=(
                        f"host {host} step time {ratio:.2f}x its own "
                        f"baseline ({baseline.mean:.3f}s -> "
                        f"{recent.mean:.3f}s over {w:.0f}s, slope "
                        f"+{slope:.5f}s/s)"
                    ),
                    host=host,
                    node_id=self._node_for_host(host),
                    suggested_action=EventAction.PROFILE.value,
                    evidence_series=f'host.step_time{{host="{host}"}}',
                    evidence=self._evidence(
                        "host.step_time", 2 * w, host=host
                    ),
                    metrics={
                        "baseline_mean_s": baseline.mean,
                        "recent_mean_s": recent.mean,
                        "ratio": ratio,
                        "slope_s_per_s": slope,
                    },
                    timestamp=self.clock(),
                )
            )
        return out

    def _detect_goodput_slo(self) -> List[HealthVerdict]:
        slo = self._cfg("goodput_slo")
        crit = self._cfg("goodput_critical")
        grace = self._cfg("goodput_grace_s")
        first = self.store.first_ts("goodput.ratio")
        if first is None or self.clock() - first < grace:
            return []
        w = self._cfg("window_s")
        stats = self.store.query("goodput.ratio", w)
        if stats is None or stats.count < int(self._cfg("min_points")):
            return []
        if stats.mean >= slo:
            return []
        severity = SEVERITY_CRITICAL if stats.mean < crit else SEVERITY_WARN
        return [
            HealthVerdict(
                detector="goodput_slo",
                severity=severity,
                message=(
                    f"goodput ratio {stats.mean:.2f} below SLO "
                    f"{slo:.2f} over the last {w:.0f}s"
                ),
                suggested_action="",
                evidence_series="goodput.ratio",
                evidence=self._evidence("goodput.ratio", w),
                metrics={"ratio": stats.mean, "slo": slo},
                timestamp=self.clock(),
            )
        ]

    def _detect_data_starvation(self) -> List[HealthVerdict]:
        """Fraction of wall time a host's train loop spent blocked on
        input, from the rate of the cumulative data-wait counter."""
        w = self._cfg("window_s")
        warn_f = self._cfg("starvation_warn_frac")
        crit_f = self._cfg("starvation_crit_frac")
        out: List[HealthVerdict] = []
        for host in self._hosts():
            frac = self.store.rate("host.data_wait_s", w, host=host)
            if frac is None or frac < warn_f:
                continue
            severity = (
                SEVERITY_CRITICAL if frac >= crit_f else SEVERITY_WARN
            )
            out.append(
                HealthVerdict(
                    detector="data_starvation",
                    severity=severity,
                    message=(
                        f"host {host} blocked on input "
                        f"{100.0 * frac:.0f}% of wall time over the "
                        f"last {w:.0f}s"
                    ),
                    host=host,
                    node_id=self._node_for_host(host),
                    suggested_action=EventAction.PROFILE.value,
                    evidence_series=(
                        f'host.data_wait_s{{host="{host}"}}'
                    ),
                    evidence=self._evidence(
                        "host.data_wait_s", w, host=host
                    ),
                    metrics={"data_wait_frac": frac},
                    timestamp=self.clock(),
                )
            )
        return out

    def _detect_recompile_storm(self) -> List[HealthVerdict]:
        w = self._cfg("window_s")
        warn_pm = self._cfg("recompile_warn_per_min")
        crit_pm = self._cfg("recompile_crit_per_min")
        out: List[HealthVerdict] = []
        for host in self._hosts():
            rate = self.store.rate("host.compiles", w, host=host)
            if rate is None:
                continue
            per_min = rate * 60.0
            if per_min < warn_pm:
                continue
            severity = (
                SEVERITY_CRITICAL
                if per_min >= crit_pm
                else SEVERITY_WARN
            )
            out.append(
                HealthVerdict(
                    detector="recompile_storm",
                    severity=severity,
                    message=(
                        f"host {host} recompiling at "
                        f"{per_min:.1f}/min over the last {w:.0f}s "
                        "(steady state should be ~0)"
                    ),
                    host=host,
                    node_id=self._node_for_host(host),
                    suggested_action=EventAction.PROFILE.value,
                    evidence_series=f'host.compiles{{host="{host}"}}',
                    evidence=self._evidence(
                        "host.compiles", w, host=host
                    ),
                    metrics={"compiles_per_min": per_min},
                    timestamp=self.clock(),
                )
            )
        return out

    def _detect_rss_growth(self) -> List[HealthVerdict]:
        """Sustained robust RSS slope + material relative growth —
        the leak signature, filtered against benign one-off jumps by
        the Theil–Sen estimator."""
        w = 2 * self._cfg("window_s")
        warn_s = self._cfg("rss_warn_mb_per_s")
        crit_s = self._cfg("rss_crit_mb_per_s")
        min_frac = self._cfg("rss_min_growth_frac")
        min_pts = int(self._cfg("min_points"))
        out: List[HealthVerdict] = []
        for host in self._hosts():
            stats = self.store.query("host.memory_mb", w, host=host)
            if stats is None or stats.count < 2 * min_pts:
                continue
            slope = self.store.slope("host.memory_mb", w, host=host)
            if slope is None or slope < warn_s or stats.first <= 0:
                continue
            growth = (stats.last - stats.first) / stats.first
            if growth < min_frac:
                continue
            severity = (
                SEVERITY_CRITICAL if slope >= crit_s else SEVERITY_WARN
            )
            out.append(
                HealthVerdict(
                    detector="rss_growth",
                    severity=severity,
                    message=(
                        f"host {host} RSS climbing "
                        f"{slope:.2f} MB/s "
                        f"({stats.first:.0f} -> {stats.last:.0f} MB, "
                        f"+{100.0 * growth:.0f}% over {w:.0f}s)"
                    ),
                    host=host,
                    node_id=self._node_for_host(host),
                    suggested_action=EventAction.DIAGNOSE.value,
                    evidence_series=(
                        f'host.memory_mb{{host="{host}"}}'
                    ),
                    evidence=self._evidence(
                        "host.memory_mb", w, host=host
                    ),
                    metrics={
                        "slope_mb_per_s": slope,
                        "growth_frac": growth,
                    },
                    timestamp=self.clock(),
                )
            )
        return out

    def _detect_straggler_persistence(self) -> List[HealthVerdict]:
        """A straggler verdict that REFUSES to go away: the speed
        monitor scores instantaneous relative slowness; this detector
        adds the time dimension (N consecutive evaluations)."""
        if self.speed_monitor is None:
            return []
        warn_t = int(self._cfg("straggler_warn_ticks"))
        crit_t = int(self._cfg("straggler_crit_ticks"))
        try:
            scores = self.speed_monitor.straggler_scores()
            slow = set(self.speed_monitor.stragglers())
        except Exception:  # noqa: BLE001 — scoring must not kill
            # the evaluation tick
            return []
        for node_id in list(self._straggler_ticks):
            if node_id not in slow:
                del self._straggler_ticks[node_id]
        out: List[HealthVerdict] = []
        for node_id in slow:
            ticks = self._straggler_ticks.get(node_id, 0) + 1
            self._straggler_ticks[node_id] = ticks
            if ticks < warn_t:
                continue
            severity = (
                SEVERITY_CRITICAL if ticks >= crit_t else SEVERITY_WARN
            )
            out.append(
                HealthVerdict(
                    detector="straggler_persistence",
                    severity=severity,
                    message=(
                        f"node {node_id} scored a straggler for "
                        f"{ticks} consecutive evaluations "
                        f"(score {scores.get(node_id, 0.0):.2f}x "
                        "fleet median)"
                    ),
                    node_id=node_id,
                    suggested_action=EventAction.PROFILE.value,
                    evidence_series=(
                        f'host.step_ewma{{node="{node_id}"}}'
                    ),
                    evidence=self._evidence(
                        "host.step_ewma",
                        2 * self._cfg("window_s"),
                        node=str(node_id),
                    ),
                    metrics={
                        "score": scores.get(node_id, 0.0),
                        "ticks": float(ticks),
                    },
                    timestamp=self.clock(),
                )
            )
        return out

    def heartbeat_ages(self) -> Dict[int, float]:
        if self._heartbeat_ages is not None:
            return self._heartbeat_ages()
        if self.job_manager is None:
            return {}
        # Node heartbeat stamps are process-local monotonic (see the
        # PR-5 clock sweep), so the age probe must be too — the
        # engine's injectable wall clock only drives series windows.
        now = time.monotonic()
        ages: Dict[int, float] = {}
        for node in self.job_manager.alive_nodes():
            hb = getattr(node, "heartbeat_time", 0.0) or 0.0
            if hb > 0:
                ages[node.id] = max(now - hb, 0.0)
        return ages

    def _detect_heartbeat_gap(self) -> List[HealthVerdict]:
        """An alive node most of the way to its heartbeat timeout:
        the early warning BEFORE the watchdog declares it dead.
        Normally no suggested action — a node that is not
        heartbeating cannot be handed one — EXCEPT when the stall
        correlator attributes a live fleet-wide stall to this silent
        node: then the critical verdict carries DIAGNOSE, parked in
        the node's FIFO so the capture fires the moment the agent
        reconnects (cooldown-shared with every other action on this
        subject via the engine's stamps)."""
        warn_f = self._cfg("heartbeat_warn_frac")
        crit_f = self._cfg("heartbeat_crit_frac")
        timeout = max(self.heartbeat_timeout, 1e-9)
        suspects = (
            getattr(self.stall, "silent_suspects", None) or ()
            if self.stall is not None
            else ()
        )
        out: List[HealthVerdict] = []
        for node_id, age in sorted(self.heartbeat_ages().items()):
            frac = age / timeout
            if frac < warn_f:
                continue
            severity = (
                SEVERITY_CRITICAL if frac >= crit_f else SEVERITY_WARN
            )
            message = (
                f"node {node_id} last heartbeat {age:.0f}s "
                f"ago ({100.0 * frac:.0f}% of the "
                f"{timeout:.0f}s timeout)"
            )
            suggested = ""
            if severity == SEVERITY_CRITICAL and node_id in suspects:
                suggested = EventAction.DIAGNOSE.value
                message += (
                    "; fleet stall attributed to this silent node"
                )
            out.append(
                HealthVerdict(
                    detector="heartbeat_gap",
                    severity=severity,
                    message=message,
                    node_id=node_id,
                    suggested_action=suggested,
                    evidence_series="heartbeat_age_s",
                    evidence=[(self.clock(), age)],
                    metrics={"age_s": age, "timeout_frac": frac},
                    timestamp=self.clock(),
                )
            )
        return out

    def _detect_replica_unhealthy(self) -> List[HealthVerdict]:
        """A serving replica that is demonstrably not serving: READY
        with dispatched requests and no progress past the router's
        ``progress_timeout_s``, or DRAINING and never re-registered.
        No suggested heartbeat action — the remediation engine owns
        the response ladder (drain -> restart -> replace), keyed on
        this detector."""
        if self.serving is None:
            return []
        try:
            facts = self.serving.unhealthy_replicas()
        except Exception:  # noqa: BLE001 — a router bug must not
            # kill the evaluation tick
            logger.warning(
                "serving unhealthy_replicas probe failed",
                exc_info=True,
            )
            return []
        crit_ratio = self._cfg("replica_stall_crit_ratio")
        out: List[HealthVerdict] = []
        for f in facts:
            stale = float(f.get("stale_s", 0.0))
            timeout = max(float(f.get("timeout_s", 1.0)), 1e-9)
            severity = (
                SEVERITY_CRITICAL
                if stale >= crit_ratio * timeout
                or f.get("state") == "draining"
                else SEVERITY_WARN
            )
            role = str(f.get("role", "mixed"))
            out.append(
                HealthVerdict(
                    detector="replica_unhealthy",
                    severity=severity,
                    message=(
                        f"serving replica {f.get('replica_id')} "
                        f"({role}, {f.get('state')}) holds "
                        f"{f.get('dispatched', 0)} request(s) with "
                        f"no progress for {stale:.1f}s "
                        f"(timeout {timeout:.1f}s)"
                    ),
                    node_id=int(f.get("replica_id", -1)),
                    host=str(f.get("addr", "")),
                    suggested_action="",
                    evidence_series="serving.replica_progress_age_s",
                    evidence=[(self.clock(), stale)],
                    metrics={
                        "stale_s": stale,
                        "timeout_s": timeout,
                        "dispatched": float(
                            f.get("dispatched", 0)
                        ),
                    },
                    timestamp=self.clock(),
                )
            )
        return out

    def _slo_bad_frac(
        self, spec: SLOSpec, window_s: float
    ) -> Tuple[float, int]:
        """(bad-sample fraction, sample count) of the SLO's series
        over the trailing window. No samples = no burn — an idle
        tenant must not page."""
        pts = self.store.points(
            spec.series, window_s, **spec.labels
        )
        if not pts:
            return 0.0, 0
        if spec.direction == "max":
            bad = sum(1 for _, v in pts if v > spec.objective)
        else:
            bad = sum(1 for _, v in pts if v < spec.objective)
        return bad / len(pts), len(pts)

    def _detect_slo_burn(self) -> List[HealthVerdict]:
        """Multi-window multi-burn-rate error-budget detector (the
        Google SRE workbook shape): a pair fires only when BOTH its
        short and long windows burn past the threshold — the short
        window for fast resolution, the long one so a blip cannot
        page. The fast pair (5m/1h, 14.4x) is critical, the slow
        pair (6h/3d, 1x) is warn; each pair is its own verdict
        subject so a drill can watch fast fire critical while slow
        stays warn."""
        if not self.slos:
            return []
        pairs = (
            (
                "fast",
                self._cfg("slo_fast_short_s"),
                self._cfg("slo_fast_long_s"),
                self._cfg("slo_fast_burn"),
                SEVERITY_CRITICAL,
            ),
            (
                "slow",
                self._cfg("slo_slow_short_s"),
                self._cfg("slo_slow_long_s"),
                self._cfg("slo_slow_burn"),
                SEVERITY_WARN,
            ),
        )
        now = self.clock()
        out: List[HealthVerdict] = []
        for spec in self.slos:
            budget = max(spec.budget, 1e-9)
            period_bad, period_n = self._slo_bad_frac(
                spec, spec.period_s
            )
            remaining = max(0.0, 1.0 - period_bad / budget)
            _SLO_BUDGET_REMAINING.set(
                remaining, tenant=spec.tenant, slo=spec.slo
            )
            burns: Dict[str, float] = {}
            for name, short_s, long_s, threshold, severity in pairs:
                short_bad, short_n = self._slo_bad_frac(
                    spec, short_s
                )
                long_bad, long_n = self._slo_bad_frac(spec, long_s)
                # Both windows must burn: min() of the two rates.
                burn = min(short_bad, long_bad) / budget
                burns[name] = burn
                if not short_n or not long_n or burn < threshold:
                    continue
                out.append(
                    HealthVerdict(
                        detector="slo_burn",
                        severity=severity,
                        message=(
                            f"tenant {spec.tenant} {spec.slo} "
                            f"burning its error budget at "
                            f"{burn:.1f}x ({name} windows "
                            f"{short_s:.0f}s/{long_s:.0f}s, budget "
                            f"{budget:.3f}, "
                            f"{100.0 * remaining:.0f}% remaining)"
                        ),
                        host=f"{spec.key()}/{name}",
                        suggested_action="",
                        evidence_series=spec.series,
                        evidence=self._evidence(
                            spec.series, short_s, **spec.labels
                        ),
                        metrics={
                            "burn": burn,
                            "threshold": threshold,
                            "budget_remaining": remaining,
                            "short_bad_frac": short_bad,
                            "long_bad_frac": long_bad,
                        },
                        timestamp=now,
                    )
                )
            self._slo_last[spec.key()] = {
                **spec.to_dict(),
                "budget_remaining": remaining,
                "period_bad_frac": period_bad,
                "period_samples": period_n,
                "burn": dict(burns),
                "ts": now,
            }
        return out

    def slo_snapshot(self) -> List[dict]:
        """Per-SLO budget standing for the capacity RPC: the spec,
        budget remaining, last burn rates, and whether a burn verdict
        is currently active (and at what severity)."""
        with self._lock:
            active = dict(self._active)
        out = []
        for spec in self.slos:
            entry = dict(
                self._slo_last.get(
                    spec.key(),
                    {**spec.to_dict(), "budget_remaining": 1.0,
                     "burn": {}},
                )
            )
            severity = ""
            for name in ("fast", "slow"):
                v = active.get(
                    ("slo_burn", f"{spec.key()}/{name}", -1)
                )
                if v is not None and (
                    not severity
                    or SEVERITIES.index(v.severity)
                    > SEVERITIES.index(severity)
                ):
                    severity = v.severity
            entry["severity"] = severity
            entry["burning"] = bool(severity)
            out.append(entry)
        return out

    # -- verdict lifecycle -------------------------------------------------

    def evaluate_once(self) -> List[HealthVerdict]:
        """One evaluation tick: run every detector, reconcile the
        active set (transitions -> history/counters/events/actions/
        brain), refresh the score gauge. Returns the active verdicts,
        most severe first."""
        # Hoist the per-host scans for the whole tick: the host list
        # (two series-table walks under the store lock) and the
        # host->node map (one locked pass over the fleet's table)
        # would otherwise be recomputed by every detector.
        self._tick_hosts = self._scan_hosts()
        # Duck-typed fleets (test fakes) may only offer the per-host
        # node_for_host; without the bulk map the per-call fallback
        # in _node_for_host still works.
        mapper = getattr(self.fleet, "host_node_map", None)
        self._tick_nodes = mapper() if mapper is not None else None
        try:
            return self._evaluate_tick()
        finally:
            self._tick_hosts = None
            self._tick_nodes = None

    def _evaluate_tick(self) -> List[HealthVerdict]:
        fresh: List[HealthVerdict] = []
        for detector in self.detectors:
            try:
                fresh.extend(detector() or [])
            except Exception:  # noqa: BLE001 — one broken detector
                # must not silence the other six
                logger.warning(
                    "health detector %s failed",
                    getattr(detector, "__name__", detector),
                    exc_info=True,
                )
        now = self.clock()
        transitions: List[HealthVerdict] = []
        resolved: List[HealthVerdict] = []
        with self._lock:
            self._evaluations += 1
            previous = self._active
            current: Dict[Tuple[str, str, int], HealthVerdict] = {}
            for v in fresh:
                key = v.key()
                old = previous.get(key)
                current[key] = v
                if old is None or old.severity != v.severity:
                    transitions.append(v)
                    self._history.append(v)
            for key, old in previous.items():
                if key not in current:
                    res = dataclasses.replace(
                        old,
                        severity=SEVERITY_INFO,
                        resolved=True,
                        message=f"resolved: {old.message}",
                        suggested_action="",
                        timestamp=now,
                    )
                    resolved.append(res)
                    self._history.append(res)
            self._active = current
            score = self._score_locked()
        _HEALTH_SCORE.set(score)
        for v in transitions:
            _VERDICTS_TOTAL.inc(detector=v.detector, severity=v.severity)
            obs.event(
                "health.verdict",
                detector=v.detector,
                severity=v.severity,
                host=v.host,
                node_id=v.node_id,
                action=v.suggested_action,
            )
            logger.warning(
                "health verdict [%s] %s: %s",
                v.severity, v.detector, v.message,
            )
        for v in resolved:
            obs.event(
                "health.resolved",
                detector=v.detector,
                host=v.host,
                node_id=v.node_id,
            )
            logger.info("health resolved: %s %s", v.detector, v.host)
        self._queue_actions(transitions, now)
        self._persist(transitions + resolved, score, now)
        if transitions or resolved:
            cb = self.on_state_change
            if cb is not None:
                try:
                    cb()
                except Exception:  # noqa: BLE001
                    pass
        return sorted(
            self._active_list(), key=_verdict_sort_key
        )

    def _queue_actions(
        self, transitions: List[HealthVerdict], now: float
    ) -> None:
        """Critical verdicts with a suggested action auto-queue it on
        the subject node's heartbeat FIFO — at most once per
        ``action_cooldown_s`` per (detector, subject), so a sticky
        verdict cannot flood the agent with captures."""
        if self.action_sink is None:
            return
        cooldown = self._cfg("action_cooldown_s")
        for v in transitions:
            if (
                v.severity != SEVERITY_CRITICAL
                or not v.suggested_action
                or v.node_id < 0
            ):
                continue
            key = v.key()
            last = self.action_stamp(key)
            if last is not None and now - last < cooldown:
                continue
            self.stamp_action(key, now)
            try:
                self.action_sink(v.node_id, v.suggested_action)
                obs.event(
                    "health.action_queued",
                    detector=v.detector,
                    node_id=v.node_id,
                    action=v.suggested_action,
                )
            except Exception:  # noqa: BLE001 — the action channel
                # failing must not fail the evaluation
                logger.warning(
                    "queueing %s for node %d failed",
                    v.suggested_action, v.node_id, exc_info=True,
                )

    def _persist(
        self,
        new_verdicts: List[HealthVerdict],
        score: float,
        now: float,
    ) -> None:
        """Ship this tick's channel to the brain datastore: per-host
        runtime samples, the fleet aggregate + goodput sample, and
        every verdict transition — the history ROADMAP item 2's
        policy engine plans over. Best-effort by contract."""
        if self.brain is None:
            return
        try:
            self._persist_inner(new_verdicts, score, now)
        except Exception:  # noqa: BLE001 — a broken datastore must
            # not take the health plane down
            logger.warning("brain persistence failed", exc_info=True)

    def _persist_inner(
        self,
        new_verdicts: List[HealthVerdict],
        score: float,
        now: float,
    ) -> None:
        from dlrover_tpu.brain.service import RuntimeSample

        persist_sample = getattr(
            self.brain, "persist_runtime_sample", None
        )
        if persist_sample is not None:
            for host in self._hosts():
                cpu = self.store.latest(
                    "host.cpu_percent", host=host
                )
                mem = self.store.latest("host.memory_mb", host=host)
                tps = self.store.latest(
                    "host.tokens_per_s", host=host
                )
                persist_sample(
                    RuntimeSample(
                        job_name=self.job_name,
                        node_type="worker",
                        node_id=self._node_for_host(host),
                        used_cpu=cpu[1] if cpu else 0.0,
                        used_memory_mb=int(mem[1]) if mem else 0,
                        config_cpu=0.0,
                        config_memory_mb=0,
                        speed=tps[1] if tps else 0.0,
                        timestamp=now,
                    )
                )
        persist_fleet = getattr(self.brain, "persist_fleet_sample", None)
        if persist_fleet is not None:
            aggregates = {}
            if self.fleet is not None:
                aggregates = self.fleet.aggregates()
            ratio = self.store.latest("goodput.ratio")
            persist_fleet(
                job_name=self.job_name,
                aggregates=aggregates,
                goodput_ratio=ratio[1] if ratio else 0.0,
                health_score=score,
                timestamp=now,
            )
        persist_verdict = getattr(
            self.brain, "persist_health_verdict", None
        )
        if persist_verdict is not None:
            import json

            for v in new_verdicts:
                persist_verdict(
                    job_name=self.job_name,
                    detector=v.detector,
                    severity=v.severity,
                    node_id=v.node_id,
                    message=v.message,
                    action=v.suggested_action,
                    evidence=json.dumps(v.to_dict()["evidence"]),
                    timestamp=v.timestamp or now,
                )

    # -- shared action-cooldown stamps ------------------------------------

    def action_stamp(
        self, key: Tuple[str, str, int]
    ) -> Optional[float]:
        """Wall stamp of the last action taken for a (detector, host,
        node_id) subject — shared between the capture path (PROFILE/
        DIAGNOSE auto-queue) and the remediation engine so the two
        never hammer the same subject independently."""
        with self._lock:
            return self._last_action.get(key)

    def stamp_action(
        self, key: Tuple[str, str, int], ts: float
    ) -> None:
        with self._lock:
            self._last_action[key] = ts

    # -- warm-restart snapshot ---------------------------------------------

    def to_snapshot(self) -> dict:
        """JSON-safe recoverable state: the ACTIVE verdict set, the
        transition history, action-cooldown stamps, and straggler
        streaks. Without this, a warm restart wipes the active set, so
        a still-firing (sticky) verdict re-registers as a brand-new
        transition and re-fires its action immediately — defeating the
        cooldown every time the master bounces. All stamps are wall
        clock, so they stay meaningful across processes."""
        with self._lock:
            return {
                "active": [v.to_dict() for v in self._active.values()],
                "history": [v.to_dict() for v in self._history],
                "last_action": [
                    [k[0], k[1], k[2], ts]
                    for k, ts in self._last_action.items()
                ],
                "straggler_ticks": {
                    str(k): v
                    for k, v in self._straggler_ticks.items()
                },
            }

    def restore_snapshot(self, state: dict) -> None:
        with self._lock:
            self._active = {}
            for d in state.get("active", []):
                v = HealthVerdict.from_dict(d)
                self._active[v.key()] = v
            self._history.clear()
            for d in state.get("history", []):
                self._history.append(HealthVerdict.from_dict(d))
            self._last_action = {
                (str(det), str(host), int(node_id)): float(ts)
                for det, host, node_id, ts in state.get(
                    "last_action", []
                )
            }
            self._straggler_ticks = {
                int(k): int(v)
                for k, v in state.get("straggler_ticks", {}).items()
            }
            score = self._score_locked()
        _HEALTH_SCORE.set(score)

    # -- read surface ------------------------------------------------------

    def _active_list(self) -> List[HealthVerdict]:
        with self._lock:
            return list(self._active.values())

    def active_verdicts(self) -> List[HealthVerdict]:
        """Currently-active verdicts, most severe first."""
        return sorted(self._active_list(), key=_verdict_sort_key)

    def history(self, limit: int = 0) -> List[HealthVerdict]:
        """Verdict transitions (including resolutions), oldest first,
        bounded by the engine's history ring."""
        with self._lock:
            items = list(self._history)
        return items[-limit:] if limit > 0 else items

    def _score_locked(self) -> float:
        penalty = sum(
            SEVERITY_PENALTY.get(v.severity, 0.0)
            for v in self._active.values()
        )
        return max(0.0, min(1.0, 1.0 - penalty))

    def health_score(self) -> float:
        with self._lock:
            return self._score_locked()

    def critical_count(self) -> int:
        with self._lock:
            return sum(
                1
                for v in self._active.values()
                if v.severity == SEVERITY_CRITICAL
            )

    def healthz_payload(self) -> dict:
        """The /healthz JSON body (obs/exposition.py): the readiness
        facts a deploy probe keys on."""
        with self._lock:
            active = list(self._active.values())
            score = self._score_locked()
        critical = sum(
            1 for v in active if v.severity == SEVERITY_CRITICAL
        )
        return {
            "ok": critical == 0,
            "health_score": round(score, 4),
            "critical_verdicts": critical,
            "active_verdicts": len(active),
            "evaluations": self._evaluations,
            "detectors": sorted(
                {v.detector for v in active}
            ),
        }

    def snapshot(self) -> dict:
        """Full health snapshot for tools (obs_report --health)."""
        return {
            "ts": self.clock(),
            "score": self.health_score(),
            "critical_verdicts": self.critical_count(),
            "active": [
                v.to_dict() for v in self.active_verdicts()
            ],
            "history": [v.to_dict() for v in self.history()],
        }


def render_health(payload: dict) -> str:
    """Human rendering of a health snapshot (``HealthMonitor.
    snapshot()`` or the assembled ``HealthQueryResponse``) — the
    ``obs_report --health`` body."""
    score = float(payload.get("score", 1.0))
    active = list(payload.get("active", []))
    history = list(payload.get("history", []))
    critical = payload.get(
        "critical_verdicts",
        sum(1 for v in active if v.get("severity") == SEVERITY_CRITICAL),
    )
    lines = [
        f"job health score {score:.2f} "
        f"({len(active)} active verdict"
        f"{'' if len(active) == 1 else 's'}, {critical} critical)"
    ]
    if not active:
        lines.append("  no active verdicts — fleet healthy")
    for v in active:
        head = f"  [{v.get('severity', '?'):<8}] {v.get('detector', '?')}"
        subject = v.get("host") or (
            f"node {v['node_id']}"
            if int(v.get("node_id", -1)) >= 0
            else "job"
        )
        lines.append(f"{head} ({subject}): {v.get('message', '')}")
        if v.get("suggested_action"):
            lines.append(
                f"             action: {v['suggested_action']}"
            )
        evidence = v.get("evidence") or []
        if evidence:
            vals = [float(p[1]) for p in evidence]
            tail = " ".join(f"{x:.4g}" for x in vals[-8:])
            lines.append(
                f"             evidence {v.get('evidence_series', '?')}"
                f" ({len(evidence)} pts, min {min(vals):.4g} "
                f"max {max(vals):.4g}): ... {tail}"
            )
    if history:
        lines.append(f"history (last {min(len(history), 10)}):")
        for v in history[-10:]:
            mark = "resolved" if v.get("resolved") else v.get(
                "severity", "?"
            )
            lines.append(
                f"  {v.get('timestamp', 0):.0f} [{mark}] "
                f"{v.get('detector', '?')} "
                f"{v.get('host') or v.get('node_id')}"
            )
    return "\n".join(lines)
