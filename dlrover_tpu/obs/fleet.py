"""Fleet-level metric aggregation on the master.

Each agent periodically ships a ``MetricsSnapshotReport`` — a
serialized dump of its process-local obs registry
(``MetricsRegistry.dump()``) plus resource stats, recent per-step
timings, and new tracer events — over the existing control-plane
channel (``MasterClient.report_metrics_snapshot``, on the
ResourceMonitor cadence). The :class:`FleetAggregator` here merges
those snapshots into the master's registry so the master's
``/metrics`` endpoint and the ``MetricsRequest`` RPC answer for the
*job*, not one process:

* every host series is re-rendered with a ``host`` label
  (``dlrover_train_steps_total{host="w0"} ...``);
* cross-host aggregates (sum/min/max/p50/p90) are computed for the
  key series — step time, tokens/s, data-wait, host-syncs — as
  ``dlrover_fleet_series{series,stat}``;
* snapshots from departed nodes age out after ``ttl`` seconds (and
  are dropped immediately when the master sees the node die), so a
  scrape never shows ghosts.

The aggregator renders through a registry *collector* (see
``MetricsRegistry.add_collector``) instead of writing into typed
metric objects: counters cannot be set backwards, and collector
rendering makes age-out trivially correct — a pruned host simply
stops producing lines.

Event payloads are forwarded to the goodput accountant
(:mod:`dlrover_tpu.obs.goodput`) and per-step timings to the speed
monitor's straggler scorer, which is how trainer-side spans reach the
master's job-level accounting.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.obs.metrics import (
    MetricsRegistry,
    _escape_label_value,
    _format_value,
    get_registry,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs.timeseries import _percentile as _percentile_sorted

logger = get_logger("obs.fleet")

# Key series the fleet view aggregates across hosts, and the stats
# computed for each. Values are per-host scalars extracted from the
# snapshot (see _host_scalar).
KEY_SERIES = (
    "step_time_s",
    "tokens_per_s",
    "data_wait_s_total",
    "host_syncs_total",
    "mfu",
)
STATS = ("sum", "min", "max", "p50", "p90")

DEFAULT_TTL = 90.0  # 3x the default ResourceMonitor cadence


def _percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) on a sorted copy."""
    return _percentile_sorted(sorted(values), q)


@dataclasses.dataclass
class HostSnapshot:
    host: str
    node_id: int
    wall_ts: float
    received_mono: float
    registry: Dict[str, dict]
    resource: Dict[str, float]
    step_times: List[float]
    # The trainer's last progress stamp (obs/beacon.py record plus the
    # agent-computed ``age_s``); empty when the host runs no beacon.
    beacon: Dict = dataclasses.field(default_factory=dict)


class FleetAggregator:
    """Merges per-host registry snapshots into one fleet view."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        speed_monitor=None,
        goodput=None,
        ttl: float = DEFAULT_TTL,
        attach: bool = True,
        timeseries=None,
        trace_store=None,
    ):
        """``attach=False`` skips hooking :meth:`collect` into the
        registry's render — for owners that cannot guarantee a
        matching :meth:`close` (a collector left on the process-global
        registry would render forever). ``timeseries`` (a
        :class:`~dlrover_tpu.obs.timeseries.TimeSeriesStore`) turns
        every ingest into history: per-host scalars and fleet
        aggregates are recorded so the health detectors can query
        windows instead of instants. ``trace_store`` (a
        :class:`~dlrover_tpu.obs.trace_store.TraceStore`) receives
        any snapshot event that carries a ``trace_id`` — the channel
        by which spans emitted on OTHER hosts join the master's
        assembled trace timelines."""
        self.registry = registry or get_registry()
        self.speed_monitor = speed_monitor
        self.goodput = goodput
        self.timeseries = timeseries
        self.trace_store = trace_store
        self.ttl = ttl
        self._lock = threading.Lock()
        self._hosts: Dict[str, HostSnapshot] = {}
        self._node_to_host: Dict[int, str] = {}
        self._last_fleet_record_ts = -float("inf")
        self._skew_warned: set = set()
        if attach:
            self.registry.add_collector(self.collect)

    def close(self) -> None:
        self.registry.remove_collector(self.collect)

    # -- ingest -----------------------------------------------------------

    def ingest(self, report) -> HostSnapshot:
        """Absorb one ``MetricsSnapshotReport`` (duck-typed: anything
        with host/node_id/timestamp/registry/resource/step_times[/
        events] attributes)."""
        host = str(getattr(report, "host", "") or "")
        node_id = int(getattr(report, "node_id", -1))
        if not host:
            host = f"node{node_id}"
        snap = HostSnapshot(
            host=host,
            node_id=node_id,
            wall_ts=float(getattr(report, "timestamp", 0.0) or time.time()),
            received_mono=time.monotonic(),
            registry=dict(getattr(report, "registry", None) or {}),
            resource=dict(getattr(report, "resource", None) or {}),
            step_times=[
                float(t)
                for t in (getattr(report, "step_times", None) or [])
            ],
            beacon=dict(getattr(report, "beacon", None) or {}),
        )
        with self._lock:
            self._hosts[host] = snap
            if node_id >= 0:
                self._node_to_host[node_id] = host
        if self.speed_monitor is not None and snap.step_times:
            for t in snap.step_times:
                self.speed_monitor.observe_host_step_time(node_id, t)
        events = getattr(report, "events", None) or []
        if self.trace_store is not None and events:
            # Only trace-tagged events are trace material;
            # add_events ignores the rest.
            self.trace_store.add_events(events)
        if self.goodput is not None:
            if events:
                self.goodput.add_events(events)
            # Refresh the goodput gauges even for event-less
            # snapshots: with host tracing off, the accountant's
            # stream is fed by the servicer (step reports, failures)
            # and this is its recompute tick (debounced internally).
            self.goodput.account()
        if self.timeseries is not None:
            self._record_timeseries(snap)
        return snap

    # Snapshot scalar -> time-series name, per host. The cumulative
    # ones (data_wait seconds, host syncs, compiles) are recorded as
    # counters the store's rate() differentiates.
    _TS_SERIES = (
        ("step_time_s", "host.step_time"),
        ("tokens_per_s", "host.tokens_per_s"),
        ("data_wait_s_total", "host.data_wait_s"),
        ("host_syncs_total", "host.host_syncs"),
        ("mfu", "host.mfu"),
    )
    _TS_RESOURCE = ("cpu_percent", "memory_mb", "hbm_used_gb")

    # Minimum snapshot-time seconds between fleet-aggregate history
    # records (per-host series are never debounced).
    FLEET_RECORD_INTERVAL = 5.0

    # Snapshot stamps this far past the master's clock are clamped
    # (generous slack: RPC latency + modest NTP drift, never minutes).
    MAX_FUTURE_SKEW = 30.0

    def _record_timeseries(self, snap: HostSnapshot) -> None:
        """Fold one snapshot into the history store: per-host scalars
        (stamped with the snapshot's wall time, so fake-clock tests
        and late-arriving snapshots land where they belong) plus the
        fleet aggregates as of this ingest."""
        store = self.timeseries
        ts = snap.wall_ts
        # A host clock running ahead of the master would stamp its
        # samples past every detector's query window (anchored at the
        # master's clock) — the host silently vanishes from the
        # health plane, and the fleet-record debounce watermark jumps
        # ahead, muting everyone else. Clamp future stamps to "now"
        # (past stamps stay put: a late arrival and a backdated test
        # snapshot are indistinguishable and both legitimate).
        now = store.clock()
        if ts > now + self.MAX_FUTURE_SKEW:
            with self._lock:
                warn = snap.host not in self._skew_warned
                self._skew_warned.add(snap.host)
            if warn:
                logger.warning(
                    "host %s snapshot stamped %.0fs in the master's "
                    "future; clamping its history stamps (check NTP)",
                    snap.host, ts - now,
                )
            ts = now
        for series, name in self._TS_SERIES:
            v = self._host_scalar(snap, series)
            if v is not None:
                store.record(name, v, ts=ts, host=snap.host)
        for key in self._TS_RESOURCE:
            v = snap.resource.get(key)
            if v is not None:
                store.record(
                    f"host.{key}", float(v), ts=ts, host=snap.host
                )
        compiles = self._compile_total(snap)
        if compiles is not None:
            store.record(
                "host.compiles", compiles, ts=ts, host=snap.host
            )
        if snap.beacon:
            # Progress-vector history for the stall correlator: step
            # is a counter-shaped series (monotone while healthy),
            # age the agent-observed staleness at snapshot time.
            step = snap.beacon.get("step")
            if isinstance(step, (int, float)):
                store.record(
                    "host.beacon_step", float(step), ts=ts,
                    host=snap.host,
                )
            age = snap.beacon.get("age_s")
            if isinstance(age, (int, float)) and age >= 0:
                store.record(
                    "host.beacon_age_s", float(age), ts=ts,
                    host=snap.host,
                )
        # Fleet aggregates walk every live snapshot; recording them
        # on every per-host ingest is O(hosts^2) per collect interval
        # and floods the window with near-identical duplicates, so
        # debounce to once per FLEET_RECORD_INTERVAL of snapshot time.
        # Check-and-advance the watermark under the lock: concurrent
        # ingest RPCs must elect exactly one recorder per interval
        # (aggregates() takes the same lock, so it stays outside).
        with self._lock:
            record_fleet = (
                ts - self._last_fleet_record_ts
                >= self.FLEET_RECORD_INTERVAL
            )
            if record_fleet:
                self._last_fleet_record_ts = ts
        if record_fleet:
            for series, stats in self.aggregates().items():
                for stat, value in stats.items():
                    store.record(
                        f"fleet.{series}", value, ts=ts, stat=stat
                    )

    @staticmethod
    def _compile_total(snap: HostSnapshot) -> Optional[float]:
        """Total (re)compiles the host's CompileTracker counted, from
        its shipped registry dump (sum over the per-fn series)."""
        md = snap.registry.get("dlrover_compile_total")
        if not md or md.get("type") != "counter":
            return None
        return float(sum(row[1] for row in md.get("series", [])))

    def node_for_host(self, host: str) -> Optional[int]:
        """The node id behind a host label, for detectors that queue
        actions on the node's heartbeat FIFO."""
        with self._lock:
            for node_id, h in self._node_to_host.items():
                if h == host:
                    return node_id
        return None

    def host_node_map(self) -> Dict[str, int]:
        """host label -> node id, inverted in one locked pass — for
        callers (the health tick) that would otherwise pay an
        O(hosts) :meth:`node_for_host` scan per host."""
        with self._lock:
            return {h: n for n, h in self._node_to_host.items()}

    def remove_node(self, node_id: int) -> None:
        """Drop a departed node's snapshot immediately (the TTL is
        only the backstop for nodes that die without a master event)."""
        with self._lock:
            host = self._node_to_host.pop(node_id, None)
            if host is not None:
                self._hosts.pop(host, None)
        if host is not None and self.timeseries is not None:
            # Its history goes too: a dead host's stale series must
            # not keep convicting (or acquitting) the live fleet.
            self.timeseries.drop_label("host", host)

    def remove_host(self, host: str) -> None:
        with self._lock:
            snap = self._hosts.pop(host, None)
            if snap is not None:
                self._node_to_host.pop(snap.node_id, None)
        if snap is not None and self.timeseries is not None:
            self.timeseries.drop_label("host", host)

    def _live_locked(self) -> List[HostSnapshot]:
        now = time.monotonic()
        stale = [
            h
            for h, s in self._hosts.items()
            if now - s.received_mono > self.ttl
        ]
        for h in stale:
            snap = self._hosts.pop(h)
            self._node_to_host.pop(snap.node_id, None)
        return list(self._hosts.values())

    def live_snapshots(self) -> List[HostSnapshot]:
        with self._lock:
            return self._live_locked()

    def hosts(self) -> List[str]:
        return sorted(s.host for s in self.live_snapshots())

    # -- aggregation ------------------------------------------------------

    @staticmethod
    def _host_scalar(snap: HostSnapshot, series: str) -> Optional[float]:
        def hist(name):
            md = snap.registry.get(name)
            if not md or md.get("type") != "histogram":
                return None
            total = sum(row[2] for row in md.get("series", []))
            count = sum(row[3] for row in md.get("series", []))
            return total, count

        if series == "step_time_s":
            if snap.step_times:
                return sum(snap.step_times) / len(snap.step_times)
            h = hist("dlrover_train_step_seconds")
            if h and h[1] > 0:
                return h[0] / h[1]
            return None
        if series == "tokens_per_s":
            v = snap.resource.get("tokens_per_s")
            return float(v) if v is not None else None
        if series == "mfu":
            # Shipped via the trainer's step-metrics file -> the
            # agent snapshot's resource dict (monitor.build_snapshot).
            v = snap.resource.get("mfu")
            return float(v) if v is not None else None
        if series == "data_wait_s_total":
            h = hist("dlrover_train_data_wait_seconds")
            return h[0] if h else None
        if series == "host_syncs_total":
            md = snap.registry.get("dlrover_train_host_syncs_total")
            if not md or md.get("type") != "counter":
                return None
            return float(
                sum(row[1] for row in md.get("series", []))
            )
        return None

    def aggregates(self) -> Dict[str, Dict[str, float]]:
        """``{series: {stat: value}}`` over live hosts; a series with
        no reporting host is omitted."""
        snaps = self.live_snapshots()
        out: Dict[str, Dict[str, float]] = {}
        for series in KEY_SERIES:
            values = [
                v
                for v in (
                    self._host_scalar(s, series) for s in snaps
                )
                if v is not None
            ]
            if not values:
                continue
            out[series] = {
                "sum": sum(values),
                "min": min(values),
                "max": max(values),
                "p50": _percentile(values, 50.0),
                "p90": _percentile(values, 90.0),
            }
        return out

    # -- exposition -------------------------------------------------------

    def _series_line(
        self,
        name: str,
        key: List[str],
        labelnames: List[str],
        value: float,
        host: str,
        suffix: str = "",
        extra: str = "",
    ) -> str:
        pairs = [
            f'{ln}="{_escape_label_value(lv)}"'
            for ln, lv in zip(labelnames, key)
        ]
        pairs.append(f'host="{_escape_label_value(host)}"')
        if extra:
            pairs.append(extra)
        return (
            f"{name}{suffix}{{{','.join(pairs)}}} "
            f"{_format_value(value)}"
        )

    def _metric_lines(
        self, name: str, md: dict, host: str
    ) -> List[str]:
        labelnames = list(md.get("labelnames", []))
        mtype = md.get("type", "gauge")
        lines: List[str] = []
        if mtype in ("counter", "gauge"):
            for key, value in md.get("series", []):
                lines.append(
                    self._series_line(
                        name, list(key), labelnames, float(value), host
                    )
                )
            return lines
        if mtype == "histogram":
            bounds = [float(b) for b in md.get("buckets", [])]
            bounds.append(float("inf"))
            for key, counts, total, count in md.get("series", []):
                for bound, c in zip(bounds, counts):
                    lines.append(
                        self._series_line(
                            name, list(key), labelnames, float(c),
                            host, suffix="_bucket",
                            extra=f'le="{_format_value(bound)}"',
                        )
                    )
                lines.append(
                    self._series_line(
                        name, list(key), labelnames, float(total),
                        host, suffix="_sum",
                    )
                )
                lines.append(
                    self._series_line(
                        name, list(key), labelnames, float(count),
                        host, suffix="_count",
                    )
                )
        return lines

    def collect(self) -> List[str]:
        """Registry collector: host-labeled series + fleet aggregates.
        Runs inside ``registry.render()`` for every scrape."""
        snaps = self.live_snapshots()
        lines: List[str] = []
        # TYPE headers only for names the master registry does not
        # already expose (those already got their header above us).
        known = set(self.registry.names())
        typed: set = set()
        for snap in sorted(snaps, key=lambda s: s.host):
            for name in sorted(snap.registry):
                md = snap.registry[name]
                if name not in known and name not in typed:
                    help_ = str(md.get("help", "") or "")
                    if help_:
                        lines.append(f"# HELP {name} {help_}")
                    lines.append(
                        f"# TYPE {name} {md.get('type', 'gauge')}"
                    )
                    typed.add(name)
                lines.extend(self._metric_lines(name, md, snap.host))
        lines.append(
            "# TYPE dlrover_fleet_hosts gauge"
        )
        lines.append(f"dlrover_fleet_hosts {len(snaps)}")
        aggs = self.aggregates()
        if aggs:
            lines.append("# TYPE dlrover_fleet_series gauge")
            for series in sorted(aggs):
                for stat in STATS:
                    lines.append(
                        f'dlrover_fleet_series{{series="{series}",'
                        f'stat="{stat}"}} '
                        f"{_format_value(aggs[series][stat])}"
                    )
        return lines
