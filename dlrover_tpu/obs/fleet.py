"""Fleet-level metric aggregation on the master.

Each agent periodically ships a ``MetricsSnapshotReport`` — a
serialized dump of its process-local obs registry
(``MetricsRegistry.dump()``) plus resource stats, recent per-step
timings, and new tracer events — over the existing control-plane
channel (``MasterClient.report_metrics_snapshot``, on the
ResourceMonitor cadence). The :class:`FleetAggregator` here merges
those snapshots into the master's registry so the master's
``/metrics`` endpoint and the ``MetricsRequest`` RPC answer for the
*job*, not one process:

* every host series is re-rendered with a ``host`` label
  (``dlrover_train_steps_total{host="w0"} ...``);
* cross-host aggregates (sum/min/max/p50/p90) are computed for the
  key series — step time, tokens/s, data-wait, host-syncs — as
  ``dlrover_fleet_series{series,stat}``;
* snapshots from departed nodes age out after ``ttl`` seconds (and
  are dropped immediately when the master sees the node die), so a
  scrape never shows ghosts.

The aggregator renders through a registry *collector* (see
``MetricsRegistry.add_collector``) instead of writing into typed
metric objects: counters cannot be set backwards, and collector
rendering makes age-out trivially correct — a pruned host simply
stops producing lines.

Event payloads are forwarded to the goodput accountant
(:mod:`dlrover_tpu.obs.goodput`) and per-step timings to the speed
monitor's straggler scorer, which is how trainer-side spans reach the
master's job-level accounting.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.obs.metrics import (
    MetricsRegistry,
    _escape_label_value,
    _format_value,
    get_registry,
)

# Key series the fleet view aggregates across hosts, and the stats
# computed for each. Values are per-host scalars extracted from the
# snapshot (see _host_scalar).
KEY_SERIES = (
    "step_time_s",
    "tokens_per_s",
    "data_wait_s_total",
    "host_syncs_total",
    "mfu",
)
STATS = ("sum", "min", "max", "p50", "p90")

DEFAULT_TTL = 90.0  # 3x the default ResourceMonitor cadence


def _percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) on a sorted copy."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(
        0, min(len(ordered) - 1, int(round(q / 100.0 * (len(ordered) - 1))))
    )
    return ordered[rank]


@dataclasses.dataclass
class HostSnapshot:
    host: str
    node_id: int
    wall_ts: float
    received_mono: float
    registry: Dict[str, dict]
    resource: Dict[str, float]
    step_times: List[float]


class FleetAggregator:
    """Merges per-host registry snapshots into one fleet view."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        speed_monitor=None,
        goodput=None,
        ttl: float = DEFAULT_TTL,
        attach: bool = True,
    ):
        """``attach=False`` skips hooking :meth:`collect` into the
        registry's render — for owners that cannot guarantee a
        matching :meth:`close` (a collector left on the process-global
        registry would render forever)."""
        self.registry = registry or get_registry()
        self.speed_monitor = speed_monitor
        self.goodput = goodput
        self.ttl = ttl
        self._lock = threading.Lock()
        self._hosts: Dict[str, HostSnapshot] = {}
        self._node_to_host: Dict[int, str] = {}
        if attach:
            self.registry.add_collector(self.collect)

    def close(self) -> None:
        self.registry.remove_collector(self.collect)

    # -- ingest -----------------------------------------------------------

    def ingest(self, report) -> HostSnapshot:
        """Absorb one ``MetricsSnapshotReport`` (duck-typed: anything
        with host/node_id/timestamp/registry/resource/step_times[/
        events] attributes)."""
        host = str(getattr(report, "host", "") or "")
        node_id = int(getattr(report, "node_id", -1))
        if not host:
            host = f"node{node_id}"
        snap = HostSnapshot(
            host=host,
            node_id=node_id,
            wall_ts=float(getattr(report, "timestamp", 0.0) or time.time()),
            received_mono=time.monotonic(),
            registry=dict(getattr(report, "registry", None) or {}),
            resource=dict(getattr(report, "resource", None) or {}),
            step_times=[
                float(t)
                for t in (getattr(report, "step_times", None) or [])
            ],
        )
        with self._lock:
            self._hosts[host] = snap
            if node_id >= 0:
                self._node_to_host[node_id] = host
        if self.speed_monitor is not None and snap.step_times:
            for t in snap.step_times:
                self.speed_monitor.observe_host_step_time(node_id, t)
        events = getattr(report, "events", None) or []
        if self.goodput is not None:
            if events:
                self.goodput.add_events(events)
            # Refresh the goodput gauges even for event-less
            # snapshots: with host tracing off, the accountant's
            # stream is fed by the servicer (step reports, failures)
            # and this is its recompute tick (debounced internally).
            self.goodput.account()
        return snap

    def remove_node(self, node_id: int) -> None:
        """Drop a departed node's snapshot immediately (the TTL is
        only the backstop for nodes that die without a master event)."""
        with self._lock:
            host = self._node_to_host.pop(node_id, None)
            if host is not None:
                self._hosts.pop(host, None)

    def remove_host(self, host: str) -> None:
        with self._lock:
            snap = self._hosts.pop(host, None)
            if snap is not None:
                self._node_to_host.pop(snap.node_id, None)

    def _live_locked(self) -> List[HostSnapshot]:
        now = time.monotonic()
        stale = [
            h
            for h, s in self._hosts.items()
            if now - s.received_mono > self.ttl
        ]
        for h in stale:
            snap = self._hosts.pop(h)
            self._node_to_host.pop(snap.node_id, None)
        return list(self._hosts.values())

    def live_snapshots(self) -> List[HostSnapshot]:
        with self._lock:
            return self._live_locked()

    def hosts(self) -> List[str]:
        return sorted(s.host for s in self.live_snapshots())

    # -- aggregation ------------------------------------------------------

    @staticmethod
    def _host_scalar(snap: HostSnapshot, series: str) -> Optional[float]:
        def hist(name):
            md = snap.registry.get(name)
            if not md or md.get("type") != "histogram":
                return None
            total = sum(row[2] for row in md.get("series", []))
            count = sum(row[3] for row in md.get("series", []))
            return total, count

        if series == "step_time_s":
            if snap.step_times:
                return sum(snap.step_times) / len(snap.step_times)
            h = hist("dlrover_train_step_seconds")
            if h and h[1] > 0:
                return h[0] / h[1]
            return None
        if series == "tokens_per_s":
            v = snap.resource.get("tokens_per_s")
            return float(v) if v is not None else None
        if series == "mfu":
            # Shipped via the trainer's step-metrics file -> the
            # agent snapshot's resource dict (monitor.build_snapshot).
            v = snap.resource.get("mfu")
            return float(v) if v is not None else None
        if series == "data_wait_s_total":
            h = hist("dlrover_train_data_wait_seconds")
            return h[0] if h else None
        if series == "host_syncs_total":
            md = snap.registry.get("dlrover_train_host_syncs_total")
            if not md or md.get("type") != "counter":
                return None
            return float(
                sum(row[1] for row in md.get("series", []))
            )
        return None

    def aggregates(self) -> Dict[str, Dict[str, float]]:
        """``{series: {stat: value}}`` over live hosts; a series with
        no reporting host is omitted."""
        snaps = self.live_snapshots()
        out: Dict[str, Dict[str, float]] = {}
        for series in KEY_SERIES:
            values = [
                v
                for v in (
                    self._host_scalar(s, series) for s in snaps
                )
                if v is not None
            ]
            if not values:
                continue
            out[series] = {
                "sum": sum(values),
                "min": min(values),
                "max": max(values),
                "p50": _percentile(values, 50.0),
                "p90": _percentile(values, 90.0),
            }
        return out

    # -- exposition -------------------------------------------------------

    def _series_line(
        self,
        name: str,
        key: List[str],
        labelnames: List[str],
        value: float,
        host: str,
        suffix: str = "",
        extra: str = "",
    ) -> str:
        pairs = [
            f'{ln}="{_escape_label_value(lv)}"'
            for ln, lv in zip(labelnames, key)
        ]
        pairs.append(f'host="{_escape_label_value(host)}"')
        if extra:
            pairs.append(extra)
        return (
            f"{name}{suffix}{{{','.join(pairs)}}} "
            f"{_format_value(value)}"
        )

    def _metric_lines(
        self, name: str, md: dict, host: str
    ) -> List[str]:
        labelnames = list(md.get("labelnames", []))
        mtype = md.get("type", "gauge")
        lines: List[str] = []
        if mtype in ("counter", "gauge"):
            for key, value in md.get("series", []):
                lines.append(
                    self._series_line(
                        name, list(key), labelnames, float(value), host
                    )
                )
            return lines
        if mtype == "histogram":
            bounds = [float(b) for b in md.get("buckets", [])]
            bounds.append(float("inf"))
            for key, counts, total, count in md.get("series", []):
                for bound, c in zip(bounds, counts):
                    lines.append(
                        self._series_line(
                            name, list(key), labelnames, float(c),
                            host, suffix="_bucket",
                            extra=f'le="{_format_value(bound)}"',
                        )
                    )
                lines.append(
                    self._series_line(
                        name, list(key), labelnames, float(total),
                        host, suffix="_sum",
                    )
                )
                lines.append(
                    self._series_line(
                        name, list(key), labelnames, float(count),
                        host, suffix="_count",
                    )
                )
        return lines

    def collect(self) -> List[str]:
        """Registry collector: host-labeled series + fleet aggregates.
        Runs inside ``registry.render()`` for every scrape."""
        snaps = self.live_snapshots()
        lines: List[str] = []
        # TYPE headers only for names the master registry does not
        # already expose (those already got their header above us).
        known = set(self.registry.names())
        typed: set = set()
        for snap in sorted(snaps, key=lambda s: s.host):
            for name in sorted(snap.registry):
                md = snap.registry[name]
                if name not in known and name not in typed:
                    help_ = str(md.get("help", "") or "")
                    if help_:
                        lines.append(f"# HELP {name} {help_}")
                    lines.append(
                        f"# TYPE {name} {md.get('type', 'gauge')}"
                    )
                    typed.add(name)
                lines.extend(self._metric_lines(name, md, snap.host))
        lines.append(
            "# TYPE dlrover_fleet_hosts gauge"
        )
        lines.append(f"dlrover_fleet_hosts {len(snaps)}")
        aggs = self.aggregates()
        if aggs:
            lines.append("# TYPE dlrover_fleet_series gauge")
            for series in sorted(aggs):
                for stat in STATS:
                    lines.append(
                        f'dlrover_fleet_series{{series="{series}",'
                        f'stat="{stat}"}} '
                        f"{_format_value(aggs[series][stat])}"
                    )
        return lines
