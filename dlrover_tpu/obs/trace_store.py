"""Master-side distributed-trace assembly: bounded per-trace span
timelines, queryable over the control plane.

Every plane that runs *in* the master process (serving router,
remediation engine, rendezvous) feeds completed spans here directly;
spans emitted on other hosts arrive through the existing snapshot
event channel (``FleetAggregator.ingest`` forwards tracer events that
carry a ``trace_id``). The store is the serving counterpart of the
request ledger: ring retention (``max_traces`` newest traces, each
capped at ``max_spans_per_trace`` spans) keeps master RAM bounded
regardless of traffic volume — an evicted trace's timeline simply
becomes unknown to late queries.

A *span* is one dict: ``{name, span_id, parent_span_id, start_ts,
dur_s, tags}``. A *trace timeline* is the spans of one ``trace_id``
sorted by start time, plus the derived subject index (request ids,
``node:<id>``) the ``TraceQueryRequest`` RPC filters on. Assembly is
tolerant by design — orphan spans (parent evicted or never reported)
still render at the root, because a debugging surface must degrade to
"partial timeline", never to "no timeline".
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from dlrover_tpu.obs import metrics as _metrics

_SPANS_TOTAL = _metrics.counter(
    "dlrover_trace_spans_total",
    "Spans ingested by the master's trace store, by source plane "
    "(serve / remediation / rdzv / snapshot / other)",
    ("plane",),
)
_TRACES_GAUGE = _metrics.gauge(
    "dlrover_trace_store_traces",
    "Traces currently retained in the master's bounded trace store",
)

# Default retention: like the router's request ledger, sized so a
# master never grows RAM with traffic volume. Env-tunable
# (DLROVER_TPU_TRACE_MAX_TRACES / _MAX_SPANS_PER_TRACE) for
# high-traffic masters that want deeper history.
MAX_TRACES = 512
MAX_SPANS_PER_TRACE = 256
MAX_TRACES_ENV = "DLROVER_TPU_TRACE_MAX_TRACES"
MAX_SPANS_ENV = "DLROVER_TPU_TRACE_MAX_SPANS_PER_TRACE"


def _env_int(name: str, default: int) -> int:
    raw = os.getenv(name, "")
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    return default


def _plane_of(name: str) -> str:
    head = name.split(".", 1)[0]
    return (
        head
        if head in ("serve", "remediation", "rdzv", "pool", "stall")
        else "other"
    )


def _safe_tag(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class _Trace:
    __slots__ = ("spans", "subjects", "first_ts", "last_ts", "dropped")

    def __init__(self):
        self.spans: List[dict] = []
        self.subjects: set = set()
        self.first_ts = float("inf")
        self.last_ts = 0.0
        self.dropped = 0


class TraceStore:
    def __init__(
        self,
        max_traces: Optional[int] = None,
        max_spans_per_trace: Optional[int] = None,
        clock: Callable[[], float] = time.time,
    ):
        if max_traces is None:
            max_traces = _env_int(MAX_TRACES_ENV, MAX_TRACES)
        if max_spans_per_trace is None:
            max_spans_per_trace = _env_int(
                MAX_SPANS_ENV, MAX_SPANS_PER_TRACE
            )
        self.max_traces = max(int(max_traces), 1)
        self.max_spans_per_trace = max(int(max_spans_per_trace), 1)
        self.clock = clock
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, _Trace]" = OrderedDict()

    # -- ingest -----------------------------------------------------------

    def add_span(
        self,
        trace_id: str,
        name: str,
        start_ts: float,
        dur_s: float = 0.0,
        span_id: str = "",
        parent_span_id: str = "",
        **tags,
    ) -> bool:
        """Record one completed span. Returns False when the trace is
        at its span cap (the drop is counted on the trace)."""
        if not trace_id or not name:
            return False
        span = {
            "name": str(name),
            "span_id": str(span_id),
            "parent_span_id": str(parent_span_id),
            "start_ts": float(start_ts),
            "dur_s": max(float(dur_s), 0.0),
            "tags": {str(k): _safe_tag(v) for k, v in tags.items()},
        }
        subjects = set()
        rid = tags.get("request_id")
        if rid:
            subjects.add(str(rid))
        for key in ("node_id", "replica_id"):
            nid = tags.get(key)
            if nid is not None and nid != -1:
                subjects.add(f"node:{nid}")
        subj = tags.get("subject")
        if subj:
            subjects.add(str(subj))
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                tr = self._traces[trace_id] = _Trace()
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            else:
                self._traces.move_to_end(trace_id)
            if len(tr.spans) >= self.max_spans_per_trace:
                tr.dropped += 1
                return False
            tr.spans.append(span)
            tr.subjects.update(subjects)
            tr.first_ts = min(tr.first_ts, span["start_ts"])
            tr.last_ts = max(
                tr.last_ts, span["start_ts"] + span["dur_s"]
            )
            n_traces = len(self._traces)
        _SPANS_TOTAL.inc(plane=_plane_of(name))
        _TRACES_GAUGE.set(n_traces)
        return True

    def add_event(self, event: dict) -> bool:
        """Absorb one tracer-style event dict (the snapshot channel's
        payload shape). Events with ``dur_s`` are spans; without, they
        become zero-duration point spans. Events with no ``trace_id``
        are not trace material and are ignored."""
        if not isinstance(event, dict):
            return False
        trace_id = event.get("trace_id")
        if not trace_id:
            return False
        reserved = (
            "name", "ts", "mono", "dur_s", "trace_id", "span_id",
            "parent_span_id", "pid", "role", "rank", "parent",
        )
        tags = {
            k: v for k, v in event.items() if k not in reserved
        }
        return self.add_span(
            str(trace_id),
            str(event.get("name", "")),
            float(event.get("ts", 0.0) or self.clock()),
            dur_s=float(event.get("dur_s", 0.0) or 0.0),
            span_id=str(event.get("span_id", "") or ""),
            parent_span_id=str(event.get("parent_span_id", "") or ""),
            **tags,
        )

    def add_events(self, events) -> int:
        n = 0
        for e in events or ():
            if self.add_event(e):
                n += 1
        return n

    # -- query ------------------------------------------------------------

    def query(
        self,
        trace_id: str = "",
        subject: str = "",
        limit: int = 0,
    ) -> List[dict]:
        """Assembled timelines, newest-trace-last. ``trace_id`` wins
        when given; else ``subject`` filters by membership (a request
        id, or ``node:<id>``); else every retained trace. ``limit``
        > 0 keeps only the newest N — applied BEFORE assembly, and
        the (potentially large) span copies are built OUTSIDE the
        store lock, so one big read never stalls the router's or
        remediation engine's span writers."""
        with self._lock:
            if trace_id:
                tr = self._traces.get(trace_id)
                items = [(trace_id, tr)] if tr is not None else []
            else:
                items = [
                    (tid, tr)
                    for tid, tr in self._traces.items()
                    if not subject or subject in tr.subjects
                ]
            if limit and limit > 0:
                items = items[-limit:]
            # Snapshot references only; span dicts are never mutated
            # after add_span, so copying them is safe lock-free.
            snap = [
                (
                    tid, list(tr.spans), sorted(tr.subjects),
                    tr.first_ts if tr.spans else 0.0,
                    tr.last_ts, tr.dropped,
                )
                for tid, tr in items
            ]
        return [
            {
                "trace_id": tid,
                "start_ts": first,
                "end_ts": last,
                "subjects": subjects,
                "spans": sorted(
                    (dict(s) for s in spans),
                    key=lambda s: (s["start_ts"], s["name"]),
                ),
                "dropped_spans": dropped,
            }
            for tid, spans, subjects, first, last, dropped in snap
        ]

    def get(self, trace_id: str) -> Optional[dict]:
        out = self.query(trace_id=trace_id)
        return out[0] if out else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


def span_tree(timeline: dict) -> List[dict]:
    """Flatten one timeline into render order: depth-first by parent
    links, siblings by start time; each entry gains a ``depth``.
    Orphans (parent unknown/evicted) root at depth 0 — a partial
    trace still renders."""
    spans = timeline.get("spans", [])
    by_id: Dict[str, dict] = {
        s["span_id"]: s for s in spans if s.get("span_id")
    }
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        parent = s.get("parent_span_id", "")
        if parent and parent in by_id and by_id[parent] is not s:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    out: List[dict] = []
    seen: set = set()

    def walk(span: dict, depth: int) -> None:
        key = id(span)
        if key in seen:
            return
        seen.add(key)
        entry = dict(span)
        entry["depth"] = depth
        out.append(entry)
        for child in sorted(
            children.get(span.get("span_id", ""), ()),
            key=lambda s: (s["start_ts"], s["name"]),
        ):
            walk(child, depth + 1)

    for root in sorted(
        roots, key=lambda s: (s["start_ts"], s["name"])
    ):
        walk(root, 0)
    return out


def render_trace(timeline: dict) -> str:
    """Human rendering of one assembled trace — the body of
    ``obs_report --trace``."""
    lines = [
        f"trace {timeline.get('trace_id', '?')}: "
        f"{len(timeline.get('spans', []))} span(s), "
        f"subjects {', '.join(timeline.get('subjects', [])) or '-'}"
    ]
    start = timeline.get("start_ts", 0.0)
    for s in span_tree(timeline):
        tags = s.get("tags", {})
        tag_str = " ".join(
            f"{k}={tags[k]}" for k in sorted(tags)
            if tags[k] not in (None, "")
        )
        lines.append(
            "  " + "  " * s["depth"]
            + f"{s['name']}  +{s['start_ts'] - start:.3f}s"
            + (f"  {s['dur_s'] * 1e3:.1f}ms" if s["dur_s"] else "")
            + (f"  [{tag_str}]" if tag_str else "")
        )
    if timeline.get("dropped_spans"):
        lines.append(
            f"  ({timeline['dropped_spans']} span(s) dropped at the "
            "per-trace cap)"
        )
    return "\n".join(lines)
