"""Pool capacity accounting: per-tenant chip-second interval ledger.

The pool (PR 14) schedules multi-tenant gangs but, until this plane,
could not answer the capacity-planning question the brain needs
(ROADMAP item 5): *what did tenant A's slices produce per chip-second,
and how much of the pool burned idle, preempting, or recovering?*

:class:`CapacityLedger` records every slice's state timeline as
timestamped intervals::

    idle | allocated{tenant,job} | preempting | draining | restoring

fed by hooks in :mod:`dlrover_tpu.pool.slice_pool` (allocate/release)
and :mod:`dlrover_tpu.pool.scheduler` (preemption park, resume
placement, cancel drain). Accounting is *settle-based*: a slice's
open interval accrues ``chips x elapsed`` into its ``(tenant, state)``
cell exactly when it closes, so at any instant the closed cells plus
the open accruals partition ``total_chips x elapsed`` exactly — the
same partition discipline as the step-phase profiler, asserted by the
acceptance drill.

Joining the ledger with each pool job's ``GoodputAccountant`` ratio
(:meth:`CapacityLedger.observe_goodput`, fed by the pool master's
watch tick) yields per-tenant **productive** chip-seconds and
goodput-per-chip. Closed intervals and tenant rollups persist to the
brain datastore (``capacity_intervals`` / ``tenant_goodput`` tables)
so the future capacity brain warm-starts from history; per-job series
are purged from the :class:`TimeSeriesStore` when a job retires
(:meth:`CapacityLedger.retire_job`), the same way departed hosts are
purged, so long-lived pool masters never accumulate dead-tenant
series toward the store's series cap.

Exported metrics (see tests/test_obs.py's hygiene audit)::

    dlrover_pool_chip_seconds_total{tenant,state}   counter
    dlrover_tenant_goodput_per_chip{tenant}         gauge
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.log import get_logger

logger = get_logger("obs.capacity")

STATE_IDLE = "idle"
STATE_ALLOCATED = "allocated"
STATE_PREEMPTING = "preempting"
STATE_DRAINING = "draining"
STATE_RESTORING = "restoring"
STATES = (
    STATE_IDLE,
    STATE_ALLOCATED,
    STATE_PREEMPTING,
    STATE_DRAINING,
    STATE_RESTORING,
)

# States in which a tenant *holds* chips without producing: the
# overhead the brain subtracts when it scores goodput-per-chip.
OVERHEAD_STATES = (STATE_PREEMPTING, STATE_DRAINING, STATE_RESTORING)

# The tenant label of idle capacity. A real dash-tenant cannot exist:
# pool tenants come from PoolJobSpec which defaults "default".
IDLE_TENANT = "-"

# Closed intervals kept in memory for snapshots/renderers; the brain
# table is the durable history.
INTERVAL_RETENTION = 512

_CHIP_SECONDS = obs.counter(
    "dlrover_pool_chip_seconds_total",
    "Chip-seconds accrued by pool capacity per tenant and slice "
    "state (idle capacity carries tenant '-')",
    ("tenant", "state"),
)
_GOODPUT_PER_CHIP = obs.gauge(
    "dlrover_tenant_goodput_per_chip",
    "Chips-weighted goodput ratio across a tenant's placed pool "
    "jobs (most recent observation)",
    ("tenant",),
)


@dataclasses.dataclass(frozen=True)
class SliceInterval:
    """One closed segment of one slice's state timeline."""

    slice_id: int
    state: str
    tenant: str
    job_id: str
    start_ts: float
    end_ts: float
    chips: int

    @property
    def chip_seconds(self) -> float:
        return max(self.end_ts - self.start_ts, 0.0) * self.chips

    def to_dict(self) -> dict:
        return {
            "slice_id": self.slice_id,
            "state": self.state,
            "tenant": self.tenant,
            "job_id": self.job_id,
            "start_ts": round(self.start_ts, 3),
            "end_ts": round(self.end_ts, 3),
            "chips": self.chips,
            "chip_seconds": round(self.chip_seconds, 3),
        }


class _JobAccount:
    """Per-job goodput accrual state (ledger-internal)."""

    __slots__ = ("tenant", "slices", "chips", "ratio", "mark",
                 "productive")

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.slices: List[int] = []
        self.chips = 0
        self.ratio = 0.0
        # Wall stamp productive accrual is settled up to; None while
        # the job holds no allocated-state chips (preempted, parked,
        # restoring) so overhead intervals never count as productive.
        self.mark: Optional[float] = None
        self.productive = 0.0


class CapacityLedger:
    """Thread-safe interval ledger over a fixed slice inventory.

    ``specs`` is the pool's inventory (:class:`SliceSpec` list — only
    ``slice_id`` and ``chips`` are read, so duck-typed fakes work).
    ``timeseries``/``brain`` are optional sinks: goodput observations
    land in the store (series ``tenant.goodput{tenant,job}``), closed
    intervals and tenant rollups in the brain datastore — both
    best-effort by contract. ``clock`` is injectable so drills replay
    backdated timelines hermetically.
    """

    def __init__(
        self,
        specs: Sequence,
        timeseries=None,
        brain=None,
        job_name: str = "pool",
        clock: Callable[[], float] = time.time,
        retention: int = INTERVAL_RETENTION,
    ):
        self._chips: Dict[int, int] = {
            s.slice_id: int(s.chips) for s in specs
        }
        self.total_chips = sum(self._chips.values())
        self.timeseries = timeseries
        self.brain = brain
        self.job_name = job_name
        self.clock = clock
        self._lock = threading.Lock()
        now = self.clock()
        self.start_ts = now
        # slice_id -> [state, tenant, job_id, since] (the open
        # interval; every slice is born idle at ledger start).
        self._open: Dict[int, List] = {
            sid: [STATE_IDLE, IDLE_TENANT, "", now]
            for sid in self._chips
        }
        # (tenant, state) -> closed chip-seconds.
        self._totals: Dict[Tuple[str, str], float] = {}
        self._intervals: deque = deque(maxlen=retention)
        self._jobs: Dict[str, _JobAccount] = {}
        # Productive chip-seconds of retired jobs, folded per tenant
        # so tenant history survives job retirement.
        self._retired_productive: Dict[str, float] = {}
        self._retired_held: Dict[str, float] = {}

    # -- state transitions (pool/scheduler hooks) ---------------------------

    def on_allocate(
        self,
        job_id: str,
        tenant: str,
        slice_ids: Sequence[int],
        ts: Optional[float] = None,
    ) -> None:
        """SlicePool.allocate hook: the gang's slices enter
        ``allocated{tenant,job}``. Idempotent per slice (a re-fired
        hook with the same owner is a no-op transition)."""
        ts = self._stamp(ts)
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                job = self._jobs[job_id] = _JobAccount(tenant)
            job.tenant = tenant
            job.slices = list(slice_ids)
            job.chips = sum(
                self._chips.get(sid, 0) for sid in slice_ids
            )
            self._transition_locked(
                slice_ids, STATE_ALLOCATED, tenant, job_id, ts
            )
            # Accrual starts now; ratio stays at its last known value
            # (0.0 for a fresh job — time before the first goodput
            # report conservatively counts as non-productive).
            job.mark = ts

    def on_release(
        self,
        job_id: str,
        slice_ids: Sequence[int],
        ts: Optional[float] = None,
    ) -> None:
        """SlicePool.release hook: the job's slices return to idle.
        The job account survives (a preempted job resumes later);
        :meth:`retire_job` is the terminal path."""
        ts = self._stamp(ts)
        with self._lock:
            job = self._jobs.get(job_id)
            if job is not None:
                self._settle_productive_locked(job, ts)
                job.mark = None
                job.slices = []
                job.chips = 0
            self._transition_locked(
                slice_ids, STATE_IDLE, IDLE_TENANT, "", ts
            )

    def mark_preempting(
        self, job_id: str, ts: Optional[float] = None
    ) -> None:
        """Preemption engine hook: the victim's slices stop producing
        while its park (checkpoint + stop) is in flight."""
        self._mark_state(job_id, STATE_PREEMPTING, ts)

    def mark_draining(
        self, job_id: str, ts: Optional[float] = None
    ) -> None:
        """Cancel hook: slices drain between the cancel decision and
        the release back to idle."""
        self._mark_state(job_id, STATE_DRAINING, ts)

    def mark_restoring(
        self, job_id: str, ts: Optional[float] = None
    ) -> None:
        """Resume-placement hook: a preempted job's new gang restores
        from checkpoint — held but not yet productive."""
        self._mark_state(job_id, STATE_RESTORING, ts)

    def _mark_state(
        self, job_id: str, state: str, ts: Optional[float]
    ) -> None:
        ts = self._stamp(ts)
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or not job.slices:
                return
            self._settle_productive_locked(job, ts)
            job.mark = None
            self._transition_locked(
                job.slices, state, job.tenant, job_id, ts
            )

    def job_ready(
        self, job_id: str, ts: Optional[float] = None
    ) -> None:
        """Workers registered after a resume placement: flip the
        job's ``restoring`` slices back to ``allocated`` and restart
        productive accrual. Idempotent — fresh placements (already
        allocated) and unknown jobs are no-ops."""
        ts = self._stamp(ts)
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or not job.slices:
                return
            open_state = self._open.get(job.slices[0])
            if open_state is None or open_state[0] != STATE_RESTORING:
                return
            self._transition_locked(
                job.slices, STATE_ALLOCATED, job.tenant, job_id, ts
            )
            job.mark = ts

    def retire_job(
        self,
        job_id: str,
        retire_tenant: bool = False,
        ts: Optional[float] = None,
    ) -> None:
        """Terminal path (complete/cancel): fold the job's productive
        history into its tenant and purge its per-job time series —
        and, when the scheduler says this was the tenant's last live
        job, the tenant-labeled series too (the PR-8 departed-host
        purge, applied to tenants)."""
        ts = self._stamp(ts)
        with self._lock:
            job = self._jobs.pop(job_id, None)
            if job is None:
                return
            self._settle_productive_locked(job, ts)
            tenant = job.tenant
            self._retired_productive[tenant] = (
                self._retired_productive.get(tenant, 0.0)
                + job.productive
            )
        store = self.timeseries
        if store is not None:
            try:
                store.drop_label("job", job_id)
                if retire_tenant:
                    store.drop_label("tenant", tenant)
            except Exception:  # noqa: BLE001 — purge is best-effort
                logger.warning(
                    "series purge for job %s failed", job_id,
                    exc_info=True,
                )
        if retire_tenant:
            # The gauge must not report the dead tenant's last ratio
            # forever (same contract as the slice-pool tenant gauge).
            _GOODPUT_PER_CHIP.set(0.0, tenant=tenant)

    # -- goodput join -------------------------------------------------------

    def observe_goodput(
        self,
        job_id: str,
        ratio: float,
        ts: Optional[float] = None,
    ) -> None:
        """One goodput observation for a placed job (the pool
        master's watch tick feeds each embedded JobMaster's
        ``GoodputAccountant`` ratio through here). Accrues
        ``chips x elapsed x ratio`` productive chip-seconds since the
        previous observation, refreshes the tenant gauge, and ships a
        ``tenant_goodput`` rollup to the brain."""
        ts = self._stamp(ts)
        try:
            ratio = max(0.0, min(1.0, float(ratio)))
        except (TypeError, ValueError):
            return
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return
            self._settle_productive_locked(job, ts)
            job.ratio = ratio
            tenant = job.tenant
            gauge_ratio = self._tenant_ratio_locked(tenant)
            rollup = self._tenant_rollup_locked(tenant, ts)
        _GOODPUT_PER_CHIP.set(gauge_ratio, tenant=tenant)
        store = self.timeseries
        if store is not None:
            # Two series: the per-job stream (purged when the job
            # retires) and the tenant-level stream the SLO budget
            # engine queries (series match on the EXACT label set).
            store.record(
                "tenant.goodput", ratio, ts=ts,
                tenant=tenant, job=job_id,
            )
            store.record(
                "tenant.goodput", ratio, ts=ts, tenant=tenant
            )
        self._persist_tenant_goodput(tenant, rollup, ts)

    def _settle_productive_locked(
        self, job: _JobAccount, ts: float
    ) -> None:
        """Accrue productive chip-seconds up to ``ts`` at the job's
        last known ratio, then advance the mark."""
        if job.mark is None:
            return
        dt = ts - job.mark
        if dt > 0:
            job.productive += dt * job.chips * job.ratio
        job.mark = ts

    # -- interval mechanics -------------------------------------------------

    def _stamp(self, ts: Optional[float]) -> float:
        return float(ts) if ts is not None else self.clock()

    def _transition_locked(
        self,
        slice_ids: Sequence[int],
        state: str,
        tenant: str,
        job_id: str,
        ts: float,
    ) -> None:
        closed: List[SliceInterval] = []
        for sid in slice_ids:
            open_rec = self._open.get(sid)
            if open_rec is None:
                continue  # not our inventory — ignore, never raise
            old_state, old_tenant, old_job, since = open_rec
            if (old_state, old_tenant, old_job) == (
                state, tenant, job_id
            ):
                continue  # no-op transition keeps the open interval
            end = max(ts, since)  # clamp clock skew, never negative
            chips = self._chips.get(sid, 0)
            dur = end - since
            cell = (old_tenant, old_state)
            self._totals[cell] = (
                self._totals.get(cell, 0.0) + dur * chips
            )
            if dur * chips > 0:
                _CHIP_SECONDS.inc(
                    dur * chips, tenant=old_tenant, state=old_state
                )
            interval = SliceInterval(
                slice_id=sid,
                state=old_state,
                tenant=old_tenant,
                job_id=old_job,
                start_ts=since,
                end_ts=end,
                chips=chips,
            )
            if dur > 0:
                self._intervals.append(interval)
                closed.append(interval)
            self._open[sid] = [state, tenant, job_id, end]
        for interval in closed:
            self._persist_interval(interval)

    # -- rollups ------------------------------------------------------------

    def _held_locked(self, tenant: str, ts: float) -> float:
        """Chip-seconds ``tenant`` has held in ANY state so far:
        closed cells plus open accruals — no settling, so calling
        this never fragments intervals."""
        held = sum(
            cs
            for (t, _), cs in self._totals.items()
            if t == tenant
        )
        for sid, (state, t, _job, since) in self._open.items():
            if t == tenant:
                held += max(ts - since, 0.0) * self._chips.get(sid, 0)
        return held

    def _productive_locked(self, tenant: str, ts: float) -> float:
        prod = self._retired_productive.get(tenant, 0.0)
        for job in self._jobs.values():
            if job.tenant != tenant:
                continue
            prod += job.productive
            if job.mark is not None and ts > job.mark:
                prod += (ts - job.mark) * job.chips * job.ratio
        return prod

    def _tenant_ratio_locked(self, tenant: str) -> float:
        """Chips-weighted current goodput ratio across the tenant's
        placed jobs (0.0 when it holds nothing)."""
        chips = 0
        weighted = 0.0
        for job in self._jobs.values():
            if job.tenant == tenant and job.chips > 0:
                chips += job.chips
                weighted += job.chips * job.ratio
        return weighted / chips if chips else 0.0

    def _tenant_rollup_locked(self, tenant: str, ts: float) -> dict:
        held = self._held_locked(tenant, ts)
        productive = self._productive_locked(tenant, ts)
        chips = sum(
            j.chips for j in self._jobs.values()
            if j.tenant == tenant
        )
        return {
            "chips": chips,
            "held_chip_seconds": held,
            "productive_chip_seconds": productive,
            "goodput_per_chip": (
                productive / held if held > 0 else 0.0
            ),
        }

    # -- brain persistence (best-effort by contract) ------------------------

    def _persist_interval(self, interval: SliceInterval) -> None:
        persist = getattr(
            self.brain, "persist_capacity_interval", None
        )
        if persist is None:
            return
        try:
            persist(
                job_name=self.job_name,
                slice_id=interval.slice_id,
                state=interval.state,
                tenant=interval.tenant,
                job_id=interval.job_id,
                start_ts=interval.start_ts,
                end_ts=interval.end_ts,
                chip_seconds=interval.chip_seconds,
            )
        except Exception:  # noqa: BLE001 — a broken datastore must
            # not take the accounting plane down
            logger.warning(
                "capacity interval persistence failed", exc_info=True
            )

    def _persist_tenant_goodput(
        self, tenant: str, rollup: dict, ts: float
    ) -> None:
        persist = getattr(self.brain, "persist_tenant_goodput", None)
        if persist is None:
            return
        try:
            persist(
                job_name=self.job_name,
                tenant=tenant,
                chips=rollup["chips"],
                held_chip_seconds=rollup["held_chip_seconds"],
                productive_chip_seconds=rollup[
                    "productive_chip_seconds"
                ],
                goodput_per_chip=rollup["goodput_per_chip"],
                timestamp=ts,
            )
        except Exception:  # noqa: BLE001
            logger.warning(
                "tenant goodput persistence failed", exc_info=True
            )

    # -- read surface -------------------------------------------------------

    def recent_intervals(self, limit: int = 50) -> List[dict]:
        with self._lock:
            items = list(self._intervals)
        return [iv.to_dict() for iv in items[-limit:]]

    def snapshot(self, ts: Optional[float] = None) -> dict:
        """The capacity accounting rollup ``obs_report --capacity``
        renders. Cells include open-interval accrual up to ``ts``, so
        the per-{tenant,state} chip-seconds always partition
        ``total_chips x elapsed`` exactly."""
        ts = self._stamp(ts)
        with self._lock:
            elapsed = max(ts - self.start_ts, 0.0)
            cells: Dict[Tuple[str, str], float] = dict(self._totals)
            for sid, (state, tenant, _job, since) in (
                self._open.items()
            ):
                cell = (tenant, state)
                cells[cell] = cells.get(cell, 0.0) + (
                    max(ts - since, 0.0) * self._chips.get(sid, 0)
                )
            by_state: Dict[str, float] = {}
            by_tenant: Dict[str, Dict[str, float]] = {}
            for (tenant, state), cs in cells.items():
                by_state[state] = by_state.get(state, 0.0) + cs
                by_tenant.setdefault(tenant, {})[state] = cs
            tenants = {}
            names = (
                {t for t, _ in cells if t != IDLE_TENANT}
                | {j.tenant for j in self._jobs.values()}
                | set(self._retired_productive)
            )
            for tenant in sorted(names):
                rollup = self._tenant_rollup_locked(tenant, ts)
                states = by_tenant.get(tenant, {})
                rollup["states"] = {
                    s: round(states.get(s, 0.0), 3) for s in STATES
                    if states.get(s)
                }
                rollup["overhead_chip_seconds"] = sum(
                    states.get(s, 0.0) for s in OVERHEAD_STATES
                )
                rollup["ratio_now"] = self._tenant_ratio_locked(
                    tenant
                )
                rollup["jobs"] = sorted(
                    jid for jid, j in self._jobs.items()
                    if j.tenant == tenant
                )
                tenants[tenant] = rollup
            accounted = sum(cells.values())
            capacity = self.total_chips * elapsed
            busy = capacity - by_state.get(STATE_IDLE, 0.0)
        return {
            "ts": ts,
            "start_ts": self.start_ts,
            "elapsed_s": elapsed,
            "pool_slices": len(self._chips),
            "total_chips": self.total_chips,
            "chip_seconds": {
                "capacity": capacity,
                "accounted": accounted,
                "by_state": {
                    s: round(cs, 3) for s, cs in by_state.items()
                },
            },
            # |accounted - capacity| should be float noise only; a
            # material gap means a transition hook was missed.
            "partition_ok": (
                abs(accounted - capacity)
                <= 1e-6 * max(capacity, 1.0)
            ),
            "utilization": busy / capacity if capacity > 0 else 0.0,
            "tenants": tenants,
        }


def render_capacity(payload: dict) -> str:
    """Human rendering of a capacity snapshot (plus the SLO budget
    block the pool master attaches) — the ``obs_report --capacity``
    body."""
    lines = []
    elapsed = float(payload.get("elapsed_s", 0.0))
    util = float(payload.get("utilization", 0.0))
    lines.append(
        f"pool capacity: {payload.get('pool_slices', 0)} slice(s) / "
        f"{payload.get('total_chips', 0)} chip(s), "
        f"elapsed {elapsed:.0f}s, utilization {util * 100:.0f}%"
    )
    cs = payload.get("chip_seconds", {})
    by_state = cs.get("by_state", {})
    if by_state:
        lines.append(
            "chip-seconds by state: "
            + "  ".join(
                f"{s} {by_state[s]:.1f}"
                for s in STATES
                if s in by_state
            )
        )
    if not payload.get("partition_ok", True):
        lines.append(
            "WARNING: accounted chip-seconds "
            f"{cs.get('accounted', 0.0):.1f} != capacity "
            f"{cs.get('capacity', 0.0):.1f} — missed transition hook?"
        )
    tenants = payload.get("tenants", {})
    if tenants:
        lines.append(
            f"{'tenant':<12} {'chips':>5} {'held-cs':>10} "
            f"{'prod-cs':>10} {'goodput/chip':>12} {'overhead-cs':>11}"
        )
        for tenant in sorted(tenants):
            t = tenants[tenant]
            lines.append(
                f"{tenant:<12} {t.get('chips', 0):>5} "
                f"{t.get('held_chip_seconds', 0.0):>10.1f} "
                f"{t.get('productive_chip_seconds', 0.0):>10.1f} "
                f"{t.get('goodput_per_chip', 0.0):>12.3f} "
                f"{t.get('overhead_chip_seconds', 0.0):>11.1f}"
            )
            if t.get("jobs"):
                lines.append(
                    f"{'':<12} jobs: {', '.join(t['jobs'])}"
                )
    else:
        lines.append("no tenants have held capacity yet")
    slo = payload.get("slo") or {}
    budgets = slo.get("budgets", [])
    if budgets:
        lines.append("slo budgets:")
        for b in budgets:
            alert = ""
            if b.get("burning"):
                alert = (
                    f"  BURNING [{b.get('severity', 'warn')}]"
                    f" fast {b.get('burn', {}).get('fast', 0.0):.1f}x"
                    f" slow {b.get('burn', {}).get('slow', 0.0):.1f}x"
                )
            lines.append(
                f"  {b.get('tenant', '?')}/{b.get('slo', '?')}: "
                f"budget remaining "
                f"{100.0 * float(b.get('budget_remaining', 1.0)):.0f}%"
                f" (objective {b.get('direction', 'min')} "
                f"{b.get('objective', 0.0)} on {b.get('series', '?')})"
                + alert
            )
    return "\n".join(lines)
