"""Postmortem assembly: fold a forensics dir into one failure report.

A crashed or hung run leaves three kinds of artifacts in its
forensics dir (obs/flight_recorder.py):

* ``bundle_*.json`` — per-process black-box bundles (ring contents,
  all-thread Python stacks, notes, env/process info);
* ``stacks_*.txt`` — faulthandler text dumps (fatal signals and the
  agent's SIGUSR1 while-hung snapshots);
* optionally ``*.jsonl`` — tracer event exports, when the run traced
  to a file inside the same dir.

:func:`render_postmortem` merges them into a "last N seconds before
failure" narrative: the failure instant, the recovery-timeline and
goodput attribution over the trailing window, then each bundle's
per-thread stacks and last log lines, then the final faulthandler
dump of each stacks file. Pure functions over files — hermetically
covered by ``tools/obs_report.py --selftest`` and
tests/test_forensics.py.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import List, Optional, Tuple

from dlrover_tpu.obs.goodput import attribute_goodput, render_goodput
from dlrover_tpu.obs.timeline import (
    load_events,
    reconstruct_recovery_timeline,
    render_timeline,
)

# Events that mark "the failure" (latest wins), in the order the
# master/agent emit them around a death or hang.
FAILURE_EVENT_NAMES = (
    "node.fail",
    "node.gone",
    "node.heartbeat_timeout",
    "agent.hang_detected",
    # The stall correlator's coordinated-capture moment: for a hang
    # that never crashed, the incident IS the failure instant.
    "stall.incident",
)

_STACKS_TAIL_CAP = 16384
_MAX_RENDER_FRAMES = 12
_MAX_RENDER_LOGS = 8


def load_bundles(dir_: str) -> List[dict]:
    """Parse every ``bundle_*.json`` (unparseable files are skipped),
    oldest first by bundle timestamp."""
    bundles = []
    for path in sorted(glob.glob(os.path.join(dir_, "bundle_*.json"))):
        try:
            with open(path) as f:
                bundle = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(bundle, dict):
            bundle["_path"] = path
            bundles.append(bundle)
    bundles.sort(key=lambda b: float(b.get("ts", 0.0)))
    return bundles


def last_fault_dump(text: str) -> str:
    """The terminal faulthandler content of a stacks file.

    A fatal crash writes one ``Fatal Python error:`` header followed
    by per-thread sections — return from the LAST such header (the
    thread markers inside it belong to it). Without a Fatal header
    (SIGUSR1 while-hung snapshots have only thread sections), return
    from the first thread marker, i.e. everything after the install
    header comment — consecutive snapshots are indistinguishable
    without timestamps and all of them are forensically relevant."""
    fatals = [
        m.start()
        for m in re.finditer(
            r"^Fatal Python error:", text, re.MULTILINE
        )
    ]
    if fatals:
        return text[fatals[-1]:].strip()
    threads = [
        m.start()
        for m in re.finditer(
            r"^(Current thread|Thread) 0x", text, re.MULTILINE
        )
    ]
    if not threads:
        return ""
    return text[threads[0]:].strip()


def load_stack_dumps(dir_: str) -> List[dict]:
    """``stacks_*.txt`` files with their last dump pre-extracted."""
    dumps = []
    for path in sorted(glob.glob(os.path.join(dir_, "stacks_*.txt"))):
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(size - _STACKS_TAIL_CAP, 0))
                text = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        m = re.search(r"stacks_(\d+)\.txt$", path)
        dumps.append(
            {
                "path": path,
                "pid": int(m.group(1)) if m else -1,
                "text": text,
                "last_dump": last_fault_dump(text),
            }
        )
    return dumps


def collect_events(dir_: str, bundles: List[dict]) -> List[dict]:
    """Union of bundle event rings and any ``*.jsonl`` traces in the
    dir, deduped on (name, ts) and time-ordered."""
    events: List[dict] = []
    for bundle in bundles:
        events.extend(
            e for e in bundle.get("events", []) if isinstance(e, dict)
        )
    for path in sorted(glob.glob(os.path.join(dir_, "*.jsonl"))):
        try:
            events.extend(load_events(path))
        except OSError:
            continue
    seen = set()
    unique = []
    for e in events:
        if "ts" not in e or "name" not in e:
            continue
        key = (e["name"], e["ts"], e.get("pid"))
        if key in seen:
            continue
        seen.add(key)
        unique.append(e)
    unique.sort(key=lambda e: e["ts"])
    return unique


def failure_instant(
    events: List[dict], bundles: List[dict]
) -> Tuple[Optional[float], str]:
    """(ts, source) of the failure: the latest failure-class event,
    else the latest bundle, else the latest event."""
    marks = [
        e for e in events if e.get("name") in FAILURE_EVENT_NAMES
    ]
    if marks:
        last = max(marks, key=lambda e: e["ts"])
        return float(last["ts"]), str(last["name"])
    if bundles:
        last_b = max(bundles, key=lambda b: float(b.get("ts", 0.0)))
        return (
            float(last_b.get("ts", 0.0)),
            f"bundle:{last_b.get('kind', '?')}",
        )
    if events:
        return float(events[-1]["ts"]), "last_event"
    return None, ""


def _render_bundle(bundle: dict) -> List[str]:
    lines = [
        f"bundle {os.path.basename(bundle.get('_path', '?'))} "
        f"[{bundle.get('kind', '?')}] role={bundle.get('role', '?')}"
        f"/r{bundle.get('rank', '?')} pid={bundle.get('pid', '?')} "
        f"ts={float(bundle.get('ts', 0.0)):.3f}"
    ]
    reason = str(bundle.get("reason", "") or "")
    if reason:
        lines.append(f"  reason: {reason[:300]}")
    notes = bundle.get("notes") or {}
    if notes:
        rendered = ", ".join(
            f"{k}={v}" for k, v in sorted(notes.items())
        )
        lines.append(f"  notes: {rendered[:300]}")
    proc = bundle.get("proc") or {}
    if proc:
        lines.append(
            f"  proc: python {proc.get('python', '?')}, "
            f"jax={proc.get('jax_platform', '?')}"
        )
    tb = str(bundle.get("traceback", "") or "")
    if tb:
        lines.append("  traceback:")
        for tb_line in tb.strip().splitlines():
            lines.append(f"    {tb_line}")
    trainer_stacks = str(bundle.get("trainer_stacks", "") or "")
    if trainer_stacks:
        lines.append("  trainer stacks (agent SIGUSR1 snapshot):")
        for ts_line in trainer_stacks.strip().splitlines():
            lines.append(f"    {ts_line}")
    for stack in bundle.get("stacks", []):
        flag = " (current)" if stack.get("current") else ""
        daemon = " daemon" if stack.get("daemon") else ""
        lines.append(
            f"  thread {stack.get('thread', '?')}{daemon}{flag}:"
        )
        frames = stack.get("frames", [])
        # Innermost frames carry the verdict: render the tail.
        for frame in frames[-_MAX_RENDER_FRAMES:]:
            lines.append(f"    {frame}")
    logs = bundle.get("logs", [])
    if logs:
        lines.append("  last logs:")
        for rec in logs[-_MAX_RENDER_LOGS:]:
            lines.append(
                f"    {rec.get('level', '?'):<8}"
                f" {str(rec.get('msg', ''))[:160]}"
            )
    return lines


def render_postmortem(dir_: str, window: float = 60.0) -> str:
    """The merged report; raises nothing, returns a message when the
    dir holds no forensics artifacts."""
    bundles = load_bundles(dir_)
    stack_dumps = load_stack_dumps(dir_)
    if not bundles and not stack_dumps:
        return f"no forensics artifacts (bundle_*.json / stacks_*.txt) in {dir_}"
    events = collect_events(dir_, bundles)
    t_fail, source = failure_instant(events, bundles)
    kinds: dict = {}
    for b in bundles:
        kinds[b.get("kind", "?")] = kinds.get(b.get("kind", "?"), 0) + 1
    kind_s = ", ".join(f"{k} x{n}" for k, n in sorted(kinds.items()))
    lines = [
        f"postmortem: {dir_}",
        f"  {len(bundles)} bundle(s)"
        + (f" ({kind_s})" if kind_s else "")
        + f", {len(stack_dumps)} stack dump(s), {len(events)} event(s)",
    ]
    windowed = events
    if t_fail is not None:
        lines.append(
            f"  failure instant: {t_fail:.3f} (from {source})"
        )
        windowed = [
            e
            for e in events
            if t_fail - window <= e["ts"] <= t_fail + window
        ]
        lines.append(
            f"\nlast {window:.0f}s before failure "
            f"({len(windowed)} events):"
        )
        for e in windowed[-15:]:
            extras = {
                k: v
                for k, v in e.items()
                if k
                not in ("name", "ts", "mono", "pid", "role", "rank")
            }
            extra_s = (
                " " + json.dumps(extras, default=str)
                if extras
                else ""
            )
            lines.append(
                f"  {e['ts'] - t_fail:+8.3f}s {e['name']}{extra_s}"
            )
    stall_marks = [
        e
        for e in windowed
        if e.get("name") in ("stall.incident", "stall.resolved")
    ]
    if stall_marks:
        lines.append("")
        lines.append("stall incidents in window:")
        for e in stall_marks:
            if e["name"] == "stall.incident":
                who = (
                    f"culprit {e['culprit']}"
                    if e.get("culprit")
                    else "no localized culprit"
                )
                lines.append(
                    f"  {e.get('incident', '?')} opened at "
                    f"{float(e['ts']):.3f}: {e.get('kind', '?')}, "
                    f"{who}, {e.get('hosts', '?')} host(s) parked "
                    f"(trace id = incident id; obs_report --trace "
                    f"{e.get('incident', '?')})"
                )
            else:
                lines.append(
                    f"  {e.get('incident', '?')} resolved at "
                    f"{float(e['ts']):.3f} after "
                    f"{float(e.get('open_s', 0.0)):.0f}s"
                )
    if windowed:
        tl = reconstruct_recovery_timeline(windowed)
        if tl is not None:
            lines.append("")
            lines.append(render_timeline(tl))
        gp = attribute_goodput(windowed)
        if gp is not None:
            lines.append("")
            lines.append(render_goodput(gp))
    for bundle in bundles:
        lines.append("")
        lines.extend(_render_bundle(bundle))
    for dump in stack_dumps:
        lines.append("")
        lines.append(
            f"stack dump {os.path.basename(dump['path'])} "
            f"(pid {dump['pid']}):"
        )
        body = dump["last_dump"] or dump["text"].strip()
        if not body:
            lines.append("  (empty)")
            continue
        for text_line in body.splitlines():
            lines.append(f"  {text_line}")
    return "\n".join(lines)
