"""Cross-host stall localization over the fleet's progress beacons.

When one worker wedges inside a collective, every peer blocks in the
same ``psum`` and — to every *per-host* probe built so far — all N
hosts look identically stuck. The missing signal is relative
progress: the N−1 healthy hosts are parked at the *entry* of step
K's collective (their beacons stamped dispatch-of-step-K just before
blocking), while the wedged host h never got there — its last stamp
sits at an earlier phase or an earlier step. This module is the
master-side correlator that turns the fleet's shipped beacon stamps
(:mod:`dlrover_tpu.obs.beacon`, ridden in on every
``MetricsSnapshotReport``) into exactly that comparison.

Decision table, evaluated on the HealthMonitor tick (a host is
*stalled* once its effective beacon age exceeds ``stall_after_s``
for ``stall_ticks`` consecutive ticks; a beacon that advances resets
its streak, so a flapping beacon never convicts):

==============================  ======================================
fleet state                     verdict
==============================  ======================================
no host stalled                 none (open incident resolves)
some but not all stalled        none (a true collective stall parks
                                everyone within one step; partial
                                staleness is transient/restart noise)
all stalled, one host strictly  ``collective_stall`` CRITICAL on that
behind every peer               host — the localized culprit; feeds
                                the remediation ladder's
                                cordon-replace rung
all stalled at the same spot    ``fleet_stall`` CRITICAL, job subject
(or several tied behind)        (data/master problem — nobody is
                                convicted); if a *silent* node (no
                                heartbeat) explains it, that node is
                                recorded as the attributed suspect and
                                the ``heartbeat_gap`` verdict upgrade
                                carries DIAGNOSE
==============================  ======================================

On the first stalled tick that opens an incident the correlator also:

* mints a hang-incident trace in the TraceStore — a ``stall.incident``
  root span with one ``stall.progress`` child per host (step / phase /
  microbatch / age tags) and one ``stall.capture`` child per queued
  capture — queryable via ``obs_report --trace <incident-id>``;
* queues the **coordinated capture**: DIAGNOSE + PROFILE pushed to
  *every* host's heartbeat FIFO inside one loop (dedupe keys
  ``stall:<incident>:<action>:<node>`` make replays idempotent), so
  the resulting forensics bundles are a simultaneous fleet snapshot
  of who waits on whom.

The incident (plus the rolling per-host progress table) is served
over ``StallQueryRequest`` / ``obs_report --stall``; the rc contract
there is 1 while an incident is open, 0 after resolution.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.constants import EventAction
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.obs.beacon import BEACON_PHASES, progress_key
from dlrover_tpu.obs.health import (
    SEVERITY_CRITICAL,
    HealthVerdict,
)

logger = get_logger("obs.stall")

STALL_ENV_PREFIX = "DLROVER_TPU_STALL_"

DEFAULTS: Dict[str, float] = {
    # Effective beacon age (agent-observed staleness + snapshot age at
    # the master) before a host counts as stalled. Must sit above any
    # sane step time AND above one ResourceMonitor cadence.
    "stall_after_s": 120.0,
    # Consecutive stalled ticks before any verdict: one tick of
    # staleness is snapshot jitter, not a stall.
    "stall_ticks": 2.0,
    # Minimum seconds between coordinated capture rounds (a flapping
    # incident must not hammer every host's FIFO).
    "capture_cooldown_s": 300.0,
    # Closed incidents retained for --stall / --postmortem.
    "incident_history": 16.0,
}

_INCIDENTS_TOTAL = obs.counter(
    "dlrover_stall_incidents_total",
    "Stall incidents opened by the master's correlator, by kind "
    "(laggard = localized single-host culprit, fleet_wide = "
    "everyone parked at the same spot)",
    ("kind",),
)
_OPEN_INCIDENT = obs.gauge(
    "dlrover_stall_open_incident",
    "1 while a stall incident is open, else 0 (the obs_report "
    "--stall rc contract reads the same state)",
)
_BEACON_HOSTS = obs.gauge(
    "dlrover_stall_beacon_hosts",
    "Hosts currently shipping a progress beacon in their fleet "
    "snapshots",
)
_CAPTURES_TOTAL = obs.counter(
    "dlrover_stall_captures_total",
    "Coordinated-capture actions the correlator queued to host "
    "heartbeat FIFOs, by action (diagnose / profile)",
    ("action",),
)

KIND_LAGGARD = "laggard"
KIND_FLEET_WIDE = "fleet_wide"


def _phase_name(idx: int) -> str:
    if 0 <= idx < len(BEACON_PHASES):
        return BEACON_PHASES[idx]
    return "init"


class StallCorrelator:
    """Aligns per-host progress vectors; localizes collective stalls.

    ``fleet`` is anything with ``live_snapshots()`` returning objects
    with host/node_id/wall_ts/beacon attributes (the
    FleetAggregator); ``capture`` is the coordinated-capture sink
    ``(node_id, action, dedupe_key) -> bool`` (the servicer's
    ``push_action``); ``traces`` a TraceStore; ``diagnostics`` an
    optional ``node_id -> [DiagnosticsReport-like]`` probe used to
    cross-link capture bundle paths into the served snapshot;
    ``silent_probe`` an optional ``() -> {node_id: heartbeat_age}``
    over nodes already past their critical heartbeat fraction
    (:meth:`~dlrover_tpu.obs.health.HealthMonitor.attach_stall`
    wires it). The clock is injectable and everything is evaluated
    on the caller's tick — hermetically testable with a fake clock.
    """

    def __init__(
        self,
        fleet=None,
        traces=None,
        capture: Optional[Callable[..., bool]] = None,
        diagnostics: Optional[Callable[[int], list]] = None,
        silent_probe: Optional[Callable[[], Dict[int, float]]] = None,
        clock: Callable[[], float] = time.time,
        config: Optional[Dict[str, float]] = None,
    ):
        self.fleet = fleet
        self.traces = traces
        self.capture = capture
        self.diagnostics = diagnostics
        self.silent_probe = silent_probe
        self.clock = clock
        self._config = dict(config or {})
        self._lock = threading.Lock()
        # host -> last progress key / consecutive stalled ticks /
        # last rendered row (the --stall progress table).
        self._progress: Dict[str, Tuple[int, int, int]] = {}
        self._stalled_ticks: Dict[str, int] = {}
        self._rows: Dict[str, dict] = {}
        self._incident: Optional[dict] = None
        self._incidents: deque = deque(
            maxlen=max(int(self._cfg("incident_history")), 1)
        )
        self._seq = 0
        self._last_capture_ts = -float("inf")
        # Node ids a fleet-wide stall is attributed to because they
        # went heartbeat-silent — read by _detect_heartbeat_gap's
        # DIAGNOSE upgrade.
        self.silent_suspects: set = set()
        _OPEN_INCIDENT.set(0)

    def _cfg(self, knob: str) -> float:
        if knob in self._config:
            return float(self._config[knob])
        env = os.getenv(STALL_ENV_PREFIX + knob.upper(), "")
        if env:
            try:
                return float(env)
            except ValueError:
                logger.warning(
                    "bad %s%s=%r; using default %s",
                    STALL_ENV_PREFIX, knob.upper(), env,
                    DEFAULTS[knob],
                )
        return DEFAULTS[knob]

    # -- per-tick evaluation ----------------------------------------------

    def _gather(self, now: float) -> Dict[str, dict]:
        """The current beacon table: one row per live beacon-shipping
        host, with the master-side effective staleness (agent-observed
        age + how long ago the snapshot itself was taken)."""
        rows: Dict[str, dict] = {}
        if self.fleet is None:
            return rows
        for snap in self.fleet.live_snapshots():
            stamp = getattr(snap, "beacon", None) or {}
            if not stamp:
                continue
            age = stamp.get("age_s")
            age = (
                float(age)
                if isinstance(age, (int, float)) and age >= 0
                else 0.0
            )
            key = progress_key(stamp)
            rows[snap.host] = {
                "host": snap.host,
                "node_id": int(getattr(snap, "node_id", -1)),
                "step": key[0],
                "phase": _phase_name(key[1]),
                "phase_idx": key[1],
                "microbatch": key[2],
                "age_s": round(
                    age + max(now - float(snap.wall_ts or now), 0.0), 3
                ),
                "key": key,
            }
        return rows

    def evaluate(self) -> List[HealthVerdict]:
        """One correlator tick — runs as a HealthMonitor detector, so
        its verdicts get the engine's full lifecycle (transition
        history, action cooldowns, resolution, persistence)."""
        now = self.clock()
        rows = self._gather(now)
        stall_after = self._cfg("stall_after_s")
        need_ticks = max(int(self._cfg("stall_ticks")), 1)
        with self._lock:
            for host in list(self._stalled_ticks):
                if host not in rows:
                    # Departed host: its streak must not outlive its
                    # series (fleet drop_label purges history; this
                    # purges the conviction state).
                    self._stalled_ticks.pop(host, None)
                    self._progress.pop(host, None)
            for host, row in rows.items():
                prev = self._progress.get(host)
                if prev is not None and row["key"] > prev:
                    # Progress since last tick: a flapping beacon
                    # resets its streak and never convicts.
                    self._stalled_ticks[host] = 0
                elif row["age_s"] >= stall_after:
                    self._stalled_ticks[host] = (
                        self._stalled_ticks.get(host, 0) + 1
                    )
                else:
                    self._stalled_ticks[host] = 0
                self._progress[host] = row["key"]
                row["stalled_ticks"] = self._stalled_ticks[host]
                row["stalled"] = (
                    self._stalled_ticks[host] >= need_ticks
                )
            self._rows = {
                h: {k: v for k, v in r.items() if k != "key"}
                for h, r in rows.items()
            }
        _BEACON_HOSTS.set(len(rows))
        stalled = {h: r for h, r in rows.items() if r["stalled"]}
        if not stalled or len(stalled) < len(rows):
            # Nobody (or not everybody) is parked: a true collective
            # stall blocks the whole fleet within one step.
            self._resolve_incident(now)
            self.silent_suspects = set()
            return []
        return self._verdicts_for_stall(now, rows)

    def _verdicts_for_stall(
        self, now: float, rows: Dict[str, dict]
    ) -> List[HealthVerdict]:
        min_key = min(r["key"] for r in rows.values())
        behind = [h for h, r in rows.items() if r["key"] == min_key]
        localized = len(rows) >= 2 and len(behind) == 1
        suspects: Dict[int, float] = {}
        if not localized and self.silent_probe is not None:
            try:
                suspects = dict(self.silent_probe() or {})
            except Exception:  # noqa: BLE001 — a probe bug must not
                # kill the evaluation tick
                logger.warning("silent probe failed", exc_info=True)
        self.silent_suspects = set(suspects)
        if localized:
            culprit = behind[0]
            kind, culprit_row = KIND_LAGGARD, rows[culprit]
        else:
            culprit, culprit_row, kind = "", None, KIND_FLEET_WIDE
        incident = self._ensure_incident(
            now, kind, culprit, rows, suspects
        )
        peers = [r for h, r in rows.items() if h != culprit]
        peer_step = max((r["step"] for r in peers), default=0)
        if localized:
            ages = culprit_row["age_s"]
            message = (
                f"host {culprit} wedged at step {culprit_row['step']} "
                f"{culprit_row['phase']}"
                + (
                    f" microbatch {culprit_row['microbatch']}"
                    if culprit_row["microbatch"] >= 0
                    else ""
                )
                + f" (beacon stale {ages:.0f}s) while {len(peers)} "
                f"peer(s) sit parked at step {peer_step} collective "
                f"entry — incident {incident['id']}"
            )
            verdict = HealthVerdict(
                detector="collective_stall",
                severity=SEVERITY_CRITICAL,
                message=message,
                node_id=culprit_row["node_id"],
                host=culprit,
                suggested_action=EventAction.DIAGNOSE.value,
                evidence_series="host.beacon_step",
                evidence=[(now, float(culprit_row["step"]))],
                metrics={
                    "hosts": float(len(rows)),
                    "culprit_step": float(culprit_row["step"]),
                    "culprit_phase_idx": float(
                        culprit_row["phase_idx"]
                    ),
                    "peer_step": float(peer_step),
                    "beacon_age_s": float(culprit_row["age_s"]),
                },
                timestamp=now,
            )
        else:
            min_age = min(r["age_s"] for r in rows.values())
            message = (
                f"fleet-wide stall: all {len(rows)} beacon host(s) "
                f"parked at step {min_key[0]} "
                f"{_phase_name(min_key[1])} for {min_age:.0f}s — "
                f"incident {incident['id']}"
            )
            if suspects:
                silent = ", ".join(
                    f"node {n} ({a:.0f}s silent)"
                    for n, a in sorted(suspects.items())
                )
                message += f"; attributed to silent {silent}"
            verdict = HealthVerdict(
                detector="fleet_stall",
                severity=SEVERITY_CRITICAL,
                message=message,
                node_id=-1,
                host="",
                suggested_action="",
                evidence_series="host.beacon_age_s",
                evidence=[
                    (now, float(min(r["age_s"] for r in rows.values())))
                ],
                metrics={
                    "hosts": float(len(rows)),
                    "fleet_step": float(min_key[0]),
                    "silent_nodes": float(len(suspects)),
                },
                timestamp=now,
            )
        return [verdict]

    # -- incident lifecycle -----------------------------------------------

    def _ensure_incident(
        self,
        now: float,
        kind: str,
        culprit: str,
        rows: Dict[str, dict],
        suspects: Dict[int, float],
    ) -> dict:
        with self._lock:
            inc = self._incident
            if inc is not None:
                # Re-localization mid-incident (e.g. the fleet split
                # only became visible a tick later) updates the
                # subject; the incident identity stays.
                if kind == KIND_LAGGARD and inc["kind"] != kind:
                    inc["kind"] = kind
                    inc["culprit"] = culprit
                    inc["culprit_node"] = rows[culprit]["node_id"]
                inc["silent_nodes"] = sorted(suspects)
                return inc
            self._seq += 1
            inc_id = f"stall-{int(now)}-{self._seq}"
            inc = {
                "id": inc_id,
                "trace_id": inc_id,
                "kind": kind,
                "culprit": culprit,
                "culprit_node": (
                    rows[culprit]["node_id"] if culprit else -1
                ),
                "opened_ts": now,
                "resolved_ts": 0.0,
                "silent_nodes": sorted(suspects),
                "hosts": {
                    h: {
                        k: r[k]
                        for k in (
                            "node_id", "step", "phase",
                            "microbatch", "age_s",
                        )
                    }
                    for h, r in rows.items()
                },
                "captures": {},
            }
            self._incident = inc
        _INCIDENTS_TOTAL.inc(kind=kind)
        _OPEN_INCIDENT.set(1)
        obs.event(
            "stall.incident",
            incident=inc_id,
            kind=kind,
            culprit=culprit,
            hosts=len(rows),
        )
        logger.warning(
            "stall incident %s opened (%s%s): %d host(s) parked",
            inc_id, kind, f", culprit {culprit}" if culprit else "",
            len(rows),
        )
        self._mint_trace(inc, rows, now)
        self._coordinated_capture(inc, rows, now)
        return inc

    def _mint_trace(
        self, inc: dict, rows: Dict[str, dict], now: float
    ) -> None:
        if self.traces is None:
            return
        tid = inc["trace_id"]
        root = f"{tid}:root"
        self.traces.add_span(
            tid,
            "stall.incident",
            start_ts=now,
            span_id=root,
            kind=inc["kind"],
            culprit=inc["culprit"],
            hosts=len(rows),
            subject="stall",
        )
        for host, r in sorted(rows.items()):
            self.traces.add_span(
                tid,
                "stall.progress",
                start_ts=now,
                span_id=f"{tid}:h:{host}",
                parent_span_id=root,
                host=host,
                node_id=r["node_id"],
                step=r["step"],
                phase=r["phase"],
                microbatch=r["microbatch"],
                age_s=r["age_s"],
                culprit=(host == inc["culprit"]),
            )

    def _coordinated_capture(
        self, inc: dict, rows: Dict[str, dict], now: float
    ) -> None:
        """DIAGNOSE + PROFILE to every host's heartbeat FIFO in one
        loop — the fleet snapshot is only useful if the bundles are
        (near-)simultaneous, so all pushes happen inside one tick.
        Dedupe keys make a replay (warm restart, RPC retry) a no-op."""
        if self.capture is None:
            return
        if now - self._last_capture_ts < self._cfg("capture_cooldown_s"):
            return
        self._last_capture_ts = now
        actions = (
            EventAction.DIAGNOSE.value,
            EventAction.PROFILE.value,
        )
        for host, r in sorted(rows.items()):
            node_id = r["node_id"]
            if node_id < 0:
                continue
            queued = []
            for action in actions:
                try:
                    ok = self.capture(
                        node_id,
                        action,
                        dedupe_key=(
                            f"stall:{inc['id']}:{action}:{node_id}"
                        ),
                    )
                except Exception:  # noqa: BLE001 — a push failure on
                    # one host must not abort the fleet round
                    logger.warning(
                        "capture push %s -> node %d failed",
                        action, node_id, exc_info=True,
                    )
                    ok = False
                if ok:
                    queued.append(action)
                    _CAPTURES_TOTAL.inc(action=action)
            with self._lock:
                inc["captures"][host] = {
                    "node_id": node_id,
                    "queued": queued,
                }
            if self.traces is not None:
                self.traces.add_span(
                    inc["trace_id"],
                    "stall.capture",
                    start_ts=now,
                    span_id=f"{inc['trace_id']}:cap:{host}",
                    parent_span_id=f"{inc['trace_id']}:root",
                    host=host,
                    node_id=node_id,
                    actions=",".join(queued) or "none",
                )

    def _resolve_incident(self, now: float) -> None:
        with self._lock:
            inc, self._incident = self._incident, None
            if inc is None:
                return
            inc["resolved_ts"] = now
            self._incidents.append(inc)
        _OPEN_INCIDENT.set(0)
        obs.event(
            "stall.resolved",
            incident=inc["id"],
            kind=inc["kind"],
            culprit=inc["culprit"],
            open_s=round(now - inc["opened_ts"], 3),
        )
        logger.info(
            "stall incident %s resolved after %.0fs",
            inc["id"], now - inc["opened_ts"],
        )
        if self.traces is not None:
            self.traces.add_span(
                inc["trace_id"],
                "stall.resolved",
                start_ts=now,
                span_id=f"{inc['trace_id']}:resolved",
                parent_span_id=f"{inc['trace_id']}:root",
                open_s=round(now - inc["opened_ts"], 3),
            )

    # -- read surface ------------------------------------------------------

    def _bundles_for(self, inc: dict) -> Dict[str, list]:
        """Capture bundles that answered this incident, per host —
        diagnostics reports filed at/after the incident opened (small
        slack for clock skew between filing and opening)."""
        if self.diagnostics is None:
            return {}
        out: Dict[str, list] = {}
        since = inc["opened_ts"] - 5.0
        for host, cap in inc.get("captures", {}).items():
            try:
                reports = self.diagnostics(cap["node_id"]) or []
            except Exception:  # noqa: BLE001
                continue
            rows = []
            for r in reports:
                ts = float(getattr(r, "timestamp", 0.0) or 0.0)
                if ts < since:
                    continue
                rows.append(
                    {
                        "kind": str(getattr(r, "kind", "")),
                        "bundle_path": str(
                            getattr(r, "bundle_path", "")
                        ),
                        "timestamp": ts,
                    }
                )
            if rows:
                out[host] = rows
        return out

    def open_incident(self) -> Optional[dict]:
        with self._lock:
            return dict(self._incident) if self._incident else None

    def snapshot(self) -> dict:
        """The ``StallQueryResponse`` payload: rolling per-host
        progress table, open incident (bundle paths cross-linked),
        recent closed incidents, and the effective knobs."""
        with self._lock:
            hosts = {h: dict(r) for h, r in self._rows.items()}
            incident = dict(self._incident) if self._incident else {}
            history = [dict(i) for i in self._incidents]
        if incident:
            incident["bundles"] = self._bundles_for(incident)
        return {
            "now": self.clock(),
            "hosts": hosts,
            "incident": incident,
            "incidents": history,
            "config": {
                k: self._cfg(k) for k in sorted(DEFAULTS)
            },
        }


def render_stall(payload: dict) -> str:
    """Human rendering of a stall snapshot — the body of
    ``obs_report --stall``."""
    hosts = payload.get("hosts", {}) or {}
    incident = payload.get("incident", {}) or {}
    history = payload.get("incidents", []) or []
    lines = [
        f"stall localization: {len(hosts)} beacon host(s), "
        + (
            f"incident {incident.get('id', '?')} OPEN"
            if incident
            else "no open incident"
        )
    ]
    if hosts:
        lines.append(
            "  host             node  step    mb  phase            "
            "age_s  state"
        )
        for host in sorted(hosts):
            r = hosts[host]
            state = (
                "STALLED" if r.get("stalled")
                else ("ok" if not r.get("stalled_ticks") else "stale")
            )
            lines.append(
                f"  {host:<15.15s} {r.get('node_id', -1):>5} "
                f"{r.get('step', 0):>5} {r.get('microbatch', -1):>5} "
                f"{str(r.get('phase', '?')):<16.16s} "
                f"{r.get('age_s', 0.0):>6.0f}  {state}"
            )
    else:
        lines.append("  (no host is shipping a progress beacon)")

    def _inc_lines(inc: dict, head: str) -> List[str]:
        out = [
            f"{head} {inc.get('id', '?')}: {inc.get('kind', '?')}"
            + (
                f", culprit {inc['culprit']}"
                f" (node {inc.get('culprit_node', -1)})"
                if inc.get("culprit")
                else ""
            )
            + f", trace {inc.get('trace_id', '?')}"
        ]
        for host in sorted(inc.get("hosts", {})):
            r = inc["hosts"][host]
            mark = " <- culprit" if host == inc.get("culprit") else ""
            out.append(
                f"    {host}: step {r.get('step')} "
                f"{r.get('phase')} mb {r.get('microbatch')} "
                f"(age {r.get('age_s', 0.0):.0f}s){mark}"
            )
        if inc.get("silent_nodes"):
            out.append(
                "    silent node(s): "
                + ", ".join(str(n) for n in inc["silent_nodes"])
            )
        for host in sorted(inc.get("captures", {})):
            cap = inc["captures"][host]
            out.append(
                f"    capture -> {host} (node {cap.get('node_id')}): "
                f"queued {','.join(cap.get('queued', [])) or 'none'}"
            )
        for host in sorted(inc.get("bundles", {}) or {}):
            for b in inc["bundles"][host]:
                out.append(
                    f"    bundle [{b.get('kind')}] {host}: "
                    f"{b.get('bundle_path') or '(digest only)'}"
                )
        return out

    if incident:
        lines.extend(_inc_lines(incident, "  open incident"))
    for inc in reversed(history[-3:]):
        dur = max(
            inc.get("resolved_ts", 0.0) - inc.get("opened_ts", 0.0),
            0.0,
        )
        lines.extend(
            _inc_lines(
                inc, f"  resolved after {dur:.0f}s —"
            )
        )
    return "\n".join(lines)
