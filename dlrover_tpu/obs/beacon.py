"""Collective-stall progress beacon: wedge-proof progress stamps.

A host wedged inside a C-level collective cannot answer an RPC, run a
signal handler, or service a thread dump — every Python-level probe
built so far (SIGUSR1 stack capture, heartbeat, metrics file) goes
dark with it. But the file its trainer wrote *just before entering*
the collective is still there, and another process can read it. This
module is that file: a single fixed-size, mmap'd record holding the
trainer's last-crossed progress boundary — step index, microbatch
index, phase id (the :data:`~dlrover_tpu.obs.profiling.PHASES`
boundary it came from), and a monotonic timestamp — rewritten in
place on every boundary the hot loop already crosses.

Cost model: one ~200-byte memcpy into an mmap per phase boundary (a
handful per optimizer step), no syscall on the write path, no host
sync, no device interaction — the step-loop AST host-sync audits and
the transfer-guard tripwires see nothing new. The *reader* (the
co-hosted agent, ``bench.py``'s parent, ``obs_report``) opens the
file fresh each time; because CLOCK_MONOTONIC is machine-wide on
Linux, ``time.monotonic() - stamp["mono"]`` in any process on the
host is the true staleness age even when the writer is wedged.

Record schema (JSON, space-padded to :data:`RECORD_SIZE` bytes)::

    {"pid": 1234,          # writer pid (restart detection)
     "step": 17,           # optimizer step the stamp belongs to
     "microbatch": 3,      # last staged microbatch, -1 before any
     "phase": "dispatch",  # last boundary crossed (BEACON_PHASES)
     "mono": 8123.4,       # time.monotonic() at the stamp
     "ts": 1754...,        # wall clock (rendering only)
     "seq": 91}            # total stamps this writer has made

A torn read (the writer memcpy'd mid-``open``) fails JSON parsing and
is reported as "no stamp"; the next read self-heals. Readers never
block writers and vice versa.
"""

from __future__ import annotations

import json
import mmap
import os
import time
from typing import Callable, Optional, Tuple

BEACON_FILE_ENV = "DLROVER_TPU_BEACON_FILE"
BEACON_ENABLE_ENV = "DLROVER_TPU_BEACON"

# One page is overkill; 512 bytes fits the record with headroom and
# keeps the whole stamp inside a single cache-line burst.
RECORD_SIZE = 512

# Progress ordering *within* one step, for the correlator: a stamp at
# a later index has made strictly more progress through the step.
# ``init`` is the pre-first-stamp state; ``compile`` and ``dispatch``
# are the same boundary (mutually exclusive per step) but compile
# sorts first so a host stuck compiling reads as "behind" a peer that
# already dispatched.
BEACON_PHASES = (
    "init",
    "data_wait",
    "h2d_stage",
    "compile",
    "dispatch",
    "device_execute",
)


def beacon_file() -> str:
    """Where this job's trainer stamps progress. Job-scoped (two jobs
    on one host must not read each other's progress)."""
    job = os.getenv("DLROVER_TPU_JOB_NAME", "default")
    return os.getenv(
        BEACON_FILE_ENV, f"/tmp/dlrover_tpu_beacon_{job}.json"
    )


def beacon_enabled() -> bool:
    return os.getenv(BEACON_ENABLE_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def phase_index(phase: str) -> int:
    """Ordering rank of a phase name; unknown phases rank as init."""
    try:
        return BEACON_PHASES.index(phase)
    except ValueError:
        return 0


def progress_key(stamp: Optional[dict]) -> Tuple[int, int, int]:
    """Totally-ordered progress position ``(step, phase, microbatch)``
    of a stamp — the correlator compares hosts with plain tuple
    comparison. ``None`` (no beacon yet) sorts before everything."""
    if not isinstance(stamp, dict):
        return (-1, 0, -1)
    try:
        return (
            int(stamp.get("step", 0)),
            phase_index(str(stamp.get("phase", "init"))),
            int(stamp.get("microbatch", -1)),
        )
    except (TypeError, ValueError):
        return (-1, 0, -1)


def stamp_age(
    stamp: Optional[dict], now_mono: Optional[float] = None
) -> Optional[float]:
    """Seconds since the stamp was written, on the machine-wide
    monotonic clock — meaningful only on the writer's host."""
    if not isinstance(stamp, dict):
        return None
    try:
        mono = float(stamp["mono"])
    except (KeyError, TypeError, ValueError):
        return None
    now = time.monotonic() if now_mono is None else now_mono
    return max(now - mono, 0.0)


def read_beacon(path: Optional[str] = None) -> Optional[dict]:
    """The last stamp at ``path``, or None when absent/torn/invalid.
    Opens the file fresh — works on a wedged writer's beacon."""
    path = path or beacon_file()
    try:
        with open(path, "rb") as f:
            raw = f.read(RECORD_SIZE)
    except OSError:
        return None
    try:
        stamp = json.loads(raw.decode("utf-8", "replace").strip("\x00 \r\n"))
    except ValueError:
        return None
    return stamp if isinstance(stamp, dict) else None


class ProgressBeacon:
    """The writer half: owns the mmap'd record and rewrites it in
    place on every :meth:`stamp`. Construction is best-effort — a
    read-only ``/tmp`` degrades to a no-op beacon, never a trainer
    crash. Clocks are injectable for hermetic tests."""

    def __init__(
        self,
        path: Optional[str] = None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ):
        self.path = path or beacon_file()
        self._clock = clock
        self._wall = wall
        self.step = 0
        self.microbatch = -1
        self.phase = "init"
        self.seq = 0
        self._mm: Optional[mmap.mmap] = None
        self._fd: Optional[int] = None
        try:
            # The file appears atomically at its final size, so a
            # reader never sees a short file.
            tmp = f"{self.path}.tmp{os.getpid()}"
            fd = os.open(
                tmp, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644
            )
            try:
                os.ftruncate(fd, RECORD_SIZE)
                os.replace(tmp, self.path)
            except OSError:
                os.close(fd)
                raise
            self._fd = fd
            self._mm = mmap.mmap(fd, RECORD_SIZE)
        except (OSError, ValueError):
            self._close()
        else:
            self.stamp()  # the init stamp: "trainer alive, step 0"

    @property
    def active(self) -> bool:
        return self._mm is not None

    def stamp(
        self,
        step: Optional[int] = None,
        microbatch: Optional[int] = None,
        phase: Optional[str] = None,
    ) -> None:
        """Record a crossed boundary. Omitted fields keep their last
        value, so a microbatch-only stamp doesn't regress the phase."""
        if self._mm is None:
            return
        if step is not None:
            self.step = int(step)
        if microbatch is not None:
            self.microbatch = int(microbatch)
        if phase is not None:
            self.phase = str(phase)
        self.seq += 1
        data = json.dumps(
            {
                "pid": os.getpid(),
                "step": self.step,
                "microbatch": self.microbatch,
                "phase": self.phase,
                "mono": round(self._clock(), 4),
                "ts": round(self._wall(), 4),
                "seq": self.seq,
            },
            separators=(",", ":"),
        ).encode("utf-8")
        if len(data) > RECORD_SIZE:
            return
        try:
            self._mm[:RECORD_SIZE] = data.ljust(RECORD_SIZE)
        except (ValueError, OSError):
            self._close()

    def read(self) -> Optional[dict]:
        return read_beacon(self.path)

    def _close(self) -> None:
        mm, self._mm = self._mm, None
        fd, self._fd = self._fd, None
        if mm is not None:
            try:
                mm.close()
            except (OSError, ValueError):
                pass
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass

    def close(self) -> None:
        """Flush-and-release; the file (and its last stamp) remains
        for post-mortem readers."""
        if self._mm is not None:
            try:
                self._mm.flush()
            except (OSError, ValueError):
                pass
        self._close()

    def __del__(self):  # pragma: no cover - GC timing
        self._close()


def default_beacon() -> Optional[ProgressBeacon]:
    """The beacon a hot loop should run: job-scoped path, real
    clocks; None when disabled via DLROVER_TPU_BEACON=0."""
    if not beacon_enabled():
        return None
    b = ProgressBeacon()
    return b if b.active else None
