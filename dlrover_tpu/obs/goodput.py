"""Goodput/badput accounting: exhaustive wall-time attribution.

Folds an obs event stream into the job-level question "where did the
wall time go", in the framing of Meta's large-scale reliability study
and Google Cloud's ML Goodput work: every second of the accounting
window lands in EXACTLY one bucket —

    productive    steps are landing (time between trainer.step marks)
    compile       cold XLA compilation (trainer.compile_done spans)
    data_wait     the train loop blocked on input
                  (trainer.prefetch_wait)
    checkpoint    save/stage/persist/restore (ckpt.* spans)
    recovery      a failure event until the relaunched trainer's
                  first step (node.fail / node.gone /
                  node.heartbeat_timeout -> trainer.first_step_done)
    idle_unknown  wall time no signal explains (startup, rendezvous
                  waits outside a recovery, silent stalls)

Attribution is a boundary sweep over category intervals with a fixed
precedence (``recovery > checkpoint > compile > data_wait >
productive``), so overlapping signals never double-count and the
bucket sums equal the window length exactly — property-tested in
``tests/test_fleet_telemetry.py`` and asserted by
``tools/obs_report.py --selftest``.

Timestamp conventions of the sources (see tracer.py): span events
carry ``ts`` = start and ``dur_s``; plain events with a ``dur_s`` tag
(``trainer.prefetch_wait``, ``trainer.compile_done``) are emitted at
the END of the measured interval.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from dlrover_tpu.obs import metrics as _metrics

# Buckets in precedence order (highest first); productive is the
# lowest explicit signal and idle_unknown is the remainder.
CATEGORIES = (
    "recovery",
    "checkpoint",
    "compile",
    "data_wait",
    "productive",
    "idle_unknown",
)

FAILURE_EVENTS = ("node.fail", "node.gone", "node.heartbeat_timeout")
# Recovery closes at the explicit phase mark, or — when the trainer's
# marks never reach this stream (tracing off on the host) — at the
# first step landing after the failure: steps landing IS recovery.
RECOVERY_END = ("trainer.first_step_done", "trainer.step")

# Events whose ts marks the END of the measured duration.
_END_STAMPED = {"trainer.prefetch_wait", "trainer.compile_done"}


@dataclasses.dataclass
class GoodputReport:
    """Wall-time attribution over ``[t0, t1]``; ``seconds`` maps every
    category to its share and sums to ``total_s`` exactly."""

    t0: float
    t1: float
    seconds: Dict[str, float]
    steps: int

    @property
    def total_s(self) -> float:
        return self.t1 - self.t0

    @property
    def goodput_ratio(self) -> float:
        if self.total_s <= 0:
            return 0.0
        return self.seconds.get("productive", 0.0) / self.total_s

    def to_dict(self) -> dict:
        return {
            "t0": self.t0,
            "t1": self.t1,
            "total_s": round(self.total_s, 6),
            "goodput_ratio": round(self.goodput_ratio, 6),
            "steps": self.steps,
            "seconds": {
                k: round(v, 6) for k, v in self.seconds.items()
            },
        }


def _clip(
    intervals: List[Tuple[float, float]], t0: float, t1: float
) -> List[Tuple[float, float]]:
    out = []
    for a, b in intervals:
        a, b = max(a, t0), min(b, t1)
        if b > a:
            out.append((a, b))
    return out


def _merge(
    intervals: List[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    """Sort and coalesce overlapping intervals."""
    out: List[Tuple[float, float]] = []
    for a, b in sorted(intervals):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _category_intervals(
    events: List[dict], t0: float, t1: float
) -> Dict[str, List[Tuple[float, float]]]:
    """Raw (unclipped, possibly overlapping) intervals per category."""
    by_cat: Dict[str, List[Tuple[float, float]]] = {
        c: [] for c in CATEGORIES
    }
    step_ts: List[float] = []
    open_failure: Optional[float] = None
    for ev in events:
        name = ev.get("name", "")
        ts = float(ev["ts"])
        dur = float(ev.get("dur_s", 0.0) or 0.0)
        if name == "trainer.step":
            step_ts.append(ts)
        if name in FAILURE_EVENTS:
            if open_failure is None:
                open_failure = ts
        elif name in RECOVERY_END and open_failure is not None:
            by_cat["recovery"].append((open_failure, ts))
            open_failure = None
        if dur <= 0:
            continue
        if name in _END_STAMPED:
            start, end = ts - dur, ts
        else:
            start, end = ts, ts + dur
        if name == "trainer.prefetch_wait":
            by_cat["data_wait"].append((start, end))
        elif name == "trainer.compile_done":
            by_cat["compile"].append((start, end))
        elif name.startswith("ckpt."):
            by_cat["checkpoint"].append((start, end))
    if open_failure is not None:
        # Failure never recovered inside the window: badput to the end.
        by_cat["recovery"].append((open_failure, t1))
    for a, b in zip(step_ts, step_ts[1:]):
        if b > a:
            by_cat["productive"].append((a, b))
    return by_cat


def attribute_goodput(
    events: Iterable[dict],
    t0: Optional[float] = None,
    t1: Optional[float] = None,
) -> Optional[GoodputReport]:
    """Sweep ``events`` into a :class:`GoodputReport` over ``[t0, t1]``
    (defaulting to the event span). Returns None when there is nothing
    to account (no events and no explicit window).

    Exhaustive and exclusive by construction: the window is cut at
    every interval boundary and each elementary segment is assigned to
    the highest-precedence category covering it; uncovered segments
    are ``idle_unknown``.
    """
    evs = sorted(
        (e for e in events if "ts" in e and "name" in e),
        key=lambda e: float(e["ts"]),
    )
    if t0 is None:
        t0 = float(evs[0]["ts"]) if evs else None
    if t1 is None and evs:
        # Window end covers interval ENDS, not just event stamps: a
        # start-stamped span at the tail (e.g. a trailing ckpt.*)
        # extends dur_s past its ts and must not be clipped away.
        t1 = max(
            float(e["ts"])
            + (
                float(e.get("dur_s", 0.0) or 0.0)
                if e.get("name") not in _END_STAMPED
                else 0.0
            )
            for e in evs
        )
    if t0 is None or t1 is None or t1 < t0:
        return None
    by_cat = _category_intervals(evs, t0, t1)
    merged = {
        c: _merge(_clip(iv, t0, t1)) for c, iv in by_cat.items()
    }

    bounds = {t0, t1}
    for iv in merged.values():
        for a, b in iv:
            bounds.add(a)
            bounds.add(b)
    cuts = sorted(bounds)
    seconds = {c: 0.0 for c in CATEGORIES}
    # Precedence: first category in CATEGORIES covering the segment.
    # Segments ascend, so one pointer per category makes the sweep
    # linear in cuts + intervals.
    ptr = {c: 0 for c in CATEGORIES}
    for a, b in zip(cuts, cuts[1:]):
        mid = (a + b) / 2.0
        for cat in CATEGORIES[:-1]:
            iv = merged[cat]
            i = ptr[cat]
            while i < len(iv) and iv[i][1] <= mid:
                i += 1
            ptr[cat] = i
            if i < len(iv) and iv[i][0] <= mid < iv[i][1]:
                seconds[cat] += b - a
                break
        else:
            seconds["idle_unknown"] += b - a
    steps = sum(1 for e in evs if e.get("name") == "trainer.step")
    return GoodputReport(t0=t0, t1=t1, seconds=seconds, steps=steps)


def render_goodput(report: GoodputReport) -> str:
    """Human-readable breakdown (tools/obs_report.py --goodput)."""
    lines = [
        f"goodput over {report.total_s:.2f}s wall "
        f"({report.steps} steps, "
        f"goodput_ratio {100.0 * report.goodput_ratio:.1f}%):",
    ]
    total = max(report.total_s, 1e-12)
    for cat in CATEGORIES:
        sec = report.seconds.get(cat, 0.0)
        lines.append(
            f"  {cat:<13} {sec:10.2f}s  {100.0 * sec / total:5.1f}%"
        )
    return "\n".join(lines)


class GoodputAccountant:
    """Master-side accountant: accumulates the job's event stream
    (master lifecycle events + trainer spans arriving in agent
    metric snapshots) and keeps the goodput gauges current.

    The window is anchored at the first event seen (the job's observed
    start) and re-accounted on demand — cheap at snapshot cadence
    (seconds), bounded by ``max_events``.
    """

    def __init__(
        self,
        registry=None,
        max_events: int = 100_000,
        min_account_interval: float = 5.0,
        timeseries=None,
    ):
        """``timeseries`` (a TimeSeriesStore) additionally records
        every recompute's ratio and per-category seconds as history,
        so the goodput-SLO detector can judge a window instead of the
        instantaneous gauge."""
        registry = registry or _metrics.get_registry()
        self.timeseries = timeseries
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._max_events = max_events
        # Re-accounting is O(events): debounce the snapshot-cadence
        # callers so a large fleet cannot pin the master's RPC thread
        # re-sweeping the same stream (account(force=True) bypasses).
        self._min_account_interval = min_account_interval
        self._last_account_mono = -float("inf")
        self._last_report: Optional[GoodputReport] = None
        self._seconds = registry.gauge(
            "dlrover_goodput_seconds_total",
            "Wall-time attribution of job time by category "
            "(exhaustive: categories sum to the accounting window)",
            ("category",),
        )
        self._ratio = registry.gauge(
            "dlrover_goodput_ratio",
            "Fraction of the accounting window spent in productive "
            "training steps",
        )

    def add_events(self, events: Iterable[dict]) -> None:
        with self._lock:
            for ev in events:
                if isinstance(ev, dict) and "ts" in ev and "name" in ev:
                    self._events.append(ev)
            if len(self._events) > self._max_events:
                # Keep the newest; the dropped prefix ages the window
                # start forward, which is the right bias for a gauge.
                self._events = self._events[-self._max_events:]

    def account(
        self,
        t0: Optional[float] = None,
        t1: Optional[float] = None,
        force: bool = False,
    ) -> Optional[GoodputReport]:
        with self._lock:
            if (
                not force
                and t0 is None
                and t1 is None
                and time.monotonic() - self._last_account_mono
                < self._min_account_interval
            ):
                return self._last_report
            events = list(self._events)
        report = attribute_goodput(events, t0=t0, t1=t1)
        if report is not None:
            for cat in CATEGORIES:
                self._seconds.set(
                    report.seconds.get(cat, 0.0), category=cat
                )
            self._ratio.set(report.goodput_ratio)
            if self.timeseries is not None and t0 is None and t1 is None:
                # History for the health detectors: stamp at the
                # store's "now", not report.t1 — when the event
                # stream stalls, t1 freezes and frozen-stamped
                # samples would age out of the SLO detector's query
                # window during the exact episode it must see.
                ts = max(report.t1, self.timeseries.clock())
                self.timeseries.record(
                    "goodput.ratio", report.goodput_ratio, ts=ts
                )
                for cat in CATEGORIES:
                    self.timeseries.record(
                        "goodput.seconds",
                        report.seconds.get(cat, 0.0),
                        ts=ts,
                        category=cat,
                    )
        with self._lock:
            if t0 is None and t1 is None:
                self._last_account_mono = time.monotonic()
                self._last_report = report
        return report
